"""CTC loss + edit distance kernels.

Parity: reference warpctc integration (operators/warpctc_op.cc dynloading
libwarpctc — SURVEY N26) and operators/edit_distance_op. TPU-first
re-design: instead of a vendored CUDA library, CTC is the standard
log-space alpha recursion over the extended (blank-interleaved) label
sequence, vectorised over the padded batch and scanned over time — XLA
fuses it; the backward pass is jax.vjp of the forward. Edit distance is
the Levenshtein DP scanned over the hypothesis axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op
from .kernels_sequence import lod_key, seg_lengths
from .kernels_rnn import packed_to_padded, _seq_T

_NEG = -1e30


def _lod_of(ctx, slot):
    return ctx.env[lod_key(ctx.op.inputs[slot][0])]


def _bucket_of(ctx, slot, total):
    """Static padded length for THIS input's raggedness: its own per-feed
    bucket when known (so short CTC labels don't pad to the frame-length
    bucket), else the global bucket, else the packed total."""
    name = lod_key(ctx.op.inputs[slot][0])
    b = ctx.seq_buckets.get(name)
    if b is not None:
        return min(int(b), int(total))
    return _seq_T(ctx, total, ctx.env.get(name))


@register_op("warpctc")
def _warpctc(ctx, ins, attrs):
    """Inputs: Logits packed [total_t, C] (pre-softmax, lod over time),
    Label packed [total_l, 1] (lod over label length). Output: Loss
    [n_seq, 1]. attrs: blank (default 0), norm_by_times."""
    logits = ins["Logits"][0]
    labels = ins["Label"][0].reshape(-1)
    t_off = _lod_of(ctx, "Logits")
    l_off = _lod_of(ctx, "Label")
    blank = int(attrs.get("blank", 0))
    C = logits.shape[1]
    B = t_off.shape[0] - 1

    T = _bucket_of(ctx, "Logits", logits.shape[0])
    t_lens = seg_lengths(t_off)  # [B]

    lab_p, _ = packed_to_padded(labels, l_off, _bucket_of(ctx, "Label", labels.shape[0]))
    Lmax = lab_p.shape[1]
    l_lens = seg_lengths(l_off)  # [B]

    # extended sequence: blank z1 blank z2 ... blank  (S = 2L+1)
    S = 2 * Lmax + 1
    s_idx = jnp.arange(S)
    is_lab = (s_idx % 2) == 1
    lab_at = jnp.where(is_lab, lab_p[:, jnp.clip((s_idx - 1) // 2, 0, Lmax - 1)], blank)
    s_valid = s_idx[None, :] < (2 * l_lens[:, None] + 1)  # [B,S]
    # skip transition allowed where z_s is a label differing from z_{s-2}
    prev2 = jnp.concatenate(
        [jnp.full((B, 2), blank, lab_at.dtype), lab_at[:, :-2]], axis=1
    )
    can_skip = jnp.logical_and(is_lab[None, :], lab_at != prev2)

    def ctc_loss(logits_packed):
        logit_p, _ = packed_to_padded(logits_packed, t_off, T)  # [B,T,C]
        logp = jax.nn.log_softmax(logit_p.astype(jnp.float32), axis=-1)

        def emit(t):
            # log p of emitting z_s at time t: [B,S]
            return jnp.take_along_axis(logp[:, t], lab_at, axis=1)

        a0 = jnp.full((B, S), _NEG)
        a0 = a0.at[:, 0].set(logp[:, 0, blank])
        a0 = a0.at[:, 1].set(
            jnp.where(l_lens > 0, emit(0)[:, 1], _NEG)
        )
        a0 = jnp.where(s_valid, a0, _NEG)

        def shift(a, k):
            return jnp.concatenate([jnp.full((B, k), _NEG), a[:, :-k]], axis=1)

        def step(alpha, t):
            stay = alpha
            diag = shift(alpha, 1)
            skip = jnp.where(can_skip, shift(alpha, 2), _NEG)
            m = jnp.maximum(jnp.maximum(stay, diag), skip)
            safe = jnp.where(m <= _NEG, 0.0, m)
            summed = safe + jnp.log(
                jnp.exp(jnp.where(stay <= _NEG, _NEG, stay - safe))
                + jnp.exp(jnp.where(diag <= _NEG, _NEG, diag - safe))
                + jnp.exp(jnp.where(skip <= _NEG, _NEG, skip - safe))
                + 1e-45
            )
            new = summed + emit(t)
            new = jnp.where(s_valid, new, _NEG)
            alive = (t < t_lens)[:, None]
            return jnp.where(alive, new, alpha), None

        alpha, _ = lax.scan(step, a0, jnp.arange(1, T))

        bidx = jnp.arange(B)
        send = 2 * l_lens  # index of final blank
        last_blank = alpha[bidx, send]
        last_lab = jnp.where(
            l_lens > 0, alpha[bidx, jnp.maximum(send - 1, 0)], _NEG
        )
        m = jnp.maximum(last_blank, last_lab)
        safe = jnp.where(m <= _NEG, 0.0, m)
        ll = safe + jnp.log(
            jnp.exp(last_blank - safe)
            + jnp.exp(jnp.where(last_lab <= _NEG, _NEG, last_lab - safe))
            + 1e-45
        )
        loss = -ll
        if attrs.get("norm_by_times"):
            loss = loss / jnp.maximum(t_lens.astype(loss.dtype), 1.0)
        return loss

    # WarpCTCGrad = d(sum loss)/d logits (reference warpctc_op semantics:
    # the library hands back the per-frame gradient alongside the loss).
    # XLA dead-code-eliminates the vjp when the output is never fetched.
    loss, pullback = jax.vjp(ctc_loss, logits.astype(jnp.float32))
    (grad,) = pullback(jnp.ones_like(loss))
    return {"Loss": loss.reshape(B, 1).astype(logits.dtype),
            "WarpCTCGrad": grad.astype(logits.dtype)}


@register_op("edit_distance")
def _edit_distance(ctx, ins, attrs):
    """Levenshtein distance per (Hyps_i, Refs_i) sequence pair (reference
    operators/edit_distance_op.h). Output [n_seq, 1] float; attr
    `normalized` divides by the reference length."""
    hyp = ins["Hyps"][0].reshape(-1)
    ref = ins["Refs"][0].reshape(-1)
    h_off = _lod_of(ctx, "Hyps")
    r_off = _lod_of(ctx, "Refs")
    B = h_off.shape[0] - 1

    Hm = _bucket_of(ctx, "Hyps", hyp.shape[0])
    Rm = _bucket_of(ctx, "Refs", ref.shape[0])
    hyp_p, _ = packed_to_padded(hyp, h_off, Hm)  # [B,Hm]
    ref_p, _ = packed_to_padded(ref, r_off, Rm)  # [B,Rm]
    h_lens = seg_lengths(h_off)
    r_lens = seg_lengths(r_off)

    BIG = jnp.float32(1e9)
    j = jnp.arange(Rm + 1, dtype=jnp.float32)
    row0 = jnp.broadcast_to(j, (B, Rm + 1))  # distance from empty hyp

    def step(row, i):
        # row = D[i-1, :]; compute D[i, :]
        cost_sub = jnp.where(
            hyp_p[:, i - 1][:, None] == ref_p, 0.0, 1.0
        )  # [B,Rm]
        sub = row[:, :-1] + cost_sub
        dele = row[:, 1:] + 1.0  # delete hyp[i-1]
        first = row[:, :1] + 1.0  # D[i,0] = i

        def scan_col(carry, xs):
            s_j, d_j = xs
            cur = jnp.minimum(jnp.minimum(s_j, d_j), carry + 1.0)
            return cur, cur

        _, cols = lax.scan(
            scan_col,
            first[:, 0],
            (sub.T, dele.T),
        )
        new = jnp.concatenate([first, cols.T], axis=1)
        # rows beyond the hyp length keep the previous value
        alive = (i <= h_lens)[:, None]
        return jnp.where(alive, new, row), None

    row, _ = lax.scan(step, row0, jnp.arange(1, Hm + 1))
    bidx = jnp.arange(B)
    # final D[h_len, r_len] — but clamped rows froze at h_len already
    dist = row[bidx, jnp.clip(r_lens, 0, Rm)]
    seq_num = jnp.asarray([B], jnp.int64)
    if attrs.get("normalized"):
        dist = dist / jnp.maximum(r_lens.astype(dist.dtype), 1.0)
    return {"Out": dist.reshape(B, 1).astype(jnp.float32),
            "SequenceNum": seq_num}


@register_op("ctc_align")
def _ctc_align(ctx, ins, attrs):
    """CTC greedy (best-path) decode (reference ctc_align_op.cc +
    ctc_greedy_decoder nn.py): per sequence take the argmax token per
    step, collapse repeats, drop blanks. Packed-compaction output like
    sequence_erase: kept tokens move to the buffer front, traced offsets
    describe the ragged result."""
    x = ins["Input"][0]  # [total, C] probs/logits OR [total] token ids
    from .kernels_sequence import lod_key, seg_ids

    offsets = ctx.env[lod_key(ctx.op.inputs["Input"][0])]
    blank = int(attrs.get("blank", 0))
    total = x.shape[0]
    ids = x.reshape(total, -1)
    tokens = (
        jnp.argmax(ids, axis=1).astype(jnp.int32)
        if ids.shape[1] > 1 else ids[:, 0].astype(jnp.int32)
    )
    seg = seg_ids(offsets, total)
    first = jnp.concatenate(
        [jnp.ones((1,), bool),
         (tokens[1:] != tokens[:-1]) | (seg[1:] != seg[:-1])]
    )
    kept = first & (tokens != blank)
    pos = jnp.cumsum(kept.astype(jnp.int32)) - 1
    dest = jnp.where(kept, pos, total)
    out = jnp.zeros((total + 1,), jnp.int32).at[dest].set(tokens)[:total]
    n = offsets.shape[0] - 1
    kept_per_seq = jax.ops.segment_sum(
        kept.astype(jnp.int32), seg, num_segments=n
    )
    new_off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(kept_per_seq, dtype=jnp.int32)]
    )
    ctx.env[lod_key(ctx.op.outputs["Output"][0])] = new_off
    return {"Output": out.reshape(total, 1)}
