"""Detection op kernels: prior_box, box_coder, bipartite_match,
multiclass_nms.

Parity: reference operators/prior_box_op.h, box_coder_op.h,
bipartite_match_op.cc, multiclass_nms_op.cc (and the legacy gserver
PriorBox/MultiBoxLoss/DetectionOutput layers). TPU-first re-design:
everything is static-shape. NMS's data-dependent output count becomes a
fixed [N*keep_top_k, 6] buffer, valid rows first, with traced per-image
counts riding the usual LoD side-band — the same convention beam search
decode uses (kernels_control.py).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op
from .kernels_sequence import lod_key

_NEG = -1e30


@register_op("prior_box")
def _prior_box(ctx, ins, attrs):
    """Anchor generation over a feature map (prior_box_op.h)."""
    feat = ins["Input"][0]  # [N, C, H, W]
    image = ins["Image"][0]  # [N, C, Him, Wim]
    H, W = feat.shape[2], feat.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", []):
        ars.append(float(ar))
        if attrs.get("flip", False):
            ars.append(1.0 / float(ar))
    variances = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    step_w = float(attrs.get("step_w", 0.0)) or float(img_w) / W
    step_h = float(attrs.get("step_h", 0.0)) or float(img_h) / H
    offset = float(attrs.get("offset", 0.5))

    wh = []
    for ms in min_sizes:
        for ar in ars:
            wh.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
        if max_sizes:
            mx = max_sizes[min_sizes.index(ms)]
            wh.append((math.sqrt(ms * mx), math.sqrt(ms * mx)))
    P = len(wh)
    whs = jnp.asarray(wh, jnp.float32)  # [P, 2]

    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)  # [H, W]
    c = jnp.stack([cxg, cyg], axis=-1)[:, :, None, :]  # [H,W,1,2]
    half = whs[None, None, :, :] / 2.0  # [1,1,P,2]
    mins = (c - half) / jnp.asarray([img_w, img_h], jnp.float32)
    maxs = (c + half) / jnp.asarray([img_w, img_h], jnp.float32)
    boxes = jnp.concatenate([mins, maxs], axis=-1)  # [H,W,P,4]
    if attrs.get("clip", False):
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), (H, W, P, 4)
    )
    return {"Boxes": boxes, "Variances": var}


@register_op("box_coder")
def _box_coder(ctx, ins, attrs):
    """Center-size encode/decode (box_coder_op.h)."""
    prior = ins["PriorBox"][0]  # [M, 4] xyxy
    pvar = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None  # [M,4]
    target = ins["TargetBox"][0]
    code = attrs.get("code_type", "encode_center_size")

    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    if pvar is None:
        pvar = jnp.ones_like(prior)

    if code == "encode_center_size":
        # target: [M, 4] gt boxes (broadcast against priors row-wise)
        tw = target[..., 2] - target[..., 0]
        th = target[..., 3] - target[..., 1]
        tcx = target[..., 0] + tw / 2
        tcy = target[..., 1] + th / 2
        out = jnp.stack(
            [
                (tcx - pcx) / pw / pvar[:, 0],
                (tcy - pcy) / ph / pvar[:, 1],
                jnp.log(jnp.maximum(tw / pw, 1e-12)) / pvar[:, 2],
                jnp.log(jnp.maximum(th / ph, 1e-12)) / pvar[:, 3],
            ],
            axis=-1,
        )
    else:  # decode_center_size; target [N, M, 4] offsets
        dcx = target[..., 0] * pvar[:, 0] * pw + pcx
        dcy = target[..., 1] * pvar[:, 1] * ph + pcy
        dw = jnp.exp(target[..., 2] * pvar[:, 2]) * pw
        dh = jnp.exp(target[..., 3] * pvar[:, 3]) * ph
        out = jnp.stack(
            [dcx - dw / 2, dcy - dh / 2, dcx + dw / 2, dcy + dh / 2], axis=-1
        )
    return {"OutputBox": out}


@register_op("bipartite_match")
def _bipartite_match(ctx, ins, attrs):
    """Greedy bipartite matching on a [N, M] distance matrix
    (bipartite_match_op.cc BipartiteMatch): repeatedly take the global
    max, bind its row to its column."""
    dist = ins["DistMat"][0]
    # batched via LoD on rows (one instance per sequence) or a single [N,M]
    key = lod_key(ctx.op.inputs["DistMat"][0])
    if key in ctx.env:
        raise NotImplementedError(
            "ragged bipartite_match batches: feed one instance per run "
            "or a dense [N, M] matrix for now"
        )
    N, M = dist.shape
    steps = min(N, M)

    def body(carry, _):
        d, row_of_col, dist_of_col = carry
        flat = jnp.argmax(d)
        i, j = flat // M, flat % M
        best = d[i, j]
        valid = best > _NEG
        row_of_col = jnp.where(
            valid, row_of_col.at[j].set(i.astype(jnp.int32)), row_of_col
        )
        dist_of_col = jnp.where(
            valid, dist_of_col.at[j].set(best), dist_of_col
        )
        d = jnp.where(valid, d.at[i, :].set(_NEG).at[:, j].set(_NEG), d)
        return (d, row_of_col, dist_of_col), None

    init = (
        dist.astype(jnp.float32),
        jnp.full((M,), -1, jnp.int32),
        jnp.zeros((M,), jnp.float32),
    )
    (d, row_of_col, dist_of_col), _ = lax.scan(body, init, None, length=steps)
    return {
        "ColToRowMatchIndices": row_of_col.reshape(1, M),
        "ColToRowMatchDist": dist_of_col.reshape(1, M),
    }


def _iou(boxes):
    """Pairwise IoU of [M, 4] xyxy boxes -> [M, M]."""
    area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) * jnp.maximum(
        boxes[:, 3] - boxes[:, 1], 0
    )
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    inter = jnp.prod(jnp.maximum(rb - lt, 0), axis=-1)
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-12)


def _nms_class(scores, iou, nms_threshold, max_keep):
    """Greedy NMS for one class: returns kept mask. scores [M] (already
    score-threshold-masked to -inf), iou [M, M]."""
    M = scores.shape[0]

    def body(carry, _):
        remaining, kept = carry
        i = jnp.argmax(jnp.where(remaining, scores, _NEG))
        ok = jnp.logical_and(remaining[i], scores[i] > _NEG)
        kept = jnp.where(ok, kept.at[i].set(True), kept)
        suppress = iou[i] > nms_threshold
        remaining = jnp.where(
            ok, jnp.logical_and(remaining, jnp.logical_not(suppress)), remaining
        )
        remaining = remaining.at[i].set(False)
        return (remaining, kept), None

    init = (scores > _NEG, jnp.zeros((M,), bool))
    (_, kept), _ = lax.scan(body, init, None, length=min(max_keep, M))
    return kept


@register_op("multiclass_nms")
def _multiclass_nms(ctx, ins, attrs):
    """Per-class NMS + cross-class keep_top_k (multiclass_nms_op.cc).
    Output: [N*keep_top_k, 6] rows = [label, score, x1, y1, x2, y2],
    valid-first per image, per-image counts in the LoD side-band."""
    scores = ins["Scores"][0]  # [N, C, M]
    bboxes = ins["BBoxes"][0]  # [N, M, 4]
    N, C, M = scores.shape
    bg = int(attrs.get("background_label", 0))
    score_thresh = float(attrs.get("score_threshold", 0.01))
    nms_thresh = float(attrs.get("nms_threshold", 0.3))
    nms_top_k = int(attrs.get("nms_top_k", 400))
    keep_top_k = int(attrs.get("keep_top_k", 200))
    if keep_top_k < 0:
        keep_top_k = C * M

    def one_image(sc, bx):
        # reference multiclass_nms_op.cc order: per class, sort by score
        # and CAP to nms_top_k BEFORE suppression. Tiling consequence
        # (r3 verdict weak #6): the IoU matrix is [K, K] with
        # K = min(nms_top_k, M), never [M, M] — at SSD scale
        # (M=8732 priors, K=400) that is 160k elements per class
        # instead of 76M, and it lives only inside the vmapped class
        # computation.
        K = min(nms_top_k, M) if nms_top_k > 0 else M

        def one_class(c_scores):
            s = jnp.where(c_scores > score_thresh, c_scores, _NEG)
            top_s, top_i = lax.top_k(s, K)
            iou = _iou(bx[top_i])  # [K, K]
            kept = _nms_class(top_s, iou, nms_thresh, K)
            return jnp.full((M,), _NEG, s.dtype).at[top_i].set(
                jnp.where(kept, top_s, _NEG)
            )

        per_class = jax.vmap(one_class)(sc)  # [C, M]
        if 0 <= bg < C:
            per_class = per_class.at[bg].set(_NEG)
        flat = per_class.reshape(-1)  # [C*M]
        k = min(keep_top_k, C * M)
        top_s, top_i = lax.top_k(flat, k)
        cls = (top_i // M).astype(jnp.float32)
        box = bx[top_i % M]
        valid = top_s > _NEG
        rows = jnp.concatenate(
            [cls[:, None], top_s[:, None], box], axis=1
        )  # [k, 6]
        rows = jnp.where(valid[:, None], rows, -1.0)
        return rows, valid.sum().astype(jnp.int32)

    rows, counts = jax.vmap(one_image)(scores, bboxes)  # [N,k,6], [N]
    k = rows.shape[1]
    out = rows.reshape(N * k, 6)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)]
    )
    # valid rows are already sorted first per image (top_k order); expose
    # per-image counts as LoD over a *padded* buffer (rows beyond each
    # count are -1 filler at fixed stride k)
    out_name = ctx.op.outputs["Out"][0]
    ctx.env[lod_key(out_name)] = offsets
    ctx.env[out_name + "@PAD_STRIDE"] = k
    return {"Out": out}


@register_op("roi_pool")
def _roi_pool(ctx, ins, attrs):
    """ROI max pooling (reference gserver ROIPoolLayer.cpp, RoIPooling
    per Fast R-CNN): each ROI's window on the feature map is divided into
    a pooled_h x pooled_w grid of bins and each bin max-pooled.

    TPU-first: bin membership is expressed as separable H/W masks built
    from aranges (static shapes), and the pool is a masked max — no
    per-roi dynamic slicing, so one XLA program covers every ROI set.
    ROIs: [R, 4] (x1, y1, x2, y2) with a LoD side-band mapping ROIs to
    batch images (offsets [N+1]).
    """
    x = ins["X"][0]  # [N, C, H, W]
    rois = ins["ROIs"][0]  # [R, 4]
    roi_name = ctx.op.inputs["ROIs"][0]
    key = lod_key(roi_name)
    if key in ctx.env:
        offsets = ctx.env[key]
        from .kernels_sequence import seg_ids

        batch_of = seg_ids(offsets, rois.shape[0])  # [R]
    else:  # single-image default
        batch_of = jnp.zeros((rois.shape[0],), jnp.int32)
    ph = int(attrs["pooled_height"])
    pw = int(attrs["pooled_width"])
    scale = float(attrs.get("spatial_scale", 1.0))
    N, C, H, W = x.shape

    def one_roi(roi, b):
        # round to the feature-map grid like the reference (ROIPoolLayer)
        x1 = jnp.round(roi[0] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[2] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        # bin p covers [floor(p*rh/ph), ceil((p+1)*rh/ph)) + y1, clipped
        p = jnp.arange(ph)
        hstart = jnp.clip(y1 + (p * rh) // ph, 0, H)
        hend = jnp.clip(y1 + -((-(p + 1) * rh) // ph), 0, H)
        q = jnp.arange(pw)
        wstart = jnp.clip(x1 + (q * rw) // pw, 0, W)
        wend = jnp.clip(x1 + -((-(q + 1) * rw) // pw), 0, W)
        hs = jnp.arange(H)
        ws = jnp.arange(W)
        mh = (hs[None, :] >= hstart[:, None]) & (hs[None, :] < hend[:, None])
        mw = (ws[None, :] >= wstart[:, None]) & (ws[None, :] < wend[:, None])
        feat = x[b]  # [C, H, W]
        masked = jnp.where(
            mh[None, :, None, :, None] & mw[None, None, :, None, :],
            feat[:, None, None, :, :],
            _NEG,
        )  # [C, ph, pw, H, W]
        pooled = masked.max(axis=(3, 4))
        # empty bins read 0 (reference memsets the output)
        return jnp.where(pooled <= _NEG, 0.0, pooled)

    out = jax.vmap(one_roi)(rois.astype(jnp.float32), batch_of)  # [R,C,ph,pw]
    out_name = ctx.op.outputs["Out"][0]
    if key in ctx.env:
        ctx.env[lod_key(out_name)] = ctx.env[key]
    return {"Out": out}


@register_op("ssd_multibox_loss")
def _ssd_multibox_loss(ctx, ins, attrs):
    """SSD MultiBox training loss (legacy gserver MultiBoxLossLayer.cpp):
    match priors to ground-truth boxes by IoU, smooth-L1 on the encoded
    location offsets of the positives, softmax cross-entropy on class
    confidences with hard negative mining at `neg_pos_ratio`.

    TPU-first: all matching is dense masked argmax over a static
    [N, P, G] IoU tensor (G = packed ground-truth boxes across the batch,
    images separated by a mask built from the LoD side-band) — no
    per-image host loop, one XLA program for every batch composition.
    Emits a per-image cost [N, 1], each image normalised by its matched
    prior count (the reference normalises by the batch's total).
    """
    loc = ins["Loc"][0]          # [N, P, 4] predicted offsets
    conf = ins["Conf"][0]        # [N, P, C] raw logits
    gt_box = ins["GTBox"][0]     # [G, 4] corners, packed over the batch
    gt_label = ins["GTLabel"][0].reshape(-1).astype(jnp.int32)  # [G]
    priors = ins["PriorBox"][0]  # [P, 4] corners
    prior_var = ins["PriorVar"][0]  # [P, 4]
    gt_name = ctx.op.inputs["GTBox"][0]
    offsets = ctx.env[lod_key(gt_name)]  # [N+1]
    from .kernels_sequence import seg_ids

    N, P, C = conf.shape
    G = gt_box.shape[0]
    overlap_t = float(attrs.get("overlap_threshold", 0.5))
    neg_overlap = float(attrs.get("neg_overlap", 0.5))
    neg_ratio = float(attrs.get("neg_pos_ratio", 3.0))
    bg = int(attrs.get("background_id", 0))

    img_of = seg_ids(offsets, G)  # [G]

    def _area(b):
        return jnp.maximum(b[..., 2] - b[..., 0], 0.0) * jnp.maximum(
            b[..., 3] - b[..., 1], 0.0
        )

    lt = jnp.maximum(priors[:, None, :2], gt_box[None, :, :2])
    rb = jnp.minimum(priors[:, None, 2:], gt_box[None, :, 2:])
    inter = jnp.maximum(rb - lt, 0.0)
    inter = inter[..., 0] * inter[..., 1]  # [P, G]
    union = _area(priors)[:, None] + _area(gt_box)[None, :] - inter
    iou = inter / jnp.maximum(union, 1e-10)

    in_img = img_of[None, :] == jnp.arange(N)[:, None]  # [N, G]
    iou_n = jnp.where(in_img[:, None, :], iou[None, :, :], -1.0)  # [N,P,G]
    best_iou = iou_n.max(axis=2)         # [N, P]
    best_g = iou_n.argmax(axis=2)        # [N, P] global gt index

    pos = best_iou > overlap_t
    # bipartite guarantee: greedy global matching, one (gt, prior) pair
    # per round with already-claimed priors/gts masked out — each gt gets
    # a DISTINCT prior even when two gts share a best prior (reference
    # BipartiteMatch / MultiBoxLossLayer match semantics)
    def _match_round(_, state):
        claimed, bg, matched = state
        sc = jnp.where(matched[None, :], -1.0, iou)  # [P, G]
        sc = jnp.where(claimed[img_of].T, -1.0, sc)
        idx = jnp.argmax(sc)
        p_star, g_star = idx // G, idx % G
        ok = sc[p_star, g_star] > 0.0
        n_star = img_of[g_star]
        claimed = claimed.at[n_star, p_star].set(claimed[n_star, p_star] | ok)
        bg = bg.at[n_star, p_star].set(
            jnp.where(ok, g_star, bg[n_star, p_star])
        )
        matched = matched.at[g_star].set(matched[g_star] | ok)
        return claimed, bg, matched

    claimed0 = jnp.zeros((N, P), bool)
    matched0 = jnp.zeros((G,), bool)
    claimed, best_g, _ = jax.lax.fori_loop(
        0, G, _match_round, (claimed0, best_g, matched0)
    )
    has_gt = (offsets[1:] - offsets[:-1]) > 0
    pos = (pos | claimed) & has_gt[:, None]

    # ---- location loss (smooth L1 on encoded offsets, positives only)
    def _cwh(b):
        w = b[..., 2] - b[..., 0]
        h = b[..., 3] - b[..., 1]
        return (b[..., 0] + b[..., 2]) / 2, (b[..., 1] + b[..., 3]) / 2, w, h

    pcx, pcy, pw, ph = _cwh(priors)
    g = gt_box[best_g]  # [N, P, 4]
    gcx, gcy, gw, gh = _cwh(g)
    var = prior_var[None]  # [1, P, 4]
    tx = (gcx - pcx) / jnp.maximum(pw, 1e-10) / var[..., 0]
    ty = (gcy - pcy) / jnp.maximum(ph, 1e-10) / var[..., 1]
    tw = jnp.log(jnp.maximum(gw / jnp.maximum(pw, 1e-10), 1e-10)) / var[..., 2]
    th = jnp.log(jnp.maximum(gh / jnp.maximum(ph, 1e-10), 1e-10)) / var[..., 3]
    tgt = jnp.stack([tx, ty, tw, th], axis=-1)  # [N, P, 4]
    d = loc - jax.lax.stop_gradient(tgt)
    sl1 = jnp.where(jnp.abs(d) < 1.0, 0.5 * d * d, jnp.abs(d) - 0.5)
    loc_loss = jnp.where(pos, sl1.sum(-1), 0.0).sum(axis=1)  # [N]

    # ---- confidence loss with hard negative mining
    tgt_label = jnp.where(pos, gt_label[best_g], bg)  # [N, P]
    logp = jax.nn.log_softmax(conf, axis=-1)
    ce = -jnp.take_along_axis(logp, tgt_label[..., None], axis=-1)[..., 0]
    n_pos = pos.sum(axis=1)  # [N]
    n_neg = jnp.minimum(
        (neg_ratio * n_pos).astype(jnp.int32), P - n_pos
    )
    neg_cand = (~pos) & (best_iou < neg_overlap)
    neg_score = jnp.where(neg_cand, jax.lax.stop_gradient(ce), -jnp.inf)
    order = jnp.argsort(-neg_score, axis=1)  # per image, hardest first
    rank = jnp.argsort(order, axis=1)
    neg = neg_cand & (rank < n_neg[:, None])
    conf_loss = jnp.where(pos | neg, ce, 0.0).sum(axis=1)  # [N]

    denom = jnp.maximum(n_pos.astype(conf.dtype), 1.0)
    return {"Out": ((loc_loss + conf_loss) / denom)[:, None]}


@register_op("detection_map")
def _detection_map(ctx, ins, attrs):
    """Per-batch VOC mean Average Precision as a GRAPH metric (reference
    gserver/evaluators/DetectionMAPEvaluator.cpp; the host-side
    accumulating form lives in fluid/evaluator.py DetectionMAP).

    Inputs: Detection = the padded multiclass_nms buffer [N*K, 6]
    (rows [label, score, x1, y1, x2, y2], -1 padded, pad stride K from
    the producing op's @PAD_STRIDE side-band); GTBox [G, 4] packed with
    an image LoD; GTLabel [G, 1]; optional GTDifficult [G, 1] (difficult
    ground truth is excluded from recall counts and its matches score
    neither TP nor FP, per VOC). Matching follows the VOC protocol: in
    score order each detection takes its best-OVERLAP ground truth; if
    that box is already claimed the detection is a false positive.
    Static-shape design: ONE lax.fori_loop over the padded rows with the
    per-class state vectorised over a leading class axis (compile cost
    independent of num_classes); AP is the integral form.
    """
    det = ins["Detection"][0]  # [M, 6]
    gt_box = ins["GTBox"][0]   # [G, 4]
    gt_label = ins["GTLabel"][0].reshape(-1).astype(jnp.int32)
    difficult = (
        ins["GTDifficult"][0].reshape(-1).astype(bool)
        if ins.get("GTDifficult")
        else jnp.zeros((gt_box.shape[0],), bool)
    )
    det_name = ctx.op.inputs["Detection"][0]
    offsets = ctx.env[lod_key(ctx.op.inputs["GTBox"][0])]
    if attrs.get("pad_stride"):
        K = int(attrs["pad_stride"])  # direct/test feeds
    elif det_name + "@PAD_STRIDE" in ctx.env:
        K = int(ctx.env[det_name + "@PAD_STRIDE"])
    else:
        raise ValueError(
            "detection_map input %r has no @PAD_STRIDE side-band: feed "
            "it the multiclass_nms/detection_output buffer directly, or "
            "set the pad_stride attr explicitly" % det_name
        )
    from .kernels_sequence import seg_ids

    M = det.shape[0]
    G = gt_box.shape[0]
    C = int(attrs.get("num_classes", 0))
    if not C:
        raise ValueError("detection_map needs a num_classes attr")
    thresh = float(attrs.get("overlap_threshold", 0.5))
    bg = int(attrs.get("background_id", -1))

    det_img = jnp.arange(M) // K               # [M]
    gt_img = seg_ids(offsets, G)               # [G]
    valid = det[:, 0] >= 0

    lt = jnp.maximum(det[:, None, 2:4], gt_box[None, :, :2])
    rb = jnp.minimum(det[:, None, 4:6], gt_box[None, :, 2:])
    inter = jnp.maximum(rb - lt, 0.0)
    inter = inter[..., 0] * inter[..., 1]      # [M, G]
    area_d = jnp.maximum(det[:, 4] - det[:, 2], 0.0) * jnp.maximum(
        det[:, 5] - det[:, 3], 0.0
    )
    area_g = jnp.maximum(gt_box[:, 2] - gt_box[:, 0], 0.0) * jnp.maximum(
        gt_box[:, 3] - gt_box[:, 1], 0.0
    )
    iou = inter / jnp.maximum(area_d[:, None] + area_g[None, :] - inter,
                              1e-12)
    same_img = det_img[:, None] == gt_img[None, :]

    classes = jnp.arange(C)                    # [C]
    gt_of = gt_label[None, :] == classes[:, None]          # [C, G]
    is_c = valid[None, :] & (
        det[None, :, 0].astype(jnp.int32) == classes[:, None]
    )                                                       # [C, M]
    n_gt = jnp.sum(gt_of & ~difficult[None, :], axis=1)     # [C]
    scores = jnp.where(is_c, det[None, :, 1], -jnp.inf)     # [C, M]
    order = jnp.argsort(-scores, axis=1)                    # [C, M]
    cand = jnp.where(
        same_img[None, :, :] & gt_of[:, None, :], iou[None, :, :], 0.0
    )                                                       # [C, M, G]

    def body(r, state):
        matched, tp, fp = state  # [C, G], [C, M], [C, M]
        j = order[:, r]                                      # [C]
        live = jnp.isfinite(scores[jnp.arange(C), j])        # [C]
        row = cand[jnp.arange(C), j]                         # [C, G]
        best = jnp.argmax(row, axis=1)                       # [C] best OVERLAP
        best_iou = row[jnp.arange(C), best]
        overlap = best_iou > thresh
        fresh = ~matched[jnp.arange(C), best]
        hard = difficult[best]                               # [C]
        is_tp = live & overlap & fresh & ~hard
        # difficult matches: neither TP nor FP (VOC); claimed-gt or
        # low-overlap detections are FPs
        is_fp = live & ~(overlap & hard) & ~is_tp
        matched = matched.at[jnp.arange(C), best].set(
            matched[jnp.arange(C), best] | (is_tp & overlap)
        )
        tp = tp.at[:, r].set(is_tp.astype(jnp.float32))
        fp = fp.at[:, r].set(is_fp.astype(jnp.float32))
        return matched, tp, fp

    matched0 = jnp.zeros((C, G), bool)
    _, tp, fp = jax.lax.fori_loop(
        0, M, body, (matched0, jnp.zeros((C, M)), jnp.zeros((C, M))),
    )
    ctp = jnp.cumsum(tp, axis=1)
    cfp = jnp.cumsum(fp, axis=1)
    precision = ctp / jnp.maximum(ctp + cfp, 1e-12)
    recall_step = tp / jnp.maximum(
        n_gt[:, None].astype(jnp.float32), 1.0
    )
    aps = jnp.sum(precision * recall_step, axis=1)           # [C]
    has_gt = (n_gt > 0) & (classes != bg)
    mAP = jnp.sum(aps * has_gt) / jnp.maximum(
        jnp.sum(has_gt.astype(jnp.float32)), 1.0
    )
    return {"MAP": mAP.reshape((1,))}


@register_op("pnpair_eval")
def _pnpair_eval(ctx, ins, attrs):
    """Positive-negative pair ratio (reference gserver
    PnpairEvaluator): over all within-query pairs with different labels,
    the fraction ranked correctly by score (ties count half)."""
    score = ins["Score"][0].reshape(-1)
    label = ins["Label"][0].reshape(-1).astype(jnp.float32)
    query = ins["QueryID"][0].reshape(-1).astype(jnp.int32)
    w = (
        ins["Weight"][0].reshape(-1).astype(jnp.float32)
        if ins.get("Weight")
        else jnp.ones_like(score)
    )
    same_q = query[:, None] == query[None, :]
    pos_pair = same_q & (label[:, None] > label[None, :])
    pair_w = w[:, None] * w[None, :]
    correct = (score[:, None] > score[None, :]).astype(jnp.float32)
    tie = (score[:, None] == score[None, :]).astype(jnp.float32)
    num = jnp.sum(jnp.where(pos_pair, (correct + 0.5 * tie) * pair_w, 0.0))
    den = jnp.maximum(jnp.sum(jnp.where(pos_pair, pair_w, 0.0)), 1e-12)
    return {"Out": (num / den).reshape((1,))}
