"""Detection op kernels: prior_box, box_coder, bipartite_match,
multiclass_nms.

Parity: reference operators/prior_box_op.h, box_coder_op.h,
bipartite_match_op.cc, multiclass_nms_op.cc (and the legacy gserver
PriorBox/MultiBoxLoss/DetectionOutput layers). TPU-first re-design:
everything is static-shape. NMS's data-dependent output count becomes a
fixed [N*keep_top_k, 6] buffer, valid rows first, with traced per-image
counts riding the usual LoD side-band — the same convention beam search
decode uses (kernels_control.py).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op
from .kernels_sequence import lod_key

_NEG = -1e30


@register_op("prior_box")
def _prior_box(ctx, ins, attrs):
    """Anchor generation over a feature map (prior_box_op.h)."""
    feat = ins["Input"][0]  # [N, C, H, W]
    image = ins["Image"][0]  # [N, C, Him, Wim]
    H, W = feat.shape[2], feat.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", []):
        ars.append(float(ar))
        if attrs.get("flip", False):
            ars.append(1.0 / float(ar))
    variances = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    step_w = float(attrs.get("step_w", 0.0)) or float(img_w) / W
    step_h = float(attrs.get("step_h", 0.0)) or float(img_h) / H
    offset = float(attrs.get("offset", 0.5))

    wh = []
    for ms in min_sizes:
        for ar in ars:
            wh.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
        if max_sizes:
            mx = max_sizes[min_sizes.index(ms)]
            wh.append((math.sqrt(ms * mx), math.sqrt(ms * mx)))
    P = len(wh)
    whs = jnp.asarray(wh, jnp.float32)  # [P, 2]

    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)  # [H, W]
    c = jnp.stack([cxg, cyg], axis=-1)[:, :, None, :]  # [H,W,1,2]
    half = whs[None, None, :, :] / 2.0  # [1,1,P,2]
    mins = (c - half) / jnp.asarray([img_w, img_h], jnp.float32)
    maxs = (c + half) / jnp.asarray([img_w, img_h], jnp.float32)
    boxes = jnp.concatenate([mins, maxs], axis=-1)  # [H,W,P,4]
    if attrs.get("clip", False):
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), (H, W, P, 4)
    )
    return {"Boxes": boxes, "Variances": var}


@register_op("box_coder")
def _box_coder(ctx, ins, attrs):
    """Center-size encode/decode (box_coder_op.h)."""
    prior = ins["PriorBox"][0]  # [M, 4] xyxy
    pvar = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None  # [M,4]
    target = ins["TargetBox"][0]
    code = attrs.get("code_type", "encode_center_size")

    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    if pvar is None:
        pvar = jnp.ones_like(prior)

    if code == "encode_center_size":
        # target: [M, 4] gt boxes (broadcast against priors row-wise)
        tw = target[..., 2] - target[..., 0]
        th = target[..., 3] - target[..., 1]
        tcx = target[..., 0] + tw / 2
        tcy = target[..., 1] + th / 2
        out = jnp.stack(
            [
                (tcx - pcx) / pw / pvar[:, 0],
                (tcy - pcy) / ph / pvar[:, 1],
                jnp.log(jnp.maximum(tw / pw, 1e-12)) / pvar[:, 2],
                jnp.log(jnp.maximum(th / ph, 1e-12)) / pvar[:, 3],
            ],
            axis=-1,
        )
    else:  # decode_center_size; target [N, M, 4] offsets
        dcx = target[..., 0] * pvar[:, 0] * pw + pcx
        dcy = target[..., 1] * pvar[:, 1] * ph + pcy
        dw = jnp.exp(target[..., 2] * pvar[:, 2]) * pw
        dh = jnp.exp(target[..., 3] * pvar[:, 3]) * ph
        out = jnp.stack(
            [dcx - dw / 2, dcy - dh / 2, dcx + dw / 2, dcy + dh / 2], axis=-1
        )
    return {"OutputBox": out}


@register_op("bipartite_match")
def _bipartite_match(ctx, ins, attrs):
    """Greedy bipartite matching on a [N, M] distance matrix
    (bipartite_match_op.cc BipartiteMatch): repeatedly take the global
    max, bind its row to its column."""
    dist = ins["DistMat"][0]
    # batched via LoD on rows (one instance per sequence) or a single [N,M]
    key = lod_key(ctx.op.inputs["DistMat"][0])
    if key in ctx.env:
        raise NotImplementedError(
            "ragged bipartite_match batches: feed one instance per run "
            "or a dense [N, M] matrix for now"
        )
    N, M = dist.shape
    steps = min(N, M)

    def body(carry, _):
        d, row_of_col, dist_of_col = carry
        flat = jnp.argmax(d)
        i, j = flat // M, flat % M
        best = d[i, j]
        valid = best > _NEG
        row_of_col = jnp.where(
            valid, row_of_col.at[j].set(i.astype(jnp.int32)), row_of_col
        )
        dist_of_col = jnp.where(
            valid, dist_of_col.at[j].set(best), dist_of_col
        )
        d = jnp.where(valid, d.at[i, :].set(_NEG).at[:, j].set(_NEG), d)
        return (d, row_of_col, dist_of_col), None

    init = (
        dist.astype(jnp.float32),
        jnp.full((M,), -1, jnp.int32),
        jnp.zeros((M,), jnp.float32),
    )
    (d, row_of_col, dist_of_col), _ = lax.scan(body, init, None, length=steps)
    return {
        "ColToRowMatchIndices": row_of_col.reshape(1, M),
        "ColToRowMatchDist": dist_of_col.reshape(1, M),
    }


def _iou(boxes):
    """Pairwise IoU of [M, 4] xyxy boxes -> [M, M]."""
    area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) * jnp.maximum(
        boxes[:, 3] - boxes[:, 1], 0
    )
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    inter = jnp.prod(jnp.maximum(rb - lt, 0), axis=-1)
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-12)


def _nms_class(scores, iou, nms_threshold, max_keep):
    """Greedy NMS for one class: returns kept mask. scores [M] (already
    score-threshold-masked to -inf), iou [M, M]."""
    M = scores.shape[0]

    def body(carry, _):
        remaining, kept = carry
        i = jnp.argmax(jnp.where(remaining, scores, _NEG))
        ok = jnp.logical_and(remaining[i], scores[i] > _NEG)
        kept = jnp.where(ok, kept.at[i].set(True), kept)
        suppress = iou[i] > nms_threshold
        remaining = jnp.where(
            ok, jnp.logical_and(remaining, jnp.logical_not(suppress)), remaining
        )
        remaining = remaining.at[i].set(False)
        return (remaining, kept), None

    init = (scores > _NEG, jnp.zeros((M,), bool))
    (_, kept), _ = lax.scan(body, init, None, length=min(max_keep, M))
    return kept


@register_op("multiclass_nms")
def _multiclass_nms(ctx, ins, attrs):
    """Per-class NMS + cross-class keep_top_k (multiclass_nms_op.cc).
    Output: [N*keep_top_k, 6] rows = [label, score, x1, y1, x2, y2],
    valid-first per image, per-image counts in the LoD side-band."""
    scores = ins["Scores"][0]  # [N, C, M]
    bboxes = ins["BBoxes"][0]  # [N, M, 4]
    N, C, M = scores.shape
    bg = int(attrs.get("background_label", 0))
    score_thresh = float(attrs.get("score_threshold", 0.01))
    nms_thresh = float(attrs.get("nms_threshold", 0.3))
    nms_top_k = int(attrs.get("nms_top_k", 400))
    keep_top_k = int(attrs.get("keep_top_k", 200))
    if keep_top_k < 0:
        keep_top_k = C * M

    def one_image(sc, bx):
        iou = _iou(bx)

        def one_class(c_scores):
            s = jnp.where(c_scores > score_thresh, c_scores, _NEG)
            kept = _nms_class(s, iou, nms_thresh, min(nms_top_k, M))
            return jnp.where(kept, c_scores, _NEG)

        per_class = jax.vmap(one_class)(sc)  # [C, M]
        if 0 <= bg < C:
            per_class = per_class.at[bg].set(_NEG)
        flat = per_class.reshape(-1)  # [C*M]
        k = min(keep_top_k, C * M)
        top_s, top_i = lax.top_k(flat, k)
        cls = (top_i // M).astype(jnp.float32)
        box = bx[top_i % M]
        valid = top_s > _NEG
        rows = jnp.concatenate(
            [cls[:, None], top_s[:, None], box], axis=1
        )  # [k, 6]
        rows = jnp.where(valid[:, None], rows, -1.0)
        return rows, valid.sum().astype(jnp.int32)

    rows, counts = jax.vmap(one_image)(scores, bboxes)  # [N,k,6], [N]
    k = rows.shape[1]
    out = rows.reshape(N * k, 6)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)]
    )
    # valid rows are already sorted first per image (top_k order); expose
    # per-image counts as LoD over a *padded* buffer (rows beyond each
    # count are -1 filler at fixed stride k)
    out_name = ctx.op.outputs["Out"][0]
    ctx.env[lod_key(out_name)] = offsets
    ctx.env[out_name + "@PAD_STRIDE"] = k
    return {"Out": out}
