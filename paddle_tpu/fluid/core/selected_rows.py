"""SelectedRows: the sparse-gradient value for embedding tables.

Reference parity: paddle/fluid/framework/selected_rows.h (rows + value
block + height), the SelectedRows branches of the optimizer ops
(operators/sgd_op.cc, adam_op.h) and math/selected_rows_functor.cc
(MergeAdd). The legacy counterpart is the sparse-row update machinery in
paddle/math/SparseRowMatrix.h + MultiGradientMachine.h:140-166.

TPU-native design: a SelectedRows is a pair of stacked device arrays
(`rows` int32 [n], `values` [n, dim]) with a static `height` (vocab
size). `n` is the number of *lookup sites* in the batch — static under
jit — so the whole sparse path traces to fixed-shape gather/scatter ops
the MXU-adjacent scatter units handle natively; no dense [vocab, dim]
cotangent is ever materialised. Out-of-range rows (== height) are
sentinels: every scatter in this module uses mode='drop', so sentinel
rows (padding_idx positions, merge leftovers) fall out of the update.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["SelectedRows", "as_dense"]


class SelectedRows:
    """Sparse gradient: `values[i]` is the gradient contribution to row
    `rows[i]` of a [height, dim] parameter. Rows may repeat (one entry
    per lookup occurrence); duplicates SUM, matching the dense gradient.
    """

    __slots__ = ("rows", "values", "height")

    def __init__(self, rows, values, height: int):
        self.rows = rows
        self.values = values
        self.height = int(height)

    def astype(self, dtype):
        return SelectedRows(self.rows, self.values.astype(dtype), self.height)

    @property
    def dtype(self):
        return self.values.dtype

    def to_dense(self):
        """Densify: scatter-add contributions into a zero [height, dim]
        array — bit-equal to the dense gradient (duplicates merge by
        addition; sentinel rows drop)."""
        out = jnp.zeros((self.height,) + tuple(self.values.shape[1:]),
                        self.values.dtype)
        return out.at[self.rows].add(self.values, mode="drop")

    def merged(self):
        """Combine duplicate rows (reference MergeAdd,
        math/selected_rows_functor.cc): returns (rows', values') of the
        SAME static length where each in-bounds row appears at most once
        with its contributions summed; surplus slots carry the sentinel
        row `height` (dropped by mode='drop' scatters). Required by the
        moment-tracking optimizers (adagrad/adam), whose per-row state
        update must fire once per touched row, not once per occurrence.
        """
        n = self.rows.shape[0]
        order = jnp.argsort(self.rows)
        r = jnp.take(self.rows, order)
        v = jnp.take(self.values, order, axis=0)
        is_new = jnp.concatenate(
            [jnp.ones((1,), bool), r[1:] != r[:-1]]
        )
        seg = jnp.cumsum(is_new.astype(jnp.int32)) - 1
        merged_v = jax.ops.segment_sum(v, seg, num_segments=n)
        # every element of a segment writes the same row id, so the
        # duplicate-index scatter-set is deterministic; untouched slots
        # keep the sentinel
        merged_r = (
            jnp.full((n,), self.height, dtype=jnp.int32).at[seg].set(r)
        )
        return merged_r, merged_v


def as_dense(x):
    """Densify if `x` is a SelectedRows, else pass through. Fetch sites
    and sparse-unaware consumers use this so a sparse gradient is always
    observable as its dense equivalent."""
    return x.to_dense() if isinstance(x, SelectedRows) else x
