"""Sampled-softmax-family kernels: NCE and hierarchical sigmoid.

Parity: reference operators/nce_op.{h,cc} (uniform negative sampling,
per-sample logistic loss) and operators/hierarchical_sigmoid_op
(gserver HierarchicalSigmoidLayer) whose code table is the complete
binary tree over `num_classes` leaves addressed by (label + num_classes)
bit paths (framework MatrixBitCodeFunctor semantics).

TPU-first: sampling uses the trace's counter-derived RNG key (determinism
per step — registry.LoweringContext); everything is dense batched math;
gradients come from jax.vjp, including the sparse-looking scatter into
the class embedding matrices (XLA turns it into an efficient scatter).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .registry import register_op

__all__ = []


@register_op("nce")
def _nce(ctx, ins, attrs):
    """Noise-contrastive estimation loss (reference nce_op.h NCEKernel):
    one logistic term for each true class + num_neg_samples uniform noise
    classes per example."""
    x = ins["Input"][0]  # [N, D]
    label = ins["Label"][0]  # [N, num_true]
    w = ins["Weight"][0]  # [V, D]
    b = ins["Bias"][0] if ins.get("Bias") else None  # [V]
    num_total = int(attrs["num_total_classes"])
    k = int(attrs.get("num_neg_samples", 10))
    N = x.shape[0]
    num_true = label.shape[1] if label.ndim > 1 else 1
    label = label.reshape(N, num_true)

    neg_dist = attrs.get("neg_distribution")
    if neg_dist and len(neg_dist) != num_total:
        raise ValueError(
            "neg_distribution has %d entries but num_total_classes is %d"
            % (len(neg_dist), num_total)
        )
    if neg_dist:
        # legacy NCELayer custom distribution (MultinomialSampler): noise
        # ids drawn ~ dist, and the NCE noise prob becomes k*q(id)
        dist = jnp.asarray(neg_dist, jnp.float32)
        dist = dist / jnp.sum(dist)
        samples = jax.random.categorical(
            ctx.next_key(), jnp.log(dist)[None, :], shape=(N, k)
        )
    else:
        samples = jax.random.randint(
            ctx.next_key(), (N, k), 0, num_total
        )  # uniform sampler, reference's default Sampler
    all_ids = jnp.concatenate([label, samples], axis=1)  # [N, T+k]
    wj = w[all_ids]  # [N, T+k, D]
    logits = jnp.einsum("nd,nkd->nk", x, wj)
    if b is not None:
        logits = logits + b.reshape(-1)[all_ids]

    # Reference formulation (nce_op.h:93,115-133): o = sigmoid(s),
    # b = num_neg_samples / num_total_classes; true-class cost
    # -log(o/(o+b)), sampled-class cost -log(b/(o+b)); summed (NOT
    # averaged over num_true). Stable forms: -log(o/(o+b)) =
    # log(o+b) + softplus(-s); -log(b/(o+b)) = log(o+b) - log(b).
    s = logits.astype(jnp.float32)
    o = jax.nn.sigmoid(s)
    if neg_dist:
        # clamp: a zero-probability class can still appear as a TRUE
        # label; its (masked-out) noise term must not produce log(0)=inf
        # which 0*inf would turn into NaN
        noise_b = jnp.maximum(k * dist[all_ids], 1e-20)  # [N, T+k]
    else:
        noise_b = jnp.float32(k / num_total)
    log_opb = jnp.log(o + noise_b)
    true_cost = log_opb + jax.nn.softplus(-s)
    neg_cost = log_opb - jnp.log(noise_b)
    lbl_mask = jnp.concatenate(
        [jnp.ones((N, num_true)), jnp.zeros((N, k))], axis=1
    ).astype(jnp.float32)
    loss = jnp.sum(
        lbl_mask * true_cost + (1.0 - lbl_mask) * neg_cost,
        axis=1,
        keepdims=True,
    )
    if ins.get("SampleWeight"):
        loss = loss * ins["SampleWeight"][0].reshape(N, 1)
    return {
        "Cost": loss.astype(x.dtype),
        # reference stores the POST-sigmoid activations here (nce_op.h:115)
        "SampleLogits": o.astype(x.dtype),
        "SampleLabels": all_ids,
    }


@register_op("hierarchical_sigmoid")
def _hsigmoid(ctx, ins, attrs):
    """Hierarchical sigmoid over the complete binary tree (reference
    hierarchical_sigmoid_op.h + MatrixBitCodeFunctor: node ids follow the
    heap addressing code = label + num_classes, walking down by halving;
    bit = code & 1 at each level)."""
    x = ins["X"][0]  # [N, D]
    w = ins["W"][0]  # [num_classes - 1, D]
    label = ins["Label"][0].reshape(-1)  # [N]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    C = int(attrs["num_classes"])
    N, D = x.shape
    max_depth = max(1, math.ceil(math.log2(C)))

    code = label + C  # heap index of the leaf
    # walk from the leaf up: levels of (node, bit); node indexing w rows
    # by heap_index - 1 for internal nodes (root = heap 1 -> row 0)
    losses = jnp.zeros((N,), jnp.float32)
    cur = code
    for _ in range(max_depth):
        parent = cur // 2
        bit = (cur & 1).astype(jnp.float32)  # 1 if right child
        valid = parent >= 1
        row = jnp.clip(parent - 1, 0, C - 2)
        logit = jnp.einsum("nd,nd->n", x, w[row])
        if bias is not None:
            logit = logit + bias.reshape(-1)[row]
        # sigmoid cross entropy with target = bit
        term = jax.nn.softplus(logit) - bit * logit
        losses = losses + jnp.where(valid, term, 0.0)
        cur = parent
    pre_out = jnp.zeros((N, max_depth), x.dtype)  # reference cache output
    return {"Out": losses.reshape(N, 1).astype(x.dtype), "PreOut": pre_out}
