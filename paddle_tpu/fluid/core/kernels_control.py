"""Control-flow kernels: While, LoDTensorArray ops, DynamicRNN, beam search.

TPU-first re-design of the reference's control-flow machinery
(operators/while_op.cc, operators/tensor_array_read_write_op.cc,
operators/beam_search_op.cc, operators/beam_search_decode_op.cc,
python/paddle/v2/fluid/layers/control_flow.py):

* Loop counters built from `fill_constant`/`zeros` are *concrete* values
  during tracing (jnp on non-tracer operands executes eagerly), so a
  `While` whose condition depends only on counters unrolls at trace time —
  each unrolled iteration may have different shapes, which is exactly what
  beam-search generation needs (step 0 has batch rows, later steps
  batch*beam). XLA sees one flat graph; there is no host loop at runtime.
* `LoDTensorArray` is a trace-time Python list; `array_write`/`array_read`
  move values *and* their LoD / beam side-bands through it.
* Beam search keeps beams FULL-WIDTH (exactly `beam_size` live-or-frozen
  candidates per source every step) so every iteration has a static shape;
  finished prefixes are frozen (re-emit end_id with their frozen score)
  instead of being dropped the way the reference's dynamic-shape
  PruneEndidCandidates does (beam_search_op.cc:86). Parent pointers travel
  as a traced side-band (`@BEAM_PARENTS`) instead of the reference's
  level-1 LoD offsets.
* `dynamic_rnn` runs its sub-block under one `lax.scan` over bucketed
  padded time — each step is dense MXU work over the whole batch; finished
  sequences carry state unchanged under a mask (the reference instead
  reorders the batch per timestep, sequence2batch.*).
"""

from __future__ import annotations

import weakref
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import LoweringContext, register_op
from .kernels_sequence import lod_key
from .kernels_rnn import packed_to_padded, padded_to_packed, _seq_T

# side-band suffixes that follow a value through tensor arrays
BEAM_PARENTS = "@BEAM_PARENTS"
BEAM_SCORES = "@BEAM_SCORES"
BEAM_ALIVE = "@BEAM_ALIVE"
LOD_SRC = "@LOD_SRC"  # outer (source-sentence) level of a 2-level LoD
BEAM_LENS = "@BEAM_LENS"
_SIDEBANDS = ("@LOD0", BEAM_PARENTS, BEAM_SCORES, BEAM_ALIVE, LOD_SRC, BEAM_LENS)

MAX_WHILE_ITERS = 10000


def get_sidebands(env, name) -> Dict[str, Any]:
    return {s: env[name + s] for s in _SIDEBANDS if (name + s) in env}


def set_sidebands(env, name, bands: Dict[str, Any]):
    for s, v in bands.items():
        env[name + s] = v


class TensorArray(object):
    """Trace-time LoDTensorArray: a list of (value, side-bands) items.

    When a `while` switches from peeled (unrolled) iterations to the
    compiled `lax.fori_loop` phase, slots >= `base` move into dense
    buffers (`buf` [cap, ...stable-shape] + one buffer per side-band) so
    reads/writes with a *traced* loop counter lower to dynamic slices.
    Slots < base keep their per-item (possibly differently-shaped)
    concrete values — beam search step 0 has width 1, later steps width
    beam_size."""

    def __init__(self):
        self.items: List[Any] = []
        self.bands: List[Dict[str, Any]] = []
        self.base: Optional[int] = None  # first buffered slot
        self.buf = None                  # [cap, ...] value buffer
        self.band_bufs: Dict[str, Any] = {}
        self.buffered_len = 0            # slots materialised in buffers

    def write(self, i, value, bands):
        if isinstance(i, jax.core.Tracer) or self.base is not None:
            return self._write_traced(i, value, bands)
        i = int(np.asarray(i).reshape(()))
        while len(self.items) <= i:
            self.items.append(None)
            self.bands.append({})
        self.items[i] = value
        self.bands[i] = dict(bands)

    def read(self, i):
        if isinstance(i, jax.core.Tracer):
            return self._read_traced(i)
        i = int(np.asarray(i).reshape(()))
        if self.base is not None and i >= self.base:
            k = i - self.base
            if k >= self.buffered_len:
                raise IndexError(
                    "LoDTensorArray read at slot %d past length %d"
                    % (i, len(self))
                )
            return (
                self.buf[k],
                {s: b[k] for s, b in self.band_bufs.items()},
            )
        return self.items[i], self.bands[i]

    def __len__(self):
        if self.base is None:
            return len(self.items)
        return self.base + self.buffered_len

    # -- traced (fori_loop) phase -------------------------------------
    def to_buffers(self, cap: int):
        """Move the LAST concrete item into buffer slot 0 (it has the
        stable shape every traced iteration reuses) and allocate `cap`
        slots total."""
        assert self.base is None
        last = len(self.items) - 1
        seed = jnp.asarray(self.items[last])
        self.base = last
        self.buf = jnp.zeros((cap,) + seed.shape, seed.dtype).at[0].set(seed)
        self.band_bufs = {}
        for s, v in self.bands[last].items():
            v = jnp.asarray(v)
            self.band_bufs[s] = (
                jnp.zeros((cap,) + v.shape, v.dtype).at[0].set(v)
            )
        self.items = self.items[:last]
        self.bands = self.bands[:last]
        self.buffered_len = 1

    def to_stacked(self):
        """Buffer ALL items (read-only arrays under a compiled while):
        uniform shapes required — validated by the caller."""
        assert self.base is None and self.items
        self.base = 0
        self.buf = jnp.stack([jnp.asarray(v) for v in self.items])
        self.band_bufs = {
            s: jnp.stack([jnp.asarray(b[s]) for b in self.bands])
            for s in self.bands[0]
        }
        self.buffered_len = len(self.items)
        self.items = []
        self.bands = []

    def carry(self):
        return {"buf": self.buf, **{"band:" + s: b for s, b in self.band_bufs.items()}}

    def set_carry(self, c):
        self.buf = c["buf"]
        self.band_bufs = {
            s[len("band:"):]: v for s, v in c.items() if s.startswith("band:")
        }

    def _read_traced(self, i):
        if self.base is None:
            raise NotImplementedError(
                "LoDTensorArray index must be a trace-time-concrete counter "
                "(build it with fill_constant/zeros + increment) unless the "
                "read happens inside a compiled while loop; got a traced "
                "value outside one"
            )
        k = jnp.asarray(i).reshape(()).astype(jnp.int32) - self.base
        val = lax.dynamic_index_in_dim(self.buf, k, keepdims=False)
        bands = {
            s: lax.dynamic_index_in_dim(b, k, keepdims=False)
            for s, b in self.band_bufs.items()
        }
        return val, bands

    def _write_traced(self, i, value, bands):
        if self.base is None:
            raise NotImplementedError(
                "LoDTensorArray write with a traced index outside a "
                "compiled while loop"
            )
        k = jnp.asarray(i).reshape(()).astype(jnp.int32) - self.base
        if not isinstance(i, jax.core.Tracer):
            ki = int(np.asarray(i).reshape(())) - self.base
            if ki < 0 or ki >= self.buf.shape[0]:
                # JAX scatter would silently DROP (or wrap) an
                # out-of-bounds update
                raise IndexError(
                    "LoDTensorArray write at slot %d outside the buffer "
                    "window [%d, %d) fixed by the compiled while loop"
                    % (ki + self.base, self.base,
                       self.base + self.buf.shape[0])
                )
            self.buffered_len = max(self.buffered_len, ki + 1)
        self.buf = self.buf.at[k].set(
            jnp.asarray(value).astype(self.buf.dtype)
        )
        for s, v in bands.items():
            if s in self.band_bufs:
                self.band_bufs[s] = self.band_bufs[s].at[k].set(
                    jnp.asarray(v).astype(self.band_bufs[s].dtype)
                )


@register_op("array_write")
def _array_write(ctx, ins, attrs):
    env = ctx.env
    arr_name = ctx.op.outputs["Out"][0]
    x_name = ctx.op.inputs["X"][0]
    i = env[ctx.op.inputs["I"][0]]
    arr = env.get(arr_name)
    if not isinstance(arr, TensorArray):
        arr = TensorArray()
    arr.write(i, env[x_name], get_sidebands(env, x_name))
    env[arr_name] = arr
    return {}


@register_op("array_read")
def _array_read(ctx, ins, attrs):
    env = ctx.env
    arr = env[ctx.op.inputs["X"][0]]
    i = env[ctx.op.inputs["I"][0]]
    out_name = ctx.op.outputs["Out"][0]
    value, bands = arr.read(i)
    env[out_name] = value
    # clear stale side-bands on the out name, then install the item's
    for s in _SIDEBANDS:
        env.pop(out_name + s, None)
    set_sidebands(env, out_name, bands)
    return {}


@register_op("array_length")
def _array_length(ctx, ins, attrs):
    arr = ctx.env[ctx.op.inputs["X"][0]]
    return {"Out": np.asarray([len(arr)], np.int64)}


# ops a counter-only condition chain may consist of (simulable on the
# host to count loop trips without tracing tensor work)
_SIM_OPS = frozenset(
    ["increment", "less_than", "less_equal", "greater_than", "greater_equal",
     "equal", "not_equal", "fill_constant", "assign", "cast", "scale",
     "elementwise_add", "elementwise_sub", "logical_and", "logical_or",
     "logical_not"]
)

# peel at least this many iterations before trying to compile the rest
# (beam search reaches its full-width steady state after 2 steps)
_MIN_PEEL = 1

# diagnostics for the last `while` lowering: how many iterations were
# peeled (traced unrolled) vs folded into the compiled fori_loop
LAST_WHILE_STATS = {"peeled": 0, "compiled_remaining": 0}


def _env_signature(env, names):
    sig = {}
    for n in names:
        v = env.get(n)
        if v is None or isinstance(v, TensorArray):
            continue
        if hasattr(v, "shape"):
            sig[n] = (tuple(v.shape), str(jnp.asarray(v).dtype))
    return sig


def _cond_slice_ops(sub, cond_name):
    """The sub-block ops that (transitively) produce the condition —
    iterated to a fixed point so multi-op counter chains resolve."""
    needed = {cond_name}
    for _ in range(len(sub.ops) + 1):
        keep = [
            op for op in sub.ops if set(op.output_arg_names) & needed
        ]
        new_needed = set(needed)
        for op in keep:
            new_needed |= set(op.input_arg_names)
        if new_needed == needed:
            return keep
        needed = new_needed
    return keep


def _count_remaining(sub, cond_name, env, cap):
    """Simulate the counter-only condition chain on host values to count
    how many iterations remain. Returns None when the chain is not
    simulable (non-whitelisted op or non-concrete input)."""
    from .lowering import run_op

    slice_ops = _cond_slice_ops(sub, cond_name)
    if any(op.type not in _SIM_OPS for op in slice_ops):
        return None
    names = set([cond_name])
    for op in slice_ops:
        names |= set(op.input_arg_names) | set(op.output_arg_names)
    sim_env = {}
    for n in names:
        v = env.get(n)
        if v is None:
            continue
        if isinstance(v, jax.core.Tracer):
            return None
        sim_env[n] = np.asarray(v)
    sim_ctx = LoweringContext(sub, None)
    count = 0
    while bool(np.asarray(sim_env[cond_name]).reshape(-1)[0]):
        if count >= cap:
            raise RuntimeError("while op exceeded %d iterations" % cap)
        for op in slice_ops:
            run_op(sim_ctx, op, sim_env)
        count += 1
    return count


@register_op("while")
def _while(ctx, ins, attrs):
    """Counter-bounded While: peel + one compiled lax.fori_loop.

    Phase 1 peels iterations at trace time until the shapes every body op
    produces reach a fixed point (beam-search generation widens from 1 to
    beam_size rows over the first steps — reference PruneEndidCandidates
    would instead change shape every step, beam_search_op.cc:86).
    Phase 2 counts the remaining trips by simulating the counter chain on
    the host (the fluid-era While is always counter-bounded; a traced
    condition is an error). Phase 3 runs the remainder as ONE
    lax.fori_loop whose carry holds every name the body writes plus the
    LoDTensorArrays as dense slot buffers — so an L-step decode compiles
    O(peel)+O(1) body copies instead of L (VERDICT r2 item 3: max_length
    =64, beam=4 compiles once)."""
    from .lowering import run_ops

    env = ctx.env
    cond_name = ctx.op.inputs["Condition"][0]
    sub = ctx.block.program.block(attrs["sub_block"])
    sub_ctx = LoweringContext(
        sub, ctx._base_key, is_test=ctx.is_test, seq_maxlen=ctx.seq_maxlen
    )
    sub_ctx.amp_region = getattr(ctx, "amp_region", False)
    # a nested While's gate must still see the step's fetches and the
    # OUTER loop's downstream readers
    sub_ctx.fetch_names = getattr(ctx, "fetch_names", frozenset())
    # names ops AFTER this while read — directly, through their
    # sub-blocks (program._sub_block_outer_reads), or via fetch —
    # (early-exit safety gate: values frozen at the exit step must not
    # be observable downstream). The counter/cond chain is EXEMPT: under
    # early exit it intentionally reports the exit step, the reference's
    # own semantics (RecurrentGradientMachine stops the loop where the
    # condition turned false).
    program = ctx.block.program
    reads = set(getattr(ctx, "fetch_names", ()))
    reads |= getattr(ctx, "downstream_reads", set())
    seen_self = False
    for op in ctx.block.ops:
        if op is ctx.op:
            seen_self = True
            continue
        if seen_self:
            reads |= set(op.input_arg_names)
            reads |= program._sub_block_outer_reads(op)
    cond_chain = set()
    for cop in _cond_slice_ops(sub, cond_name):
        cond_chain |= set(cop.output_arg_names)
    sub_ctx.downstream_reads = reads - cond_chain
    max_iters = attrs.get("max_iters", MAX_WHILE_ITERS)
    written = []
    for op in sub.ops:
        for n in op.output_arg_names:
            if n not in written:
                written.append(n)

    prev_sig = None
    iters = 0
    fori_ok = True
    while True:
        cond = env[cond_name]
        if isinstance(cond, jax.core.Tracer):
            # data-dependent While — the fluid-era While is always
            # counter-bounded, so this indicates a traced value leaked
            # into the counter chain.
            raise NotImplementedError(
                "While condition %r is data-dependent (traced); only "
                "counter-bounded loops compile. Keep the condition a pure "
                "function of fill_constant counters." % cond_name
            )
        if not bool(np.asarray(cond).reshape(-1)[0]):
            LAST_WHILE_STATS.update(peeled=iters, compiled_remaining=0)
            return {}
        sig = _env_signature(env, written)
        if fori_ok and iters >= _MIN_PEEL and sig == prev_sig and sig:
            remaining = _count_remaining(sub, cond_name, env, max_iters - iters)
            if remaining is None:
                fori_ok = False  # not simulable: unroll (legacy behavior)
            elif remaining == 0:
                LAST_WHILE_STATS.update(peeled=iters, compiled_remaining=0)
                return {}
            else:
                try:
                    _while_fori(sub_ctx, sub, env, written, remaining, iters)
                    LAST_WHILE_STATS.update(
                        peeled=iters, compiled_remaining=remaining
                    )
                    return {}
                except _FallbackToUnroll:
                    fori_ok = False
        if iters >= max_iters:
            raise RuntimeError("while op exceeded %d iterations" % iters)
        prev_sig = sig
        run_ops(sub_ctx, sub.ops, env)
        iters += 1


class _FallbackToUnroll(Exception):
    """Raised by _while_fori BEFORE any state mutation when the body is
    not expressible as a fori_loop; the caller keeps unrolling."""


def _while_fori(sub_ctx, sub, env, written, remaining, iters):
    """Phase 3: the remaining iterations as one lax.fori_loop."""
    from .lowering import run_ops

    # carried names: body-written values (and their side-bands) that are
    # array-like right now — they seed the carry and must keep shape/dtype
    carried = []
    for n in written:
        v = env.get(n)
        if v is None or isinstance(v, TensorArray):
            continue
        if hasattr(v, "shape") or np.isscalar(v):
            carried.append(n)
            for s in _SIDEBANDS:
                if (n + s) in env and (n + s) not in carried:
                    carried.append(n + s)
    # arrays the body touches, split by whether the body writes them
    arr_names, written_arrs = [], set()
    for op in sub.ops:
        if op.type == "array_length":
            for n in op.inputs.get("X", []):
                if isinstance(env.get(n), TensorArray):
                    # length would freeze at its trace-time value inside
                    # the compiled body — the unroll path is exact
                    raise _FallbackToUnroll()
        if op.type in ("array_write", "array_read"):
            for names in list(op.inputs.values()) + list(op.outputs.values()):
                for n in names:
                    if isinstance(env.get(n), TensorArray) and n not in arr_names:
                        arr_names.append(n)
            if op.type == "array_write":
                for n in op.outputs.get("Out", []):
                    written_arrs.add(n)
    arrays = {n: env[n] for n in arr_names}

    # validate BEFORE mutating anything (fallback must be side-effect free)
    for n, arr in arrays.items():
        if arr.base is not None:
            raise _FallbackToUnroll()  # already buffered by an outer loop
        if n in written_arrs:
            # counter-indexed growth: slot len-1 seeds the buffer and the
            # traced phase only touches slots >= len-1. An array populated
            # beyond the loop counter would read wrong slots — unroll.
            if len(arr.items) != iters + 1:
                raise _FallbackToUnroll()
        else:
            # read-only: ALL items must stack into one uniform buffer
            shapes = {tuple(np.asarray(v).shape) for v in arr.items}
            dts = {str(jnp.asarray(v).dtype) for v in arr.items}
            keys = {tuple(sorted(b.keys())) for b in arr.bands}
            if len(shapes) != 1 or len(dts) != 1 or len(keys) != 1:
                raise _FallbackToUnroll()

    snapshots = {
        n: (list(arr.items), [dict(b) for b in arr.bands])
        for n, arr in arrays.items()
    }
    for n, arr in arrays.items():
        if n in written_arrs:
            # traced writes land in slots [len-1, len-1+remaining]
            arr.to_buffers(remaining + 1)
        else:
            arr.to_stacked()

    def _restore_arrays():
        for n, arr in arrays.items():
            arr.items, arr.bands = snapshots[n]
            arr.base = None
            arr.buf = None
            arr.band_bufs = {}
            arr.buffered_len = 0

    base_env = {
        k: v
        for k, v in env.items()
        if k not in carried and not isinstance(v, TensorArray)
    }

    init = {n: jnp.asarray(env[n]) for n in carried}
    init["@arrays"] = {n: arrays[n].carry() for n in arr_names}

    # early exit (reference RecurrentGradientMachine.h:309 stops when
    # every beam has emitted end_id; r4 verdict #5): a carried
    # @BEAM_ALIVE side-band turns the fixed-trip fori_loop into a
    # lax.while_loop whose predicate also requires a live beam. Safe
    # because the full-width beam design is IDEMPOTENT once all beams
    # freeze — every further iteration re-emits end_id at the frozen
    # score with identity parents — so the skipped slots are
    # reconstructed exactly by _fill_frozen_tail below. The loop counter
    # keeps its exit value (reference semantics: the While stops where
    # the condition turned false).
    alive_names = sorted(
        n for n in carried
        if n.endswith(BEAM_ALIVE)
        and hasattr(init[n], "dtype")
        and init[n].dtype == jnp.bool_
    )
    early_exit = bool(alive_names) and EARLY_EXIT_ENABLED
    # only beam emission arrays (they carry @BEAM_PARENTS) are
    # stationary after all beams die; state arrays keep evolving under
    # the fixed-trip schedule, so their reconstructed tails would be
    # wrong. Engage early exit only when every written array consumed by
    # ops AFTER the while is a beam array (reconstructed exactly); dead
    # tails of state arrays are then never observed.
    if early_exit:
        beam_arrs = set()
        for n in written_arrs:
            # written arrays are already buffered (to_buffers above)
            if any(
                s.endswith(BEAM_PARENTS) for s in arrays[n].band_bufs
            ):
                beam_arrs.add(n)
        downstream = getattr(sub_ctx, "downstream_reads", set())
        # both non-beam arrays AND carried loop variables freeze at the
        # exit step; if anything after the while reads one, its value
        # would diverge from the fixed-trip schedule — stay exact
        if (
            ((written_arrs - beam_arrs) | set(carried)) & downstream
            or not beam_arrs
        ):
            early_exit = False

    def body(j, carry):
        del j
        step_env = dict(base_env)
        for n in carried:
            step_env[n] = carry[n]
        for n in arr_names:
            arrays[n].set_carry(carry["@arrays"][n])
            step_env[n] = arrays[n]
        run_ops(sub_ctx, sub.ops, step_env)
        out = {n: jnp.asarray(step_env[n]) for n in carried}
        out["@arrays"] = {n: arrays[n].carry() for n in arr_names}
        return out

    try:
        if early_exit:
            def cond_fn(jc):
                j, carry = jc
                # a While may host several beam chains: stop only when
                # EVERY chain's beams are dead
                live = jnp.zeros((), bool)
                for n in alive_names:
                    live = live | jnp.any(carry[n])
                return (j < remaining) & live

            def body_fn(jc):
                j, carry = jc
                return j + 1, body(j, carry)

            executed, final = lax.while_loop(cond_fn, body_fn, (0, init))
        else:
            executed = remaining
            final = lax.fori_loop(0, remaining, body, init)
    except _FallbackToUnroll:
        _restore_arrays()
        raise
    except (NotImplementedError, TypeError, ValueError) as e:
        # the body is not expressible under tracing (a kernel needed a
        # concrete value — jax Concretization/Tracer errors subclass
        # TypeError — or a carry dtype/structure mismatch): restore the
        # arrays and let the exact unroll path handle the loop. Other
        # exception types are genuine bugs and propagate.
        del e
        _restore_arrays()
        raise _FallbackToUnroll()
    for n in carried:
        env[n] = final[n]
    for n in arr_names:
        arrays[n].set_carry(final["@arrays"][n])
        if n in written_arrs:
            arrays[n].buffered_len = remaining + 1
            if early_exit and n in beam_arrs:
                _fill_frozen_tail(arrays[n], executed)
        env[n] = arrays[n]
    LAST_WHILE_STATS["early_exit_armed"] = early_exit


# kill switch for the beam early-exit (PADDLE_TPU_NO_EARLY_EXIT=1 keeps
# the fixed-trip fori_loop — the exact legacy schedule)
import os as _os

EARLY_EXIT_ENABLED = _os.environ.get("PADDLE_TPU_NO_EARLY_EXIT", "0") != "1"


def _fill_frozen_tail(arr, executed):
    """Reconstruct the slots an early-exited beam loop never wrote.

    Iteration j writes buffer slot j+1, so after `executed` iterations
    slots executed+1..cap-1 are untouched zeros. Had the loop run on,
    every one of those steps would have written the all-frozen emission:
    ids == end_id everywhere (all-dead <=> every selected id is end_id,
    so repeating the exit slot is exact), scores/LoD bands repeat the
    exit slot, parents are the identity (stable top_k over the already
    sorted frozen scores), alive is all-False (== exit slot)."""
    cap = arr.buf.shape[0]
    tail = jnp.arange(cap) > executed  # [cap]

    def rep(buf):
        exit_slot = lax.dynamic_index_in_dim(buf, executed, keepdims=True)
        shape = (cap,) + (1,) * (buf.ndim - 1)
        return jnp.where(tail.reshape(shape), exit_slot, buf)

    arr.buf = rep(arr.buf)
    for s, buf in list(arr.band_bufs.items()):
        if s.endswith(BEAM_PARENTS):
            ident = jnp.broadcast_to(
                jnp.arange(buf.shape[1], dtype=buf.dtype), buf.shape[1:]
            )
            arr.band_bufs[s] = jnp.where(
                tail.reshape((cap,) + (1,) * (buf.ndim - 1)),
                ident[None], buf,
            )
        else:
            arr.band_bufs[s] = rep(buf)


# ---------------------------------------------------------------------------
# dynamic_rnn — sub-block under lax.scan (DynamicRNN layer sugar)
# ---------------------------------------------------------------------------


@register_op("dynamic_rnn")
def _dynamic_rnn(ctx, ins, attrs):
    from .lowering import run_ops

    env = ctx.env
    op = ctx.op
    sub = ctx.block.program.block(attrs["sub_block"])
    step_outer = op.inputs.get("StepIn", [])
    step_inner = attrs["step_inner"]
    static_outer = op.inputs.get("Static", [])
    static_inner = attrs.get("static_inner", [])
    mem_pre = attrs["mem_pre"]  # inner pre-state names
    mem_update = attrs["mem_update"]  # inner updated-state names
    mem_init = attrs["mem_init_names"]  # outer init var name or "" per memory
    mem_shapes = attrs.get("mem_shapes", [])
    mem_values = attrs.get("mem_values", [])
    mem_dtypes = attrs.get("mem_dtypes", [])
    out_inner = attrs["out_inner"]
    out_outer = op.outputs["Out"]

    x0_name = step_outer[0]
    offsets = env[lod_key(x0_name)]
    total = env[x0_name].shape[0]
    T = _seq_T(ctx, total, offsets)
    B = offsets.shape[0] - 1

    xs_padded = []
    mask = None
    for name in step_outer:
        p, m = packed_to_padded(env[name], offsets, T)  # [B,T,...]
        xs_padded.append(jnp.moveaxis(p, 1, 0))  # [T,B,...]
        if mask is None:
            mask = jnp.moveaxis(m, 1, 0)  # [T,B]

    carry = {}
    for j, pre in enumerate(mem_pre):
        if mem_init[j]:
            carry[pre] = env[mem_init[j]]
        else:
            shape = (B,) + tuple(int(s) for s in mem_shapes[j] if int(s) > 0)
            carry[pre] = jnp.full(shape, mem_values[j], mem_dtypes[j])

    sub_ctx = LoweringContext(
        sub, ctx._base_key, is_test=ctx.is_test, seq_maxlen=ctx.seq_maxlen
    )
    sub_ctx.amp_region = getattr(ctx, "amp_region", False)
    # everything the sub-block reads from outside (weights, static inputs)
    # is closed over: scan hoists them as loop constants
    base_env = {
        k: v for k, v in env.items() if not isinstance(v, TensorArray)
    }
    for so, si in zip(static_outer, static_inner):
        base_env[si] = env[so]

    def body(carry, xs):
        t_inputs, m_t = xs
        senv = dict(base_env)
        for si, v in zip(step_inner, t_inputs):
            senv[si] = v
        senv.update(carry)
        run_ops(sub_ctx, sub.ops, senv)
        new_carry = {}
        for pre, upd in zip(mem_pre, mem_update):
            new = senv[upd]
            keep = m_t.reshape((-1,) + (1,) * (new.ndim - 1))
            new_carry[pre] = jnp.where(keep, new, carry[pre])
        ys = tuple(senv[o] for o in out_inner)
        return new_carry, ys

    _, ys_stacked = lax.scan(body, carry, (tuple(xs_padded), mask))

    outs = []
    for y in ys_stacked:  # each [T,B,...]
        padded = jnp.moveaxis(y, 0, 1)  # [B,T,...]
        outs.append(padded_to_packed(padded, offsets, total))
    for name in out_outer:
        env[lod_key(name)] = offsets
    return {"Out": outs}


# ---------------------------------------------------------------------------
# beam search (full-width static-shape re-design)
# ---------------------------------------------------------------------------

_NEG_INF = -1e9


@register_op("beam_search")
def _beam_search(ctx, ins, attrs):
    env = ctx.env
    op = ctx.op
    pre_ids_name = op.inputs["pre_ids"][0]
    pre_ids = env[pre_ids_name]  # [R, 1] int
    ids = env[op.inputs["ids"][0]]  # [R, K] int
    scores = env[op.inputs["scores"][0]]  # [R, K] float
    B = int(attrs["beam_size"])
    end_id = int(attrs["end_id"])

    R = pre_ids.shape[0]
    K = ids.shape[1]
    pre_bands = get_sidebands(env, pre_ids_name)
    # rows-per-source (static): the outer LoD level's *shape* gives the
    # source count even though its values are traced. Uniform widths only —
    # the full-width design keeps exactly beam_size rows per source after
    # the first step, and a direct 2-level feed must be uniform too.
    if LOD_SRC in pre_bands:
        S = int(pre_bands[LOD_SRC].shape[0]) - 1
        width = R // S
    else:
        # no outer level fed: first step (width 1) unless this is our own
        # previous full-width output
        width = B if BEAM_PARENTS in pre_bands else 1
        S = R // width  # number of source sentences (static)

    pre_score = pre_bands.get(BEAM_SCORES)
    if pre_score is None:
        pre_score = jnp.zeros((R,), scores.dtype)
    alive = pre_bands.get(BEAM_ALIVE)
    if alive is None:
        alive = jnp.ones((R,), bool)
    alive = jnp.logical_and(alive, pre_ids.reshape(-1) != end_id)

    # candidate matrix per source: width*K expansion candidates + width
    # "frozen" candidates (an ended prefix re-emits end_id at its frozen
    # score; a live prefix's frozen slot is -inf)
    exp_scores = jnp.where(alive[:, None], scores, _NEG_INF)  # [R,K]
    frozen_scores = jnp.where(alive, _NEG_INF, pre_score)  # [R]
    cand_scores = jnp.concatenate(
        [exp_scores.reshape(S, width * K), frozen_scores.reshape(S, width)], axis=1
    )  # [S, width*K + width]
    cand_ids = jnp.concatenate(
        [
            ids.reshape(S, width * K),
            jnp.full((S, width), end_id, ids.dtype),
        ],
        axis=1,
    )
    # local parent (row within source) of each candidate
    local_parent = jnp.concatenate(
        [
            jnp.repeat(jnp.arange(width, dtype=jnp.int32), K),
            jnp.arange(width, dtype=jnp.int32),
        ]
    )  # [width*K + width]

    top_scores, top_idx = lax.top_k(cand_scores, B)  # [S, B]
    sel_ids = jnp.take_along_axis(cand_ids, top_idx, axis=1)  # [S, B]
    sel_parent = (
        local_parent[top_idx] + (jnp.arange(S, dtype=jnp.int32) * width)[:, None]
    )  # [S, B] global row into R

    out_rows = S * B
    selected_ids = sel_ids.reshape(out_rows, 1)
    selected_scores = top_scores.reshape(out_rows, 1).astype(scores.dtype)
    parents = sel_parent.reshape(out_rows)
    new_alive = selected_ids.reshape(-1) != end_id

    src_offsets = jnp.arange(S + 1, dtype=jnp.int32) * B
    row_offsets = jnp.arange(out_rows + 1, dtype=jnp.int32)
    for out_name in (op.outputs["selected_ids"][0], op.outputs["selected_scores"][0]):
        set_sidebands(
            env,
            out_name,
            {
                "@LOD0": row_offsets,
                LOD_SRC: src_offsets,
                BEAM_PARENTS: parents,
                BEAM_SCORES: selected_scores.reshape(-1),
                BEAM_ALIVE: new_alive,
            },
        )
    return {"selected_ids": selected_ids, "selected_scores": selected_scores}


@register_op("beam_search_decode")
def _beam_search_decode(ctx, ins, attrs):
    """Backtrack the ids/scores TensorArrays into full sentences.

    Reference operators/beam_search_decode_op.cc walks prefix trees built
    from level-1 LoD; here parent pointers are explicit side-bands and the
    walk is a trace-time loop over the (concrete-length) array emitting one
    gather per step. Output: padded [S*beam, T] sentences + length vector,
    plus packed-LoD offsets so sequence ops can consume the result."""
    env = ctx.env
    op = ctx.op
    ids_arr: TensorArray = env[op.inputs["Ids"][0]]
    scores_arr: TensorArray = env[op.inputs["Scores"][0]]
    T = len(ids_arr) - 1  # item 0 is the init (start-token) step
    if T < 1:
        raise ValueError("beam_search_decode needs at least one search step")
    last_v, last_b = ids_arr.read(T)
    R = last_v.shape[0]  # S * beam

    row = jnp.arange(R, dtype=jnp.int32)
    toks, tok_scores, alive_flags = [], [], []
    for t in range(T, 0, -1):
        v, b = ids_arr.read(t)
        sv, _ = scores_arr.read(t)
        toks.append(v.reshape(-1)[row])
        tok_scores.append(sv.reshape(-1)[row])
        alive_flags.append(b[BEAM_ALIVE][row])
        row = b[BEAM_PARENTS][row]
    v0, _ = ids_arr.read(0)
    sv0, _ = scores_arr.read(0)
    toks.append(v0.reshape(-1)[row])
    tok_scores.append(sv0.reshape(-1)[row])
    alive_flags.append(jnp.ones((R,), bool))

    ids_mat = jnp.stack(toks[::-1], axis=1)  # [R, T+1]
    scores_mat = jnp.stack(tok_scores[::-1], axis=1)
    alive_mat = jnp.stack(alive_flags[::-1], axis=1)  # [R, T+1]

    # length = up to and including the first end token (first not-alive)
    ended = jnp.logical_not(alive_mat)
    any_end = jnp.any(ended, axis=1)
    first_end = jnp.argmax(ended, axis=1)
    lens = jnp.where(any_end, first_end + 1, T + 1).astype(jnp.int32)

    src_off = last_b.get(LOD_SRC)
    # num_results_per_sample < beam: keep only each source's top-n rows
    # by cumulative score (reference RecurrentGradientMachine's
    # numResultsPerSample truncation)
    n_res = int(attrs.get("num_results_per_sample", 0) or 0)
    beam = int(attrs.get("beam_width", 0) or 0)
    if n_res and beam and n_res < beam and R % beam == 0:
        S = R // beam
        final_sc, _ = scores_arr.read(T)
        per_src = final_sc.reshape(S, beam)
        order = jnp.argsort(-per_src, axis=1)[:, :n_res]  # best-first
        take = (
            jnp.arange(S, dtype=jnp.int32)[:, None] * beam + order
        ).reshape(-1)
        ids_mat = ids_mat[take]
        scores_mat = scores_mat[take]
        lens = lens[take]
        src_off = jnp.arange(S + 1, dtype=jnp.int32) * n_res

    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)]
    )
    for out_name in (op.outputs["SentenceIds"][0], op.outputs["SentenceScores"][0]):
        bands = {"@LOD0": offsets, BEAM_LENS: lens}
        if src_off is not None:
            bands[LOD_SRC] = src_off
        set_sidebands(env, out_name, bands)
    outs = {"SentenceIds": ids_mat, "SentenceScores": scores_mat}
    if "SentenceLens" in op.outputs:
        outs["SentenceLens"] = lens
    return outs


@register_op("beam_init")
def _beam_init(ctx, ins, attrs):
    """Synthesize generation-start ids/scores (one <bos> per source row of
    X) with the 2-level beam side-bands the beam_search kernel expects —
    the reference builds these inside RecurrentGradientMachine's
    generation path (RecurrentGradientMachine.h:307) rather than feeding
    them."""
    x = ins["X"][0]
    B = x.shape[0]
    bos = int(attrs["bos_id"])
    ids = jnp.full((B, 1), bos, jnp.int32)
    scores = jnp.ones((B, 1), jnp.float32)
    off = jnp.arange(B + 1, dtype=jnp.int32)
    for out_name in (ctx.op.outputs["Ids"][0], ctx.op.outputs["Scores"][0]):
        set_sidebands(ctx.env, out_name, {"@LOD0": off, LOD_SRC: off})
    return {"Ids": ids, "Scores": scores}


@register_op("lod_tensor_to_array")
def _lod_tensor_to_array(ctx, ins, attrs):
    """Scatter a ragged batch into a TensorArray of time steps in rank-
    table order (reference lod_tensor_to_array_op.cc): entry t holds row
    t of every sequence, sequences sorted longest-first.

    TPU-first divergence (documented): entries keep the STATIC [n, D]
    shape with zero rows once a sequence has ended, instead of the
    reference's physically shrinking batch — shrink_memory is then a
    masked no-op and one compiled program covers every batch mix."""
    from .kernels_sequence import lod_key as _lk

    x = ctx.env[ctx.op.inputs["X"][0]]
    table = ctx.env[ctx.op.inputs["RankTable"][0]]
    offsets = ctx.env[_lk(ctx.op.inputs["X"][0])]
    order = table[:, 0]
    n = order.shape[0]
    total = x.shape[0]
    from .kernels_rnn import _seq_T

    T = _seq_T(ctx, x.shape[0], offsets)
    arr = TensorArray()
    for t in range(T):
        src = offsets[order] + t
        valid = (src < offsets[order + 1]).reshape((-1,) + (1,) * (x.ndim - 1))
        row = jnp.where(valid, x[jnp.clip(src, 0, total - 1)], 0.0)
        arr.write(t, row, {})
    ctx.env[ctx.op.outputs["Out"][0]] = arr
    return {}


@register_op("array_to_lod_tensor")
def _array_to_lod_tensor(ctx, ins, attrs):
    """Inverse of lod_tensor_to_array: gather time-step entries back
    into the packed ragged layout of the rank table's original order."""
    from .kernels_sequence import lod_key as _lk

    arr = ctx.env[ctx.op.inputs["X"][0]]
    table = ctx.env[ctx.op.inputs["RankTable"][0]]
    order = table[:, 0]
    lengths = table[:, 1]
    n = order.shape[0]
    T = len(arr)
    stacked = jnp.stack([arr.read(t)[0] for t in range(T)])  # [T, n, D]
    # original offsets: lengths permuted back to original sequence ids
    orig_len = jnp.zeros((n,), jnp.int32).at[order].set(lengths)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(orig_len, dtype=jnp.int32)]
    )
    total = int(T) * int(n)
    pos = jnp.arange(total, dtype=jnp.int32)
    seq = jnp.searchsorted(offsets, pos, side="right") - 1
    seq_c = jnp.clip(seq, 0, n - 1)
    # rank slot of original sequence s
    rank_of = jnp.zeros((n,), jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32)
    )
    t_idx = pos - offsets[seq_c]
    out = stacked[jnp.clip(t_idx, 0, T - 1), rank_of[seq_c]]
    live = (pos < offsets[-1]).reshape((-1,) + (1,) * (out.ndim - 1))
    out = jnp.where(live, out, 0.0)
    ctx.env[_lk(ctx.op.outputs["Out"][0])] = offsets
    return {"Out": out}


@register_op("shrink_rnn_memory")
def _shrink_rnn_memory(ctx, ins, attrs):
    """Reference shrink_rnn_memory_op trims the state to sequences still
    alive at step I. Static-shape design: states of finished sequences
    are masked to zero instead of removed (see lod_tensor_to_array)."""
    x = ins["X"][0]
    table = ctx.env[ctx.op.inputs["RankTable"][0]]
    i = ctx.env[ctx.op.inputs["I"][0]]
    alive = (table[:, 1] > jnp.asarray(i).reshape(())[None]).reshape(
        (-1,) + (1,) * (x.ndim - 1)
    )
    return {"Out": jnp.where(alive, x, 0.0)}


# print-op access counters, keyed by the Operator instance so first_n
# survives retraces (a new feed shape re-lowers the block; the closure
# would otherwise restart the budget). WeakKey: dies with the program.
_PRINT_COUNTS = weakref.WeakKeyDictionary()


@register_op("print")
def _print_op(ctx, ins, attrs):
    """Debug print that fires when the tensor is computed (reference
    layers/control_flow.py:149 Print -> operators/print_op.cc). The fused
    XLA step has no per-op execution to hook, so the kernel taps the value
    with `jax.debug.callback` (host print at runtime, jit-safe) and prints
    the cotangent through a custom_vjp when print_phase includes backward.

    Under memory_optimize() the forward region is rematerialized, so the
    value really is computed twice per training step — the forward print
    then fires on both passes (standard JAX remat-effect semantics) and
    first_n budgets accordingly."""
    x = ins["In"][0]
    name = (ctx.op.inputs.get("In") or [""])[0]
    message = attrs.get("message", "") or ""
    first_n = int(attrs.get("first_n", -1))
    summarize = int(attrs.get("summarize", -1))
    phase = str(attrs.get("print_phase", "BOTH")).upper()
    show_name = attrs.get("print_tensor_name", True)
    show_type = attrs.get("print_tensor_type", True)
    show_shape = attrs.get("print_tensor_shape", True)
    show_lod = attrs.get("print_tensor_lod", True)
    lod = ctx.env.get(lod_key(name)) if show_lod else None

    # per-direction budgets: the reference print_op counts per op
    # invocation per direction, so first_n=N means N forward prints AND
    # N backward prints — a shared counter would halve the budget under
    # print_phase='both' (and double-spend it under remat re-emission)
    counter = _PRINT_COUNTS.setdefault(ctx.op, {"": 0, "@GRAD": 0})

    def _emit(tag, val, lod_val=None):
        # reference print_op semantics: first_n <= 0 means no limit
        if 0 < first_n <= counter[tag]:
            return
        counter[tag] += 1
        arr = np.asarray(val)
        flat = np.ravel(arr)
        if summarize >= 0:
            flat = flat[:summarize]
        bits = [message] if message else []
        if show_name:
            bits.append("name=%s%s" % (name, tag))
        if show_type:
            bits.append("dtype=%s" % arr.dtype)
        if show_shape:
            bits.append("shape=%s" % (arr.shape,))
        if lod_val is not None:
            bits.append("lod=%s" % np.asarray(lod_val).tolist())
        print("%s data=%s" % (" ".join(bits), flat), flush=True)

    fwd_print = phase in ("FORWARD", "BOTH")
    bwd_print = phase in ("BACKWARD", "BOTH")

    # the forward print attaches to the primal trace directly (a
    # custom_vjp fwd rule would only run under differentiation, and
    # inference programs never differentiate)
    if fwd_print:
        if lod is not None:
            jax.debug.callback(lambda val, lv: _emit("", val, lv), x, lod)
        else:
            jax.debug.callback(lambda val: _emit("", val), x)

    if bwd_print:

        @jax.custom_vjp
        def _tap(v):
            return v

        def _tap_fwd(v):
            return v, None

        def _tap_bwd(_, g):
            jax.debug.callback(lambda val: _emit("@GRAD", val), g)
            return (g,)

        _tap.defvjp(_tap_fwd, _tap_bwd)
        x = _tap(x)
    return {"Out": x}
