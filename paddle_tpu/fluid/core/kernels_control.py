"""Control-flow kernels: While, LoDTensorArray ops, DynamicRNN, beam search.

TPU-first re-design of the reference's control-flow machinery
(operators/while_op.cc, operators/tensor_array_read_write_op.cc,
operators/beam_search_op.cc, operators/beam_search_decode_op.cc,
python/paddle/v2/fluid/layers/control_flow.py):

* Loop counters built from `fill_constant`/`zeros` are *concrete* values
  during tracing (jnp on non-tracer operands executes eagerly), so a
  `While` whose condition depends only on counters unrolls at trace time —
  each unrolled iteration may have different shapes, which is exactly what
  beam-search generation needs (step 0 has batch rows, later steps
  batch*beam). XLA sees one flat graph; there is no host loop at runtime.
* `LoDTensorArray` is a trace-time Python list; `array_write`/`array_read`
  move values *and* their LoD / beam side-bands through it.
* Beam search keeps beams FULL-WIDTH (exactly `beam_size` live-or-frozen
  candidates per source every step) so every iteration has a static shape;
  finished prefixes are frozen (re-emit end_id with their frozen score)
  instead of being dropped the way the reference's dynamic-shape
  PruneEndidCandidates does (beam_search_op.cc:86). Parent pointers travel
  as a traced side-band (`@BEAM_PARENTS`) instead of the reference's
  level-1 LoD offsets.
* `dynamic_rnn` runs its sub-block under one `lax.scan` over bucketed
  padded time — each step is dense MXU work over the whole batch; finished
  sequences carry state unchanged under a mask (the reference instead
  reorders the batch per timestep, sequence2batch.*).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import LoweringContext, register_op
from .kernels_sequence import lod_key
from .kernels_rnn import packed_to_padded, padded_to_packed, _seq_T

# side-band suffixes that follow a value through tensor arrays
BEAM_PARENTS = "@BEAM_PARENTS"
BEAM_SCORES = "@BEAM_SCORES"
BEAM_ALIVE = "@BEAM_ALIVE"
LOD_SRC = "@LOD_SRC"  # outer (source-sentence) level of a 2-level LoD
BEAM_LENS = "@BEAM_LENS"
_SIDEBANDS = ("@LOD0", BEAM_PARENTS, BEAM_SCORES, BEAM_ALIVE, LOD_SRC, BEAM_LENS)

MAX_WHILE_ITERS = 10000


def get_sidebands(env, name) -> Dict[str, Any]:
    return {s: env[name + s] for s in _SIDEBANDS if (name + s) in env}


def set_sidebands(env, name, bands: Dict[str, Any]):
    for s, v in bands.items():
        env[name + s] = v


class TensorArray(object):
    """Trace-time LoDTensorArray: a list of (value, side-bands) items."""

    def __init__(self):
        self.items: List[Any] = []
        self.bands: List[Dict[str, Any]] = []

    def write(self, i: int, value, bands):
        i = int(i)
        while len(self.items) <= i:
            self.items.append(None)
            self.bands.append({})
        self.items[i] = value
        self.bands[i] = dict(bands)

    def read(self, i: int):
        i = int(i)
        return self.items[i], self.bands[i]

    def __len__(self):
        return len(self.items)


def _concrete_int(v) -> int:
    """Host-concrete scalar index (raises on tracers, by design: array
    indices must be loop counters, which stay concrete during tracing)."""
    if isinstance(v, jax.core.Tracer):
        raise NotImplementedError(
            "LoDTensorArray index must be a trace-time-concrete counter "
            "(build it with fill_constant/zeros + increment); got a traced "
            "value"
        )
    return int(np.asarray(v).reshape(()))


@register_op("array_write")
def _array_write(ctx, ins, attrs):
    env = ctx.env
    arr_name = ctx.op.outputs["Out"][0]
    x_name = ctx.op.inputs["X"][0]
    i = _concrete_int(env[ctx.op.inputs["I"][0]])
    arr = env.get(arr_name)
    if not isinstance(arr, TensorArray):
        arr = TensorArray()
    arr.write(i, env[x_name], get_sidebands(env, x_name))
    env[arr_name] = arr
    return {}


@register_op("array_read")
def _array_read(ctx, ins, attrs):
    env = ctx.env
    arr = env[ctx.op.inputs["X"][0]]
    i = _concrete_int(env[ctx.op.inputs["I"][0]])
    out_name = ctx.op.outputs["Out"][0]
    value, bands = arr.read(i)
    env[out_name] = value
    # clear stale side-bands on the out name, then install the item's
    for s in _SIDEBANDS:
        env.pop(out_name + s, None)
    set_sidebands(env, out_name, bands)
    return {}


@register_op("array_length")
def _array_length(ctx, ins, attrs):
    arr = ctx.env[ctx.op.inputs["X"][0]]
    return {"Out": np.asarray([len(arr)], np.int64)}


@register_op("while")
def _while(ctx, ins, attrs):
    """Trace-time bounded unroll (see module docstring)."""
    from .lowering import run_ops

    env = ctx.env
    cond_name = ctx.op.inputs["Condition"][0]
    sub = ctx.block.program.block(attrs["sub_block"])
    sub_ctx = LoweringContext(
        sub, ctx._base_key, is_test=ctx.is_test, seq_maxlen=ctx.seq_maxlen
    )
    iters = 0
    while True:
        cond = env[cond_name]
        if isinstance(cond, jax.core.Tracer):
            # data-dependent While — the fluid-era While is always
            # counter-bounded, so this indicates a traced value leaked
            # into the counter chain.
            raise NotImplementedError(
                "While condition %r is data-dependent (traced); only "
                "counter-bounded loops unroll. Keep the condition a pure "
                "function of fill_constant counters." % cond_name
            )
        if not bool(np.asarray(cond).reshape(-1)[0]):
            break
        if iters >= attrs.get("max_iters", MAX_WHILE_ITERS):
            raise RuntimeError("while op exceeded %d iterations" % iters)
        run_ops(sub_ctx, sub.ops, env)
        iters += 1
    return {}


# ---------------------------------------------------------------------------
# dynamic_rnn — sub-block under lax.scan (DynamicRNN layer sugar)
# ---------------------------------------------------------------------------


@register_op("dynamic_rnn")
def _dynamic_rnn(ctx, ins, attrs):
    from .lowering import run_ops

    env = ctx.env
    op = ctx.op
    sub = ctx.block.program.block(attrs["sub_block"])
    step_outer = op.inputs.get("StepIn", [])
    step_inner = attrs["step_inner"]
    static_outer = op.inputs.get("Static", [])
    static_inner = attrs.get("static_inner", [])
    mem_pre = attrs["mem_pre"]  # inner pre-state names
    mem_update = attrs["mem_update"]  # inner updated-state names
    mem_init = attrs["mem_init_names"]  # outer init var name or "" per memory
    mem_shapes = attrs.get("mem_shapes", [])
    mem_values = attrs.get("mem_values", [])
    mem_dtypes = attrs.get("mem_dtypes", [])
    out_inner = attrs["out_inner"]
    out_outer = op.outputs["Out"]

    x0_name = step_outer[0]
    offsets = env[lod_key(x0_name)]
    total = env[x0_name].shape[0]
    T = _seq_T(ctx, total)
    B = offsets.shape[0] - 1

    xs_padded = []
    mask = None
    for name in step_outer:
        p, m = packed_to_padded(env[name], offsets, T)  # [B,T,...]
        xs_padded.append(jnp.moveaxis(p, 1, 0))  # [T,B,...]
        if mask is None:
            mask = jnp.moveaxis(m, 1, 0)  # [T,B]

    carry = {}
    for j, pre in enumerate(mem_pre):
        if mem_init[j]:
            carry[pre] = env[mem_init[j]]
        else:
            shape = (B,) + tuple(int(s) for s in mem_shapes[j] if int(s) > 0)
            carry[pre] = jnp.full(shape, mem_values[j], mem_dtypes[j])

    sub_ctx = LoweringContext(
        sub, ctx._base_key, is_test=ctx.is_test, seq_maxlen=ctx.seq_maxlen
    )
    # everything the sub-block reads from outside (weights, static inputs)
    # is closed over: scan hoists them as loop constants
    base_env = {
        k: v for k, v in env.items() if not isinstance(v, TensorArray)
    }
    for so, si in zip(static_outer, static_inner):
        base_env[si] = env[so]

    def body(carry, xs):
        t_inputs, m_t = xs
        senv = dict(base_env)
        for si, v in zip(step_inner, t_inputs):
            senv[si] = v
        senv.update(carry)
        run_ops(sub_ctx, sub.ops, senv)
        new_carry = {}
        for pre, upd in zip(mem_pre, mem_update):
            new = senv[upd]
            keep = m_t.reshape((-1,) + (1,) * (new.ndim - 1))
            new_carry[pre] = jnp.where(keep, new, carry[pre])
        ys = tuple(senv[o] for o in out_inner)
        return new_carry, ys

    _, ys_stacked = lax.scan(body, carry, (tuple(xs_padded), mask))

    outs = []
    for y in ys_stacked:  # each [T,B,...]
        padded = jnp.moveaxis(y, 0, 1)  # [B,T,...]
        outs.append(padded_to_packed(padded, offsets, total))
    for name in out_outer:
        env[lod_key(name)] = offsets
    return {"Out": outs}


# ---------------------------------------------------------------------------
# beam search (full-width static-shape re-design)
# ---------------------------------------------------------------------------

_NEG_INF = -1e9


@register_op("beam_search")
def _beam_search(ctx, ins, attrs):
    env = ctx.env
    op = ctx.op
    pre_ids_name = op.inputs["pre_ids"][0]
    pre_ids = env[pre_ids_name]  # [R, 1] int
    ids = env[op.inputs["ids"][0]]  # [R, K] int
    scores = env[op.inputs["scores"][0]]  # [R, K] float
    B = int(attrs["beam_size"])
    end_id = int(attrs["end_id"])

    R = pre_ids.shape[0]
    K = ids.shape[1]
    pre_bands = get_sidebands(env, pre_ids_name)
    # rows-per-source (static): the outer LoD level's *shape* gives the
    # source count even though its values are traced. Uniform widths only —
    # the full-width design keeps exactly beam_size rows per source after
    # the first step, and a direct 2-level feed must be uniform too.
    if LOD_SRC in pre_bands:
        S = int(pre_bands[LOD_SRC].shape[0]) - 1
        width = R // S
    else:
        # no outer level fed: first step (width 1) unless this is our own
        # previous full-width output
        width = B if BEAM_PARENTS in pre_bands else 1
        S = R // width  # number of source sentences (static)

    pre_score = pre_bands.get(BEAM_SCORES)
    if pre_score is None:
        pre_score = jnp.zeros((R,), scores.dtype)
    alive = pre_bands.get(BEAM_ALIVE)
    if alive is None:
        alive = jnp.ones((R,), bool)
    alive = jnp.logical_and(alive, pre_ids.reshape(-1) != end_id)

    # candidate matrix per source: width*K expansion candidates + width
    # "frozen" candidates (an ended prefix re-emits end_id at its frozen
    # score; a live prefix's frozen slot is -inf)
    exp_scores = jnp.where(alive[:, None], scores, _NEG_INF)  # [R,K]
    frozen_scores = jnp.where(alive, _NEG_INF, pre_score)  # [R]
    cand_scores = jnp.concatenate(
        [exp_scores.reshape(S, width * K), frozen_scores.reshape(S, width)], axis=1
    )  # [S, width*K + width]
    cand_ids = jnp.concatenate(
        [
            ids.reshape(S, width * K),
            jnp.full((S, width), end_id, ids.dtype),
        ],
        axis=1,
    )
    # local parent (row within source) of each candidate
    local_parent = jnp.concatenate(
        [
            jnp.repeat(jnp.arange(width, dtype=jnp.int32), K),
            jnp.arange(width, dtype=jnp.int32),
        ]
    )  # [width*K + width]

    top_scores, top_idx = lax.top_k(cand_scores, B)  # [S, B]
    sel_ids = jnp.take_along_axis(cand_ids, top_idx, axis=1)  # [S, B]
    sel_parent = (
        local_parent[top_idx] + (jnp.arange(S, dtype=jnp.int32) * width)[:, None]
    )  # [S, B] global row into R

    out_rows = S * B
    selected_ids = sel_ids.reshape(out_rows, 1)
    selected_scores = top_scores.reshape(out_rows, 1).astype(scores.dtype)
    parents = sel_parent.reshape(out_rows)
    new_alive = selected_ids.reshape(-1) != end_id

    src_offsets = jnp.arange(S + 1, dtype=jnp.int32) * B
    row_offsets = jnp.arange(out_rows + 1, dtype=jnp.int32)
    for out_name in (op.outputs["selected_ids"][0], op.outputs["selected_scores"][0]):
        set_sidebands(
            env,
            out_name,
            {
                "@LOD0": row_offsets,
                LOD_SRC: src_offsets,
                BEAM_PARENTS: parents,
                BEAM_SCORES: selected_scores.reshape(-1),
                BEAM_ALIVE: new_alive,
            },
        )
    return {"selected_ids": selected_ids, "selected_scores": selected_scores}


@register_op("beam_search_decode")
def _beam_search_decode(ctx, ins, attrs):
    """Backtrack the ids/scores TensorArrays into full sentences.

    Reference operators/beam_search_decode_op.cc walks prefix trees built
    from level-1 LoD; here parent pointers are explicit side-bands and the
    walk is a trace-time loop over the (concrete-length) array emitting one
    gather per step. Output: padded [S*beam, T] sentences + length vector,
    plus packed-LoD offsets so sequence ops can consume the result."""
    env = ctx.env
    op = ctx.op
    ids_arr: TensorArray = env[op.inputs["Ids"][0]]
    scores_arr: TensorArray = env[op.inputs["Scores"][0]]
    T = len(ids_arr) - 1  # item 0 is the init (start-token) step
    if T < 1:
        raise ValueError("beam_search_decode needs at least one search step")
    last_v, last_b = ids_arr.read(T)
    R = last_v.shape[0]  # S * beam

    row = jnp.arange(R, dtype=jnp.int32)
    toks, tok_scores, alive_flags = [], [], []
    for t in range(T, 0, -1):
        v, b = ids_arr.read(t)
        sv, _ = scores_arr.read(t)
        toks.append(v.reshape(-1)[row])
        tok_scores.append(sv.reshape(-1)[row])
        alive_flags.append(b[BEAM_ALIVE][row])
        row = b[BEAM_PARENTS][row]
    v0, _ = ids_arr.read(0)
    sv0, _ = scores_arr.read(0)
    toks.append(v0.reshape(-1)[row])
    tok_scores.append(sv0.reshape(-1)[row])
    alive_flags.append(jnp.ones((R,), bool))

    ids_mat = jnp.stack(toks[::-1], axis=1)  # [R, T+1]
    scores_mat = jnp.stack(tok_scores[::-1], axis=1)
    alive_mat = jnp.stack(alive_flags[::-1], axis=1)  # [R, T+1]

    # length = up to and including the first end token (first not-alive)
    ended = jnp.logical_not(alive_mat)
    any_end = jnp.any(ended, axis=1)
    first_end = jnp.argmax(ended, axis=1)
    lens = jnp.where(any_end, first_end + 1, T + 1).astype(jnp.int32)

    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)]
    )
    src_off = last_b.get(LOD_SRC)
    for out_name in (op.outputs["SentenceIds"][0], op.outputs["SentenceScores"][0]):
        bands = {"@LOD0": offsets, BEAM_LENS: lens}
        if src_off is not None:
            bands[LOD_SRC] = src_off
        set_sidebands(env, out_name, bands)
    outs = {"SentenceIds": ids_mat, "SentenceScores": scores_mat}
    if "SentenceLens" in op.outputs:
        outs["SentenceLens"] = lens
    return outs
