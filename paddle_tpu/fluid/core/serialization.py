"""Language-neutral Program serialization.

Replaces the reference's ProgramDesc protobuf wire format
(paddle/fluid/framework/framework.proto:34-152, written by
fluid/io.py:297 save_inference_model and read back by C++
inference::Load, paddle/fluid/inference/io.cc:108) with a stable JSON
schema: the Program IR here is a plain object graph and JSON keeps it
readable from any language — the native C++ inference runner
(native/inference.cc) parses the same file with no Python.

Schema (version 1):

    {
      "format": "paddle_tpu_program",
      "version": 1,
      "random_seed": 0,
      "amp": false,
      "shardings": {"w0": [["data"], null], ...},   # PartitionSpec per var
      "blocks": [
        {"idx": 0, "parent_idx": -1,
         "vars": [{"name", "shape", "dtype", "lod_level", "persistable",
                   "stop_gradient", "is_data", "is_parameter", "trainable"}],
         "ops":  [{"type", "inputs": {slot: [names]},
                   "outputs": {slot: [names]}, "attrs": {...}}]}
      ]
    }

Weights ride alongside as one standard .npy file per persistable
(fluid/io.py save_persistables) — also directly parseable from C.
"""

from __future__ import annotations

import json
from typing import Any, Dict

import numpy as np

from .program import Block, Operator, Parameter, Program, Variable

__all__ = [
    "program_to_dict",
    "program_from_dict",
    "dumps_program",
    "loads_program",
]

FORMAT_NAME = "paddle_tpu_program"
FORMAT_VERSION = 1


def _json_safe(v):
    """Normalise an attr value for JSON: tuples->lists, numpy scalars ->
    python scalars, numpy arrays -> nested lists."""
    if isinstance(v, (tuple, list)):
        return [_json_safe(x) for x in v]
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, (str, bool, int, float)) or v is None:
        return v
    raise TypeError(
        "op attr of type %s is not serializable: %r" % (type(v).__name__, v)
    )


def _spec_to_json(spec):
    """jax PartitionSpec -> list of entries (str axis, [str,...], or None)."""
    out = []
    for e in tuple(spec):
        if e is None or isinstance(e, str):
            out.append(e)
        else:  # tuple of axis names
            out.append(list(e))
    return out


def _spec_from_json(entries):
    from jax.sharding import PartitionSpec

    parts = []
    for e in entries:
        parts.append(tuple(e) if isinstance(e, list) else e)
    return PartitionSpec(*parts)


def program_to_dict(program: Program) -> Dict[str, Any]:
    blocks = []
    for blk in program.blocks:
        vars_out = []
        for v in blk.vars.values():
            vars_out.append(
                {
                    "name": v.name,
                    "shape": list(v.shape) if v.shape is not None else None,
                    "dtype": v.dtype,
                    "lod_level": v.lod_level,
                    "persistable": bool(v.persistable),
                    "stop_gradient": bool(v.stop_gradient),
                    "is_data": bool(getattr(v, "is_data", False)),
                    "is_parameter": isinstance(v, Parameter),
                    "trainable": bool(getattr(v, "trainable", False)),
                }
            )
        ops_out = []
        for op in blk.ops:
            ops_out.append(
                {
                    "type": op.type,
                    "inputs": {k: list(v) for k, v in op.inputs.items()},
                    "outputs": {k: list(v) for k, v in op.outputs.items()},
                    "attrs": {k: _json_safe(v) for k, v in op.attrs.items()},
                }
            )
        blocks.append(
            {
                "idx": blk.idx,
                "parent_idx": blk.parent_idx,
                "vars": vars_out,
                "ops": ops_out,
            }
        )
    return {
        "format": FORMAT_NAME,
        "version": FORMAT_VERSION,
        "random_seed": program.random_seed,
        "amp": bool(program.amp),
        "remat": bool(program.remat),
        "shardings": {
            k: _spec_to_json(v) for k, v in program.shardings.items()
        },
        "blocks": blocks,
    }


def program_from_dict(d: Dict[str, Any]) -> Program:
    if d.get("format") != FORMAT_NAME:
        raise ValueError("not a %s file" % FORMAT_NAME)
    if d.get("version", 0) > FORMAT_VERSION:
        raise ValueError(
            "program schema version %s is newer than this loader (%d)"
            % (d.get("version"), FORMAT_VERSION)
        )
    program = Program()
    program.random_seed = int(d.get("random_seed", 0))
    program.amp = bool(d.get("amp", False))
    program.remat = bool(d.get("remat", False))
    if d.get("shardings"):
        program.shardings = {
            k: _spec_from_json(v) for k, v in d["shardings"].items()
        }
    # materialise blocks first (ops may reference later blocks via
    # sub_block attrs)
    for bd in d["blocks"][1:]:
        blk = Block(program, len(program.blocks), bd["parent_idx"])
        program.blocks.append(blk)
    for bd in d["blocks"]:
        blk = program.blocks[bd["idx"]]
        blk.parent_idx = bd["parent_idx"]
        for vd in bd["vars"]:
            cls = Parameter if vd.get("is_parameter") else Variable
            kwargs = dict(
                shape=vd["shape"],
                dtype=vd["dtype"],
                lod_level=vd.get("lod_level", 0),
                persistable=vd.get("persistable", False),
                stop_gradient=vd.get("stop_gradient", False),
            )
            if cls is Parameter:
                kwargs["trainable"] = vd.get("trainable", True)
            else:
                kwargs["is_data"] = vd.get("is_data", False)
            blk.vars[vd["name"]] = cls(blk, name=vd["name"], **kwargs)
        for od in bd["ops"]:
            op = Operator(
                blk,
                type=od["type"],
                inputs=None,
                outputs=None,
                attrs=od.get("attrs") or {},
            )
            op.inputs = {k: list(v) for k, v in od["inputs"].items()}
            op.outputs = {k: list(v) for k, v in od["outputs"].items()}
            blk.ops.append(op)
    program.current_block_idx = 0
    program._bump_version()
    return program


def dumps_program(program: Program, indent=None) -> str:
    return json.dumps(program_to_dict(program), indent=indent)


def loads_program(s: str) -> Program:
    return program_from_dict(json.loads(s))
