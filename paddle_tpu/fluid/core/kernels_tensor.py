"""Tensor / data-movement op kernels: fills, random init, reshape family,
concat/split, embedding lookup, one-hot, gather/scatter.

Parity: reference operators/fill_constant_op.cc, uniform_random_op.cc,
gaussian_random_op.cc, concat_op, split_op, reshape_op, transpose_op,
lookup_table_op (the dense path of N16's sparse embedding), expand_op,
gather/scatter, sequence_mask.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_op


def _np_dtype(s):
    return jnp.dtype(s) if not isinstance(s, str) else jnp.dtype(
        {"int64": "int32"}.get(s, s)  # 64-bit ints run as 32-bit on TPU
    )


@register_op("fill_constant")
def _fill_constant(ctx, ins, attrs):
    # host-side numpy, NOT jnp: under jit every jnp call is staged into the
    # trace, but a fill_constant is a pure constant — keeping it numpy lets
    # loop counters stay concrete so While/array indices unroll at trace
    # time (kernels_control.py). As an operand of any traced op it becomes
    # an XLA constant, identical result either way.
    shape = tuple(int(s) for s in attrs["shape"])
    return {
        "Out": np.full(
            shape, attrs.get("value", 0.0), _np_dtype(attrs.get("dtype", "float32"))
        )
    }


@register_op("fill_constant_batch_size_like")
def _fill_constant_bsl(ctx, ins, attrs):
    ref = ins["Input"][0]
    shape = [int(s) for s in attrs["shape"]]
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    return {"Out": jnp.full(tuple(shape), attrs.get("value", 0.0), _np_dtype(attrs.get("dtype", "float32")))}


@register_op("fill_zeros_like")
def _fill_zeros_like(ctx, ins, attrs):
    return {"Out": jnp.zeros_like(ins["X"][0])}


@register_op("uniform_random")
def _uniform_random(ctx, ins, attrs):
    shape = tuple(int(s) for s in attrs["shape"])
    # a nonzero `seed` attr pins the draw (reference uniform_random_op
    # seed semantics); seed=0 means "use the executor's RNG stream"
    seed = int(attrs.get("seed", 0) or 0)
    key = jax.random.PRNGKey(seed) if seed else ctx.next_key()
    lo = attrs.get("min", -1.0)
    hi = attrs.get("max", 1.0)
    return {"Out": jax.random.uniform(key, shape, _np_dtype(attrs.get("dtype", "float32")), lo, hi)}


@register_op("gaussian_random")
def _gaussian_random(ctx, ins, attrs):
    shape = tuple(int(s) for s in attrs["shape"])
    key = ctx.next_key()
    dt = _np_dtype(attrs.get("dtype", "float32"))
    return {"Out": attrs.get("mean", 0.0) + attrs.get("std", 1.0) * jax.random.normal(key, shape, dt)}


@register_op("truncated_gaussian_random")
def _truncated_gaussian_random(ctx, ins, attrs):
    shape = tuple(int(s) for s in attrs["shape"])
    key = ctx.next_key()
    dt = _np_dtype(attrs.get("dtype", "float32"))
    std = attrs.get("std", 1.0)
    return {
        "Out": attrs.get("mean", 0.0)
        + std * jax.random.truncated_normal(key, -2.0, 2.0, shape, dt)
    }


@register_op("assign")
def _assign(ctx, ins, attrs):
    return {"Out": ins["X"][0]}


@register_op("assign_value")
def _assign_value(ctx, ins, attrs):
    values = np.asarray(attrs["values"], dtype=_np_dtype(attrs.get("dtype", "float32")))
    return {"Out": jnp.asarray(values.reshape(tuple(attrs["shape"])))}


@register_op("shape")
def _shape(ctx, ins, attrs):
    return {"Out": jnp.asarray(ins["Input"][0].shape, jnp.int32)}


@register_op("concat")
def _concat(ctx, ins, attrs):
    return {"Out": jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))}


@register_op("split")
def _split(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    sections = attrs.get("sections", [])
    num = attrs.get("num", 0)
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register_op("reshape")
def _reshape(ctx, ins, attrs):
    x = ins["X"][0]
    shape = [int(s) for s in attrs["shape"]]
    # reference reshape_op: 0 means "copy this dim from input", -1 infers
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    return {"Out": x.reshape(tuple(shape))}


@register_op("squeeze")
def _squeeze(ctx, ins, attrs):
    axes = attrs.get("axes", [])
    x = ins["X"][0]
    if axes:
        return {"Out": jnp.squeeze(x, axis=tuple(axes))}
    return {"Out": jnp.squeeze(x)}


@register_op("unsqueeze")
def _unsqueeze(ctx, ins, attrs):
    return {"Out": jnp.expand_dims(ins["X"][0], axis=tuple(attrs["axes"]))}


@register_op("transpose")
def _transpose(ctx, ins, attrs):
    return {"Out": jnp.transpose(ins["X"][0], axes=tuple(attrs["axis"]))}


@register_op("expand")
def _expand(ctx, ins, attrs):
    x = ins["X"][0]
    times = attrs["expand_times"]
    return {"Out": jnp.tile(x, tuple(times))}


@register_op("slice")
def _slice(ctx, ins, attrs):
    x = ins["Input"][0]
    axes = attrs["axes"]
    starts = attrs["starts"]
    ends = attrs["ends"]
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = slice(st, en)
    return {"Out": x[tuple(idx)]}


@register_op("pad")
def _pad(ctx, ins, attrs):
    x = ins["X"][0]
    paddings = attrs["paddings"]  # flat [before0, after0, before1, after1, ...]
    pads = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0))}


@register_op("crop")
def _crop(ctx, ins, attrs):
    x = ins["X"][0]
    offsets = attrs.get("offsets")
    shape = attrs.get("shape")
    if ins.get("Y"):
        shape = ins["Y"][0].shape
    # -1 keeps the full remaining extent of that axis (dynamic batch dim)
    idx = tuple(
        slice(o, None) if s == -1 else slice(o, o + s)
        for o, s in zip(offsets, shape)
    )
    return {"Out": x[idx]}


@register_op("lookup_table")
def _lookup_table(ctx, ins, attrs):
    """Embedding gather (reference operators/lookup_table_op.cc). Ids come in
    as [N, 1] int; padding_idx rows read as zeros.

    Sparse-grad sites (lowering._find_sparse_sites): the table is a trace
    constant here and the gather result instead carries the site's zero
    "delta" cotangent leaf, so the vjp produces a [n_ids, dim] gradient —
    the SelectedRows value block — rather than a dense [vocab, dim]
    cotangent (reference lookup_table_op.cc SelectedRows grad branch).
    The touched row ids are recorded in the env side-band for the
    optimizer's row-scatter update; padding positions record the
    out-of-range sentinel so they drop out of the scatter."""
    w = ins["W"][0]
    ids = ins["Ids"][0]
    flat = ids.reshape(-1).astype(jnp.int32)
    padding_idx = attrs.get("padding_idx", -1)
    has_pad = padding_idx is not None and padding_idx >= 0
    out_name = ctx.op.outputs["Out"][0]
    delta_name = ctx.sparse_sites.get(out_name)
    if delta_name is not None and delta_name in ctx.env:
        out = jnp.take(w, flat, axis=0) + ctx.env[delta_name]
        rows = (
            jnp.where(flat == padding_idx, w.shape[0], flat)
            if has_pad
            else flat
        )
        ctx.env[out_name + "@sparse_rows"] = rows
    else:
        out = jnp.take(w, flat, axis=0)
    if has_pad:
        # masking AFTER the delta add zeroes the delta cotangent at
        # padding positions too (their sentinel rows drop regardless)
        out = jnp.where((flat == padding_idx)[:, None], 0.0, out)
    out_shape = tuple(ids.shape[:-1]) + (w.shape[1],) if ids.shape[-1] == 1 else tuple(ids.shape) + (w.shape[1],)
    return {"Out": out.reshape(out_shape)}


@register_op("one_hot")
def _one_hot(ctx, ins, attrs):
    ids = ins["X"][0].reshape(-1).astype(jnp.int32)
    depth = attrs["depth"]
    return {"Out": jax.nn.one_hot(ids, depth, dtype=jnp.float32)}


@register_op("gather")
def _gather(ctx, ins, attrs):
    return {"Out": jnp.take(ins["X"][0], ins["Index"][0].reshape(-1).astype(jnp.int32), axis=0)}


@register_op("scatter")
def _scatter(ctx, ins, attrs):
    # jnp.asarray: X may be a host-side numpy constant (fill_constant),
    # and .at[] indexing exists only on jax arrays
    x = jnp.asarray(ins["X"][0])
    idx = ins["Ids"][0].reshape(-1).astype(jnp.int32)
    upd = ins["Updates"][0]
    return {"Out": x.at[idx].set(upd)}


@register_op("sequence_mask")
def _sequence_mask(ctx, ins, attrs):
    lengths = ins["X"][0].reshape(-1)
    maxlen = attrs.get("maxlen", -1)
    if maxlen is None or maxlen < 0:
        raise ValueError("sequence_mask on TPU requires a static maxlen attr")
    mask = jnp.arange(maxlen)[None, :] < lengths[:, None]
    dt = attrs.get("out_dtype", "int64")
    return {"Y": mask.astype(_np_dtype(dt))}


@register_op("range")
def _range(ctx, ins, attrs):
    return {
        "Out": jnp.arange(attrs["start"], attrs["end"], attrs.get("step", 1)).astype(
            _np_dtype(attrs.get("dtype", "int32"))
        )
    }


@register_op("multiplex")
def _multiplex(ctx, ins, attrs):
    index = ins["Ids"][0].reshape(-1).astype(jnp.int32)
    stacked = jnp.stack(ins["X"], axis=0)  # [num_candidates, N, D]
    rows = jnp.arange(stacked.shape[1])
    return {"Out": stacked[index, rows]}


@register_op("row_conv")
def _row_conv(ctx, ins, attrs):
    """Lookahead row convolution (reference operators/row_conv_op.cc); each
    step mixes `future_context` future frames of the same sequence. Accepts
    the packed LoD layout [T, D] (masking mixes at sequence boundaries via
    segment ids) or a dense [N, T, D] batch."""
    x = ins["X"][0]
    filt = ins["Filter"][0]  # [future_context+1, D]
    ctx_len = filt.shape[0]
    if x.ndim == 2:
        from .kernels_sequence import lod_key, seg_ids

        key = lod_key(ctx.op.inputs["X"][0])
        total = x.shape[0]
        if key in ctx.env:
            ids = seg_ids(ctx.env[key], total)
        else:
            ids = jnp.zeros((total,), jnp.int32)  # one long sequence
        out = jnp.zeros_like(x)
        for k in range(ctx_len):
            shifted = jnp.pad(x[k:], ((0, k), (0, 0)))
            ids_k = jnp.pad(ids[k:], (0, k), constant_values=-1)
            valid = (ids_k == ids)[:, None]
            out = out + jnp.where(valid, shifted * filt[k][None, :], 0.0)
        return {"Out": out}
    out = jnp.zeros_like(x)
    for k in range(ctx_len):
        shifted = jnp.pad(x[:, k:, :], ((0, 0), (0, k), (0, 0)))
        out = out + shifted * filt[k][None, None, :]
    return {"Out": out}


@register_op("im2sequence")
def _im2sequence(ctx, ins, attrs):
    """Reference operators/im2sequence_op.cc: sliding blocks -> rows."""
    x = ins["X"][0]  # NCHW
    kh, kw = attrs.get("kernels", [1, 1])
    sh, sw = attrs.get("strides", [1, 1])
    pads = attrs.get("paddings", [0, 0, 0, 0])
    x = jnp.pad(x, ((0, 0), (0, 0), (pads[0], pads[2]), (pads[1], pads[3])))
    n, c, h, w = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW")
    )  # [N, C*kh*kw, oh, ow]
    out = patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, c * kh * kw)
    # each image becomes one sequence of oh*ow steps (reference
    # im2sequence_op.cc sets the output LoD the same way) — downstream
    # sequence ops (warpctc, dynamic RNN) read the offsets side-band
    from .kernels_sequence import lod_key

    ctx.env[lod_key(ctx.op.outputs["Out"][0])] = jnp.arange(
        n + 1, dtype=jnp.int32
    ) * (oh * ow)
    return {"Out": out}


@register_op("sampling_id")
def _sampling_id(ctx, ins, attrs):
    """Sample one class id per row from a probability distribution
    (reference sampling_id_op.cc / SamplingIdLayer)."""
    p = ins["X"][0]  # [N, C] probabilities
    key = ctx.next_key()
    logits = jnp.log(jnp.maximum(p, 1e-20))
    ids = jax.random.categorical(key, logits, axis=-1)
    return {"Out": ids.astype(jnp.int32)}


@register_op("bilinear_interp")
def _bilinear_interp(ctx, ins, attrs):
    """Bilinear image resize (reference bilinear_interp_op.cc /
    BilinearInterpLayer) on NCHW with the reference's ALIGN-CORNERS
    ratios ((in-1)/(out-1)), not jax.image's half-pixel centers."""
    x = ins["X"][0]
    oh = int(attrs["out_h"])
    ow = int(attrs["out_w"])
    h, w = x.shape[2], x.shape[3]

    def axis_coords(out_n, in_n):
        if out_n == 1 or in_n == 1:
            return jnp.zeros((out_n,), x.dtype if x.dtype in (
                jnp.float32, jnp.float64) else jnp.float32)
        ratio = (in_n - 1) / (out_n - 1)
        return jnp.arange(out_n, dtype=jnp.float32) * ratio

    ys = axis_coords(oh, h)
    xs = axis_coords(ow, w)
    y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    wy = (ys - y0).astype(x.dtype)[None, None, :, None]
    wx = (xs - x0).astype(x.dtype)[None, None, None, :]
    top = x[:, :, y0][:, :, :, x0] * (1 - wx) + x[:, :, y0][:, :, :, x1] * wx
    bot = x[:, :, y1][:, :, :, x0] * (1 - wx) + x[:, :, y1][:, :, :, x1] * wx
    return {"Out": top * (1 - wy) + bot * wy}


@register_op("conv_shift")
def _conv_shift(ctx, ins, attrs):
    """Circular convolution (reference conv_shift_op.cc /
    ConvShiftLayer): out[i, j] = sum_k x[i, (j + k - M//2) mod N] * y[i, k]
    with x [B, N], y [B, M], M odd and M <= N."""
    x = ins["X"][0]
    y = ins["Y"][0]
    n = x.shape[1]
    m = y.shape[1]
    half = m // 2
    cols = []
    for k in range(m):
        cols.append(jnp.roll(x, shift=half - k, axis=1) * y[:, k:k + 1])
    return {"Out": sum(cols)}


@register_op("reverse")
def _reverse(ctx, ins, attrs):
    """Flip along the given axes (reference reverse_op)."""
    x = ins["X"][0]
    return {"Out": jnp.flip(x, axis=tuple(attrs["axis"]))}


@register_op("scale_sub_region")
def _scale_sub_region(ctx, ins, attrs):
    """Scale values inside a per-sample (channel, height, width) box
    (reference function/ScaleSubRegionOp.cpp + gserver
    ScaleSubRegionLayer.cpp): Indices rows are 1-based INCLUSIVE
    [c0, c1, h0, h1, w0, w1]; out = x, with x*value inside the region.
    The gradient scales identically inside the region (autodiff gets
    this for free from the jnp.where formulation)."""
    x = ins["X"][0]  # [N, C, H, W]
    idx = ins["Indices"][0].astype(jnp.int32)  # [N, 6]
    value = float(attrs.get("value", 1.0))
    N, C, H, W = x.shape
    c = jnp.arange(C)
    h = jnp.arange(H)
    w = jnp.arange(W)
    mc = (c[None, :] >= idx[:, 0:1] - 1) & (c[None, :] <= idx[:, 1:2] - 1)
    mh = (h[None, :] >= idx[:, 2:3] - 1) & (h[None, :] <= idx[:, 3:4] - 1)
    mw = (w[None, :] >= idx[:, 4:5] - 1) & (w[None, :] <= idx[:, 5:6] - 1)
    mask = (
        mc[:, :, None, None] & mh[:, None, :, None] & mw[:, None, None, :]
    )
    return {"Out": jnp.where(mask, x * value, x)}


@register_op("select")
def _select(ctx, ins, attrs):
    """Scalar-condition select: Out = X if Cond else Y (backs the Switch
    control-flow class; reference conditional_block_op semantics for the
    assign-only Switch pattern)."""
    cond = ins["Cond"][0].reshape(()).astype(bool)
    return {"Out": jnp.where(cond, ins["X"][0], ins["Y"][0])}


@register_op("is_empty")
def _is_empty(ctx, ins, attrs):
    """Out = [numel(X) == 0] (reference operators/is_empty_op.cc). Static
    under XLA: emptiness is a property of the traced shape."""
    x = ins["X"][0]
    empty = int(np.prod(x.shape)) == 0 if hasattr(x, "shape") else False
    return {"Out": jnp.asarray([empty], dtype=bool)}
