"""Build-time shape/dtype propagation.

The reference runs C++ InferShape per op at graph-build time (and again at
runtime, operator.cc:484). Here shapes only matter while *building* the
program — layer functions size parameters off their input shapes — so this
is a small symbolic propagation pass invoked from Block.append_op. -1 marks
the batch (or any unknown) dimension and flows through untouched. Runtime
shapes are XLA's business entirely.
"""

from __future__ import annotations

import numpy as np

_RULES = {}


def register_infer(op_type):
    def deco(fn):
        _RULES[op_type] = fn
        return fn

    return deco


def infer_op_shapes(op, block) -> None:
    """Set shapes of op's output vars (only where still None)."""
    fn = _RULES.get(op.type, _default_rule)
    try:
        fn(op, block)
    except Exception:
        # shape inference is best-effort: a layer that later *needs* the
        # shape will raise a clear error at that point
        pass


def _var(block, name):
    return block.var(name)


def _shape(block, name):
    return block.var(name).shape


def _set(block, name, shape, dtype=None):
    v = block.var(name)
    if v.shape is None and shape is not None:
        v.shape = tuple(int(s) for s in shape)
    if dtype is not None:
        v.dtype = dtype


def _default_rule(op, block):
    """Out mirrors X (elementwise/activation/optimizer-style ops)."""
    src = None
    for slot in ("X", "Input", "Param", "Logits"):
        if op.inputs.get(slot):
            src = op.inputs[slot][0]
            break
    if src is None:
        return
    shape = _shape(block, src)
    dtype = block.var(src).dtype
    for slot, names in op.outputs.items():
        for n in names:
            if slot in ("Out", "Y", "Output", "ParamOut", "Loss", "Softmax"):
                _set(block, n, shape, dtype)


@register_infer("mul")
def _mul(op, block):
    x = _shape(block, op.inputs["X"][0])
    y = _shape(block, op.inputs["Y"][0])
    xn = op.attrs.get("x_num_col_dims", 1)
    yn = op.attrs.get("y_num_col_dims", 1)
    _set(block, op.outputs["Out"][0], tuple(x[:xn]) + tuple(y[yn:]),
         block.var(op.inputs["X"][0]).dtype)


@register_infer("matmul")
def _matmul(op, block):
    x = list(_shape(block, op.inputs["X"][0]))
    y = list(_shape(block, op.inputs["Y"][0]))
    if op.attrs.get("transpose_X"):
        x[-1], x[-2] = x[-2], x[-1]
    if op.attrs.get("transpose_Y"):
        y[-1], y[-2] = y[-2], y[-1]
    out = list(x[:-1]) + [y[-1]]
    # leading batch dims broadcast: take the longer rank's prefix
    if len(y) > len(x):
        out = list(y[:-2]) + [x[-2], y[-1]]
    _set(block, op.outputs["Out"][0], out, block.var(op.inputs["X"][0]).dtype)


def _conv_spatial(in_size, k, s, p, d):
    if in_size == -1:
        return -1
    return (in_size + 2 * p - (d * (k - 1) + 1)) // s + 1


@register_infer("conv2d")
def _conv2d(op, block):
    x = _shape(block, op.inputs["Input"][0])
    w = _shape(block, op.inputs["Filter"][0])
    s = op.attrs.get("strides", [1, 1])
    p = op.attrs.get("paddings", [0, 0])
    d = op.attrs.get("dilations", [1, 1])
    out = (
        x[0],
        w[0],
        _conv_spatial(x[2], w[2], s[0], p[0], d[0]),
        _conv_spatial(x[3], w[3], s[1], p[1], d[1]),
    )
    _set(block, op.outputs["Output"][0], out, block.var(op.inputs["Input"][0]).dtype)


register_infer("depthwise_conv2d")(_conv2d)


@register_infer("conv2d_transpose")
def _conv2d_t(op, block):
    x = _shape(block, op.inputs["Input"][0])
    w = _shape(block, op.inputs["Filter"][0])  # IOHW
    s = op.attrs.get("strides", [1, 1])
    p = op.attrs.get("paddings", [0, 0])
    d = op.attrs.get("dilations", [1, 1])
    def up(i, k, st, pd, dl):
        if i == -1:
            return -1
        return (i - 1) * st - 2 * pd + dl * (k - 1) + 1
    out = (x[0], w[1], up(x[2], w[2], s[0], p[0], d[0]), up(x[3], w[3], s[1], p[1], d[1]))
    _set(block, op.outputs["Output"][0], out, block.var(op.inputs["Input"][0]).dtype)


@register_infer("pool2d")
def _pool2d(op, block):
    x = _shape(block, op.inputs["X"][0])
    if op.attrs.get("global_pooling"):
        out = (x[0], x[1], 1, 1)
    else:
        k = op.attrs["ksize"]
        s = op.attrs.get("strides", [1, 1])
        p = op.attrs.get("paddings", [0, 0])

        def _sz(i, kk, ss, pp):
            if i == -1:
                return -1
            if op.attrs.get("ceil_mode"):
                return -(-(i + 2 * pp - kk) // ss) + 1
            return (i + 2 * pp - kk) // ss + 1

        out = (x[0], x[1], _sz(x[2], k[0], s[0], p[0]), _sz(x[3], k[1], s[1], p[1]))
    _set(block, op.outputs["Out"][0], out, block.var(op.inputs["X"][0]).dtype)


@register_infer("reshape")
def _reshape(op, block):
    x = _shape(block, op.inputs["X"][0])
    shape = [int(s) for s in op.attrs["shape"]]
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x[i]
    if -1 in shape and -1 not in x and shape.count(-1) == 1:
        known = int(np.prod([s for s in shape if s != -1]))
        total = int(np.prod(x))
        if known > 0 and total > 0 and total % known == 0:
            shape[shape.index(-1)] = total // known
    _set(block, op.outputs["Out"][0], shape, block.var(op.inputs["X"][0]).dtype)


@register_infer("transpose")
def _transpose(op, block):
    x = _shape(block, op.inputs["X"][0])
    perm = op.attrs["axis"]
    _set(block, op.outputs["Out"][0], [x[i] for i in perm],
         block.var(op.inputs["X"][0]).dtype)


@register_infer("concat")
def _concat(op, block):
    xs = [_shape(block, n) for n in op.inputs["X"]]
    axis = op.attrs.get("axis", 0)
    out = list(xs[0])
    if all(x[axis] != -1 for x in xs):
        out[axis] = sum(x[axis] for x in xs)
    else:
        out[axis] = -1
    _set(block, op.outputs["Out"][0], out, block.var(op.inputs["X"][0]).dtype)


@register_infer("split")
def _split(op, block):
    x = _shape(block, op.inputs["X"][0])
    axis = op.attrs.get("axis", -1)
    sections = op.attrs.get("sections") or []
    num = op.attrs.get("num", 0)
    outs = op.outputs["Out"]
    dtype = block.var(op.inputs["X"][0]).dtype
    if sections:
        for n, s in zip(outs, sections):
            shp = list(x)
            shp[axis] = s
            _set(block, n, shp, dtype)
    else:
        for n in outs:
            shp = list(x)
            shp[axis] = x[axis] // num if x[axis] != -1 else -1
            _set(block, n, shp, dtype)


@register_infer("lookup_table")
def _lookup_table(op, block):
    ids = _shape(block, op.inputs["Ids"][0])
    w = _shape(block, op.inputs["W"][0])
    if ids[-1] == 1:
        out = tuple(ids[:-1]) + (w[1],)
    else:
        out = tuple(ids) + (w[1],)
    _set(block, op.outputs["Out"][0], out, block.var(op.inputs["W"][0]).dtype)


@register_infer("cross_entropy")
def _cross_entropy(op, block):
    x = _shape(block, op.inputs["X"][0])
    _set(block, op.outputs["Y"][0], (x[0], 1), block.var(op.inputs["X"][0]).dtype)


@register_infer("softmax_with_cross_entropy")
def _swce(op, block):
    x = _shape(block, op.inputs["Logits"][0])
    dtype = block.var(op.inputs["Logits"][0]).dtype
    _set(block, op.outputs["Loss"][0], (x[0], 1), dtype)
    _set(block, op.outputs["Softmax"][0], x, dtype)


@register_infer("mean")
def _mean(op, block):
    _set(block, op.outputs["Out"][0], (1,), block.var(op.inputs["X"][0]).dtype)


@register_infer("squared_l2_norm")
def _sq_l2(op, block):
    _set(block, op.outputs["Out"][0], (1,), block.var(op.inputs["X"][0]).dtype)


def _reduce_rule(op, block):
    x = _shape(block, op.inputs["X"][0])
    if op.attrs.get("reduce_all"):
        out = (1,) * len(x) if op.attrs.get("keep_dim") else (1,)
    else:
        dim = op.attrs.get("dim", 0)
        dims = tuple(dim) if isinstance(dim, (list, tuple)) else (dim,)
        dims = tuple(d % len(x) for d in dims)
        if op.attrs.get("keep_dim"):
            out = tuple(1 if i in dims else s for i, s in enumerate(x))
        else:
            out = tuple(s for i, s in enumerate(x) if i not in dims)
    _set(block, op.outputs["Out"][0], out, block.var(op.inputs["X"][0]).dtype)


for _t in ("reduce_sum", "reduce_mean", "reduce_max", "reduce_min", "reduce_prod"):
    register_infer(_t)(_reduce_rule)


@register_infer("top_k")
def _top_k(op, block):
    x = _shape(block, op.inputs["X"][0])
    k = op.attrs.get("k", 1)
    out = tuple(x[:-1]) + (k,)
    _set(block, op.outputs["Out"][0], out, block.var(op.inputs["X"][0]).dtype)
    _set(block, op.outputs["Indices"][0], out, "int64")


@register_infer("accuracy")
def _accuracy(op, block):
    _set(block, op.outputs["Accuracy"][0], (1,), "float32")
    _set(block, op.outputs["Correct"][0], (1,), "int64")
    _set(block, op.outputs["Total"][0], (1,), "int64")


@register_infer("fill_constant")
def _fill_constant(op, block):
    _set(block, op.outputs["Out"][0], op.attrs["shape"],
         op.attrs.get("dtype", "float32"))


register_infer("uniform_random")(_fill_constant)
register_infer("gaussian_random")(_fill_constant)
register_infer("truncated_gaussian_random")(_fill_constant)
register_infer("assign_value")(_fill_constant)


@register_infer("fill_constant_batch_size_like")
def _fill_bsl(op, block):
    ref = _shape(block, op.inputs["Input"][0])
    shape = [int(s) for s in op.attrs["shape"]]
    shape[op.attrs.get("output_dim_idx", 0)] = ref[op.attrs.get("input_dim_idx", 0)]
    _set(block, op.outputs["Out"][0], shape, op.attrs.get("dtype", "float32"))


@register_infer("cast")
def _cast(op, block):
    _set(block, op.outputs["Out"][0], _shape(block, op.inputs["X"][0]),
         op.attrs["out_dtype"])


@register_infer("one_hot")
def _one_hot(op, block):
    x = _shape(block, op.inputs["X"][0])
    _set(block, op.outputs["Out"][0], (x[0], op.attrs["depth"]), "float32")


@register_infer("sequence_pool")
def _sequence_pool(op, block):
    x = _shape(block, op.inputs["X"][0])
    # packed [T, D] -> [batch, D]; batch unknown at build time
    _set(block, op.outputs["Out"][0], (-1,) + tuple(x[1:]),
         block.var(op.inputs["X"][0]).dtype)


@register_infer("sequence_expand")
def _sequence_expand(op, block):
    x = _shape(block, op.inputs["X"][0])
    _set(block, op.outputs["Out"][0], (-1,) + tuple(x[1:]),
         block.var(op.inputs["X"][0]).dtype)


@register_infer("im2sequence")
def _im2sequence(op, block):
    x = _shape(block, op.inputs["X"][0])
    kh, kw = op.attrs["kernels"]
    _set(block, op.outputs["Out"][0], (-1, x[1] * kh * kw),
         block.var(op.inputs["X"][0]).dtype)


@register_infer("maxout")
def _maxout(op, block):
    x = _shape(block, op.inputs["X"][0])
    g = op.attrs["groups"]
    _set(block, op.outputs["Out"][0], (x[0], x[1] // g, x[2], x[3]),
         block.var(op.inputs["X"][0]).dtype)


@register_infer("expand")
def _expand(op, block):
    x = _shape(block, op.inputs["X"][0])
    times = op.attrs["expand_times"]
    out = tuple(-1 if s == -1 else s * t for s, t in zip(x, times))
    _set(block, op.outputs["Out"][0], out, block.var(op.inputs["X"][0]).dtype)


@register_infer("gather")
def _gather(op, block):
    x = _shape(block, op.inputs["X"][0])
    idx = _shape(block, op.inputs["Index"][0])
    _set(block, op.outputs["Out"][0], (idx[0],) + tuple(x[1:]),
         block.var(op.inputs["X"][0]).dtype)


@register_infer("autodiff")
def _autodiff(op, block):
    pass  # grad var shapes were set by append_backward


def _compare_rule(op, block):
    x = _shape(block, op.inputs["X"][0])
    _set(block, op.outputs["Out"][0], x, "bool")


for _t in ("less_than", "less_equal", "greater_than", "greater_equal",
           "equal", "not_equal"):
    register_infer(_t)(_compare_rule)


def _noop_rule(op, block):
    pass


# control-flow ops manage their own vars; the default mirror rule would
# clobber e.g. a bool condition's dtype
for _t in ("while", "dynamic_rnn", "array_length", "beam_search_decode"):
    register_infer(_t)(_noop_rule)


@register_infer("array_write")
def _array_write_rule(op, block):
    # remember the element shape on the array var so array_read can
    # propagate it (build-time only; values live in the trace env)
    x = block.var(op.inputs["X"][0])
    arr = block.var(op.outputs["Out"][0])
    if getattr(arr, "elem_shape", None) is None and x.shape is not None:
        arr.elem_shape = (-1,) + tuple(x.shape[1:])
        arr.dtype = x.dtype


@register_infer("array_read")
def _array_read_rule(op, block):
    arr = block.var(op.inputs["X"][0])
    shape = getattr(arr, "elem_shape", None)
    if shape is not None:
        _set(block, op.outputs["Out"][0], shape, arr.dtype)


@register_infer("beam_search")
def _beam_search_rule(op, block):
    _set(block, op.outputs["selected_ids"][0], (-1, 1), "int64")
    _set(block, op.outputs["selected_scores"][0], (-1, 1), "float32")
