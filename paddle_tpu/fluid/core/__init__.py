"""Core of the TPU-native framework: IR, op kernels, lowering.

The C++ core of the reference (paddle/fluid/framework + operators) maps
here to: program.py (IR object model), registry.py + kernels_*.py (op set
as JAX-traceable kernels), lowering.py (block -> single fused XLA
computation). Device placement is a non-concept: XLA owns the chip.
"""

from . import program as _program
from .program import (
    Block,
    Operator,
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    grad_var_name,
    program_guard,
    switch_main_program,
    switch_startup_program,
    unique_name,
)
from .registry import LoweringContext, get_kernel, has_kernel, register_op, registered_ops

# importing the kernel modules populates the registry
from . import kernels_math  # noqa: F401
from . import kernels_nn  # noqa: F401
from . import kernels_tensor  # noqa: F401
from . import kernels_optim  # noqa: F401
from . import kernels_sequence  # noqa: F401
from . import kernels_rnn  # noqa: F401
from . import kernels_control  # noqa: F401
from . import kernels_crf  # noqa: F401
from . import kernels_ctc  # noqa: F401
from . import kernels_sampled  # noqa: F401
from . import kernels_detection  # noqa: F401
from .lowering import AUTODIFF_OP, build_step_fn, lower_block


class CPUPlace(object):
    """Device placement is vestigial on TPU (XLA owns placement); Place
    classes exist for API parity with reference platform/place.h:53."""

    def __repr__(self):
        return "CPUPlace"

    def __eq__(self, other):
        return type(self) is type(other)

    def __hash__(self):
        return hash(type(self))


class TPUPlace(CPUPlace):
    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def __repr__(self):
        return "TPUPlace(%d)" % self.device_id


class CUDAPlace(TPUPlace):
    """Alias kept so reference scripts that say CUDAPlace(0) run unchanged;
    on this framework it means 'the accelerator', i.e. the TPU chip."""

    def __repr__(self):
        return "CUDAPlace(%d)->TPU" % self.device_id
