"""Sequence (LoD / ragged) op kernels.

The reference's no-padding LoD design (framework/lod_tensor.h, legacy
Argument.h:84 sequenceStartPositions) is re-expressed TPU-first: a ragged
batch is a packed `[total_tokens, ...]` array plus an int32 offsets vector
of static shape `[batch+1]` stored in the env under `<name>@LOD0`. Offset
*values* are traced (dynamic), only the packed length is a static shape —
so sequence ops lower to XLA segment reductions (`jax.ops.segment_*`) with
`num_segments = batch` static, and a fresh compile happens only per packed-
length bucket, not per batch composition.

Parity: operators/sequence_pool_op, sequence_softmax_op,
sequence_expand_op, sequence_slice_op, sequence_concat, lod_reset,
sequence_reshape, sequence_conv (via the conv path), sequence_erase.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op

LOD_SUFFIX = "@LOD0"


def bucket_pow2(m: int, floor: int = 8) -> int:
    """Smallest power-of-two >= m (min `floor`) — the static sequence
    bucket the Executor applies to FED LoD max lengths so XLA compiles
    once per bucket, not per batch. (Trace-time-constant LoDs skip the
    bucket and use their exact max — kernels_rnn._seq_T.)"""
    b = floor
    while b < m:
        b *= 2
    return b


def lod_key(name: str) -> str:
    return name + LOD_SUFFIX


def _offsets(ctx, slot="X", idx=0):
    name = ctx.op.inputs[slot][idx]
    key = lod_key(name)
    if key not in ctx.env:
        raise ValueError(
            "op %r input %r has no LoD offsets in scope; feed it as a "
            "(data, lod) pair or via create_lod_tensor" % (ctx.op.type, name)
        )
    return ctx.env[key]


def _set_lod(ctx, slot, offsets, idx=0):
    ctx.env[lod_key(ctx.op.outputs[slot][idx])] = offsets


def seg_ids(offsets, total: int):
    """Map packed positions -> sequence index. offsets: [N+1] int32."""
    pos = jnp.arange(total, dtype=offsets.dtype)
    return jnp.searchsorted(offsets, pos, side="right") - 1


def seg_lengths(offsets):
    return offsets[1:] - offsets[:-1]


@register_op("sequence_pool")
def _sequence_pool(ctx, ins, attrs):
    x = ins["X"][0]
    offsets = _offsets(ctx)
    n = offsets.shape[0] - 1
    ptype = attrs.get("pooltype", attrs.get("pool_type", "AVERAGE")).upper()
    ids = seg_ids(offsets, x.shape[0])
    if ptype == "SUM":
        out = jax.ops.segment_sum(x, ids, num_segments=n)
    elif ptype == "AVERAGE":
        s = jax.ops.segment_sum(x, ids, num_segments=n)
        cnt = seg_lengths(offsets).astype(x.dtype).reshape((-1,) + (1,) * (x.ndim - 1))
        out = s / jnp.maximum(cnt, 1)
    elif ptype == "SQRT":
        s = jax.ops.segment_sum(x, ids, num_segments=n)
        cnt = seg_lengths(offsets).astype(x.dtype).reshape((-1,) + (1,) * (x.ndim - 1))
        out = s / jnp.sqrt(jnp.maximum(cnt, 1))
    elif ptype == "MAX":
        out = jax.ops.segment_max(x, ids, num_segments=n)
        empty = (seg_lengths(offsets) == 0).reshape((-1,) + (1,) * (x.ndim - 1))
        out = jnp.where(empty, 0.0, out)
    elif ptype == "FIRST":
        out = x[offsets[:-1]]
    elif ptype == "LAST":
        out = x[jnp.maximum(offsets[1:] - 1, 0)]
    else:
        raise ValueError("unknown pooltype %r" % ptype)
    return {"Out": out, "MaxIndex": jnp.zeros((n,), jnp.int32)}


@register_op("sequence_softmax")
def _sequence_softmax(ctx, ins, attrs):
    x = ins["X"][0]  # [T] or [T, 1]
    offsets = _offsets(ctx)
    n = offsets.shape[0] - 1
    flat = x.reshape(-1)
    ids = seg_ids(offsets, flat.shape[0])
    mx = jax.ops.segment_max(flat, ids, num_segments=n)
    e = jnp.exp(flat - mx[ids])
    denom = jax.ops.segment_sum(e, ids, num_segments=n)
    out = (e / denom[ids]).reshape(x.shape)
    _set_lod(ctx, "Out", offsets)
    return {"Out": out}


@register_op("sequence_expand")
def _sequence_expand(ctx, ins, attrs):
    """Repeat each row/sequence of X according to Y's LoD
    (operators/sequence_expand_op.cc)."""
    x = ins["X"][0]
    # beam-search states: Y carries explicit parent pointers (see
    # kernels_control.py) — each Y row gets its parent's X row
    pkey = ctx.op.inputs["Y"][0] + "@BEAM_PARENTS"
    if pkey in ctx.env:
        parents = ctx.env[pkey]
        out = x[parents]
        _set_lod(ctx, "Out", ctx.env[lod_key(ctx.op.inputs["Y"][0])])
        return {"Out": out}
    y_offsets = _offsets(ctx, "Y")
    y = ins["Y"][0]
    ids = seg_ids(y_offsets, y.shape[0])
    x_key = lod_key(ctx.op.inputs["X"][0])
    if x_key in ctx.env:
        # lod-level-1 X: expand whole sequences — round-1 supports the
        # common row-wise case where each X sequence has length 1
        x_offsets = ctx.env[x_key]
        x = x[x_offsets[:-1]]
    out = x[ids]
    _set_lod(ctx, "Out", y_offsets)
    return {"Out": out}


@register_op("sequence_concat")
def _sequence_concat(ctx, ins, attrs):
    # concat along feature axis with identical lod (common usage)
    xs = ins["X"]
    offsets = _offsets(ctx)
    _set_lod(ctx, "Out", offsets)
    return {"Out": jnp.concatenate(xs, axis=-1)}


@register_op("lod_reset")
def _lod_reset(ctx, ins, attrs):
    x = ins["X"][0]
    if ins.get("Y"):
        y_name = ctx.op.inputs["Y"][0]
        ykey = lod_key(y_name)
        if ykey in ctx.env:
            _set_lod(ctx, "Out", ctx.env[ykey])
        else:
            _set_lod(ctx, "Out", ctx.env[y_name].astype(jnp.int32))
    else:
        tgt = attrs.get("target_lod")
        _set_lod(ctx, "Out", jnp.asarray(tgt, jnp.int32))
    return {"Out": x}


@register_op("sequence_reshape")
def _sequence_reshape(ctx, ins, attrs):
    x = ins["X"][0]
    new_dim = attrs["new_dim"]
    offsets = _offsets(ctx)
    out = x.reshape(-1, new_dim)
    scale = x.shape[1] // new_dim if new_dim <= x.shape[1] else None
    if scale:
        new_off = offsets * scale
    else:
        new_off = offsets * x.shape[1] // new_dim
    _set_lod(ctx, "Out", new_off.astype(jnp.int32))
    return {"Out": out}


@register_op("sequence_slice")
def _sequence_slice(ctx, ins, attrs):
    """Per-sequence subrange (operators/sequence_slice_op): keep rows
    [offset_i, offset_i + length_i) of each sequence. TPU-first layout
    like sequence_erase: kept rows compact to the front of the
    static-size buffer, traced output offsets describe the new ragged
    layout, the tail is zeros."""
    x = ins["X"][0]
    off = ins["Offset"][0].reshape(-1)
    length = ins["Length"][0].reshape(-1)
    offsets = _offsets(ctx)
    total = x.shape[0]
    s = seg_ids(offsets, total)
    rel = jnp.arange(total, dtype=offsets.dtype) - offsets[s]
    kept = (rel >= off[s]) & (rel < off[s] + length[s])
    pos = jnp.cumsum(kept.astype(jnp.int32)) - 1
    dest = jnp.where(kept, pos, total)  # dropped -> spill slot
    out = (
        jnp.zeros((total + 1,) + x.shape[1:], x.dtype)
        .at[dest].set(x)[:total]
    )
    new_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(length.astype(jnp.int32))]
    )
    _set_lod(ctx, "Out", new_offsets)
    return {"Out": out}


@register_op("sequence_erase")
def _sequence_erase(ctx, ins, attrs):
    """Remove listed token values (operators/sequence_erase_op). The output
    is data-dependent-length; TPU-first representation: the packed buffer
    keeps its static size, kept tokens are compacted to the front in order,
    and the (traced) output offsets describe the new ragged layout —
    consumers read only up to the offsets, the tail is garbage."""
    x = ins["X"][0]
    offsets = _offsets(ctx)
    flat = x.reshape(-1)
    total = flat.shape[0]
    kept = jnp.ones((total,), bool)
    for tok in attrs.get("tokens", []):
        kept = jnp.logical_and(kept, flat != tok)
    # global stable compaction: sequences stay in order, so per-sequence
    # contiguity is preserved automatically
    pos = jnp.cumsum(kept.astype(jnp.int32)) - 1
    dest = jnp.where(kept, pos, total)  # removed -> spill slot
    out = jnp.zeros((total + 1,), flat.dtype).at[dest].set(flat)[:total]
    n = offsets.shape[0] - 1
    ids = seg_ids(offsets, total)
    kept_per_seq = jax.ops.segment_sum(
        kept.astype(jnp.int32), ids, num_segments=n
    )
    new_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(kept_per_seq, dtype=jnp.int32)]
    )
    _set_lod(ctx, "Out", new_offsets)
    return {"Out": out.reshape((total,) + tuple(x.shape[1:]))}


@register_op("sequence_reverse")
def _sequence_reverse(ctx, ins, attrs):
    """Per-sequence time reversal: row p of sequence i moves to
    offsets[i] + (offsets[i+1]-1-p). Used to lower reverse recurrent
    groups (reference RecurrentLayer/RecurrentGradientMachine
    reversed_=true walk the sequence backward; here: reverse -> forward
    scan -> reverse, one gather each way)."""
    x = ins["X"][0]
    offsets = _offsets(ctx)
    total = x.shape[0]
    pos = jnp.arange(total, dtype=offsets.dtype)
    ids = seg_ids(offsets, total)
    perm = offsets[ids] + (offsets[ids + 1] - 1 - pos)
    _set_lod(ctx, "Out", offsets)
    return {"Out": x[perm]}


@register_op("sequence_context")
def _sequence_context(ctx, ins, attrs):
    """Context-window concatenation WITHOUT weights (reference
    ContextProjection, gserver/layers/ContextProjection.cpp): row t of the
    output is [x[t+cs], ..., x[t+cs+cl-1]] with zeros beyond the sequence
    bounds — the gather half of sequence_conv."""
    x = ins["X"][0]  # [total, D]
    offsets = ctx.env[lod_key(ctx.op.inputs["X"][0])]
    total = x.shape[0]
    cl = int(attrs["context_length"])
    cs = int(attrs.get("context_start", -(cl // 2)))
    s = seg_ids(offsets, total)
    pos = jnp.arange(total, dtype=offsets.dtype)
    cols = []
    for j in range(cl):
        src = pos + cs + j
        valid = (src >= offsets[s]) & (src < offsets[s + 1])
        src_c = jnp.clip(src, 0, total - 1)
        cols.append(jnp.where(valid[:, None], x[src_c], 0.0))
    return {"Out": jnp.concatenate(cols, axis=1)}


@register_op("kmax_seq_score")
def _kmax_seq_score(ctx, ins, attrs):
    """Per-sequence top-k score indices (reference gserver
    KmaxSeqScoreLayer.cpp): scores are a width-1 sequence; the output row
    for each sequence holds the WITHIN-sequence indices of its beam_size
    highest scores, -1 padded where the sequence is shorter.

    TPU-first: one masked top_k over the packed vector per sequence
    (full static length), no host loop over sequences.
    """
    x = ins["X"][0].reshape(-1)  # [total]
    offsets = _offsets(ctx)
    total = x.shape[0]
    n = offsets.shape[0] - 1
    k = int(attrs.get("beam_size", 1))
    ids = seg_ids(offsets, total)

    def one_seq(i):
        masked = jnp.where(ids == i, x, -jnp.inf)
        top_s, top_i = jax.lax.top_k(masked, min(k, total))
        rel = top_i.astype(jnp.int32) - offsets[i]
        rel = jnp.where(jnp.isfinite(top_s), rel, -1)
        if k > total:  # more slots than tokens exist at all
            rel = jnp.pad(rel, (0, k - total), constant_values=-1)
        return rel

    out = jax.vmap(one_seq)(jnp.arange(n))
    return {"Out": out}


@register_op("sub_nested_seq")
def _sub_nested_seq(ctx, ins, attrs):
    """Select sub-sequences out of a nested (2-level LoD) sequence
    (reference gserver SubNestedSequenceLayer.cpp): input X is a nested
    sequence, `selected_indices` [N, S] gives per outer sequence the
    (within-sequence) sub-sequence indices to keep, -1 padded.

    Static-shape re-design: the output always has N*S sequences — slot
    (i, j) is sub-sequence selected_indices[i, j] of sequence i, or an
    EMPTY sequence for -1 entries; tokens are compacted to the front of a
    buffer the same packed length as X (tail rows beyond the new total
    are dead and never addressed through the LoD).
    """
    x = ins["X"][0]  # [total, D]
    sel = ins["S"][0].astype(jnp.int32)  # [N, S]
    name = ctx.op.inputs["X"][0]
    tok_off = ctx.env[lod_key(name)]  # [M+1] token offsets per sub-seq
    from .kernels_control import LOD_SRC

    outer = ctx.env.get(name + LOD_SRC)
    if outer is None:
        raise ValueError(
            "sub_nested_seq input %r is not a nested sequence (feed it "
            "with a 2-level LoD)" % name
        )
    outer = outer.astype(jnp.int32)  # [N+1] sub-seq slots per sequence
    total = x.shape[0]
    M = tok_off.shape[0] - 1  # number of sub-sequences
    N, S = sel.shape

    valid = sel >= 0
    g = jnp.clip(outer[:-1, None] + sel, 0, M - 1)  # [N,S] global sub-seq id
    # guard: a selected index past the sequence's own sub-seq count is -1
    valid &= (outer[:-1, None] + sel) < outer[1:, None]
    lengths = jnp.where(valid, tok_off[g + 1] - tok_off[g], 0)  # [N,S]
    flat_len = lengths.reshape(-1)
    new_off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(flat_len, dtype=jnp.int32)]
    )  # [N*S+1]
    # token gather: output position p in slot (i,j) reads
    # x[tok_off[g[i,j]] + (p - new_off[slot])]
    pos = jnp.arange(total, dtype=jnp.int32)
    slot = jnp.searchsorted(new_off, pos, side="right") - 1
    slot_c = jnp.clip(slot, 0, N * S - 1)
    src = tok_off[g.reshape(-1)[slot_c]] + (pos - new_off[slot_c])
    live = pos < new_off[-1]
    out = jnp.where(
        live.reshape((-1,) + (1,) * (x.ndim - 1)),
        x[jnp.clip(src, 0, total - 1)],
        0.0,
    )
    _set_lod(ctx, "Out", new_off)
    return {"Out": out}


@register_op("lambda_rank")
def _lambda_rank(ctx, ins, attrs):
    """LambdaRank listwise cost (reference gserver CostLayer.cpp
    LambdaCost::forward/calcGrad/calcNDCG): forward emits each sequence's
    NDCG@K (computed from the MODEL-score ranking) broadcast over the
    sequence's rows; the backward pass is the classic lambda gradient —
    for every in-sequence pair, |deltaDCG| (from the LABEL-score ranking)
    times the logistic factor on the model-score difference, normalised
    by maxDCG. maxSortSize=-1 semantics (full sort) only.

    TPU-first: ranks come from pairwise comparison matrices masked to
    same-sequence pairs — one [T, T] computation, no per-sequence loop.
    """
    out_score = ins["X"][0].reshape(-1)  # model scores, packed [T]
    label = ins["Score"][0].reshape(-1)  # relevance labels, packed [T]
    offsets = _offsets(ctx)
    K = int(attrs.get("NDCG_num", 5))
    total = out_score.shape[0]
    n = offsets.shape[0] - 1
    ids = seg_ids(offsets, total)
    same = ids[:, None] == ids[None, :]  # [T, T]
    pos = jnp.arange(total)

    def _rank(v):
        """0-based rank of each token within its sequence, descending v
        (ties by position, matching std::sort on (value, index) pairs)."""
        gt = (v[None, :] > v[:, None]) | (
            (v[None, :] == v[:, None]) & (pos[None, :] < pos[:, None])
        )
        return jnp.sum(same & gt, axis=1)

    gain = jnp.exp2(label) - 1.0
    inv_log = lambda r: 1.0 / jnp.log(r.astype(jnp.float32) + 2.0)

    rank_lbl = _rank(label)
    max_dcg = jax.ops.segment_sum(
        jnp.where(rank_lbl < K, gain * inv_log(rank_lbl), 0.0),
        ids, num_segments=n,
    )
    max_dcg = jnp.maximum(max_dcg, 1e-12)

    @jax.custom_vjp
    def _cost(s):
        rank_out = _rank(s)
        dcg = jax.ops.segment_sum(
            jnp.where(rank_out < K, gain * inv_log(rank_out), 0.0),
            ids, num_segments=n,
        )
        return (dcg / max_dcg)[ids][:, None]  # [T, 1]

    def _fwd(s):
        return _cost(s), s

    def _bwd(s, gbar):
        ra = rank_lbl[:, None]
        rb = rank_lbl[None, :]
        upper = same & (ra < rb)  # pair (a, b) with a ranked above b
        dcg_dif = (jnp.exp2(label)[:, None] - jnp.exp2(label)[None, :]) * (
            inv_log(ra) - inv_log(rb)
        )
        lam = -jnp.abs(dcg_dif) / (1.0 + jnp.exp(s[:, None] - s[None, :]))
        lam = jnp.where(upper, lam / max_dcg[ids][:, None], 0.0)
        g = jnp.sum(lam, axis=1) - jnp.sum(lam, axis=0)
        return (g * gbar.reshape(-1),)

    _cost.defvjp(_fwd, _bwd)
    return {"Out": _cost(out_score)}


@register_op("cross_entropy_over_beam")
def _cross_entropy_over_beam(ctx, ins, attrs):
    """Cross-entropy over beam expansions (reference gserver
    CrossEntropyOverBeam.cpp, DSL layers.py cross_entropy_over_beam).
    Each expansion e contributes, per outer sequence i, a globally
    normalised term  logsumexp(scores_e over i's candidates) -
    score_e[gold_i]; expansions are summed into a [N, 1] cost.

    Simplification vs the reference (documented divergence): the
    reference drops expansions after the step where gold falls off the
    beam (CrossEntropyOverBeam.h CostForOneSequence); here every
    expansion is counted — equivalent whenever gold stays on the beam,
    which the trimming layers (kmax_seq_score/sub_nested_seq/
    sequence_slice) are designed to ensure during training.
    """
    scores_list = ins["Scores"]
    gold_list = ins["Gold"]
    total_cost = None
    for k, (s, g) in enumerate(zip(scores_list, gold_list)):
        s = s.reshape(-1)
        name = ctx.op.inputs["Scores"][k]
        offsets = ctx.env[lod_key(name)]
        n = offsets.shape[0] - 1
        ids = seg_ids(offsets, s.shape[0])
        m = jax.ops.segment_max(s, ids, num_segments=n)
        lse = m + jnp.log(
            jax.ops.segment_sum(jnp.exp(s - m[ids]), ids, num_segments=n)
        )
        gold_pos = offsets[:-1] + g.reshape(-1).astype(jnp.int32)
        ce = lse - s[jnp.clip(gold_pos, 0, s.shape[0] - 1)]
        total_cost = ce if total_cost is None else total_cost + ce
    return {"Out": total_cost[:, None]}


# ---------------------------------------------------------------------------
# LoD plumbing layer ops (reference layers/control_flow.py lod_rank_table,
# max_sequence_len, reorder_lod_tensor_by_rank, split/merge_lod_tensor —
# the building blocks of the reference's while-op DynamicRNN and IfElse).
# Our DynamicRNN lowers to lax.scan instead, but the ops stand alone as
# user-visible surface with the same semantics on the packed+offsets
# ragged representation.
# ---------------------------------------------------------------------------


@register_op("lod_rank_table")
def _lod_rank_table(ctx, ins, attrs):
    """Sequences sorted by length descending (stable): out rows are
    [original_index, length] (reference lod_rank_table.h RankTable)."""
    offsets = _offsets(ctx)
    lengths = seg_lengths(offsets)
    n = lengths.shape[0]
    # stable descending sort: key = (-length, index)
    order = jnp.lexsort((jnp.arange(n), -lengths))
    table = jnp.stack(
        [order.astype(jnp.int32), lengths[order].astype(jnp.int32)], axis=1
    )
    return {"Out": table}


@register_op("max_sequence_len")
def _max_sequence_len(ctx, ins, attrs):
    table = ins["RankTable"][0]
    return {"Out": jnp.max(table[:, 1]).reshape((1,)).astype(jnp.int64)}


@register_op("reorder_lod_tensor_by_rank")
def _reorder_lod_tensor_by_rank(ctx, ins, attrs):
    """Reorder X's sequences into the rank table's order (reference
    reorder_lod_tensor_by_rank_op.cc): compaction gather on the packed
    buffer, new offsets from the permuted lengths."""
    x = ins["X"][0]
    table = ins["RankTable"][0]
    offsets = _offsets(ctx)
    total = x.shape[0]
    order = table[:, 0]
    lengths = table[:, 1]
    new_off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(lengths, dtype=jnp.int32)]
    )
    pos = jnp.arange(total, dtype=jnp.int32)
    slot = jnp.searchsorted(new_off, pos, side="right") - 1
    slot = jnp.clip(slot, 0, order.shape[0] - 1)
    src = offsets[order[slot]] + (pos - new_off[slot])
    out = x[jnp.clip(src, 0, total - 1)]
    _set_lod(ctx, "Out", new_off)
    return {"Out": out}


@register_op("split_lod_tensor")
def _split_lod_tensor(ctx, ins, attrs):
    """Route rows by boolean mask into two full-size buffers with valid
    counts (reference split_lod_tensor_op.cc; the IfElse scatter half).
    Row order within each branch preserves input order; tail rows beyond
    each branch's count are zeros, addressed only through the LoD."""
    x = ins["X"][0]
    mask = ins["Mask"][0].reshape(-1).astype(bool)
    n = x.shape[0]
    rank_t = jnp.cumsum(mask.astype(jnp.int32)) - 1
    rank_f = jnp.cumsum((~mask).astype(jnp.int32)) - 1
    dest_t = jnp.where(mask, rank_t, n)
    dest_f = jnp.where(~mask, rank_f, n)
    buf = jnp.zeros((n + 1,) + x.shape[1:], x.dtype)
    out_t = buf.at[dest_t].set(x)[:n]
    out_f = buf.at[dest_f].set(x)[:n]
    n_true = mask.sum().astype(jnp.int32)
    env = ctx.env
    env[lod_key(ctx.op.outputs["OutTrue"][0])] = jnp.stack(
        [jnp.zeros((), jnp.int32), n_true]
    )
    env[lod_key(ctx.op.outputs["OutFalse"][0])] = jnp.stack(
        [jnp.zeros((), jnp.int32), n - n_true]
    )
    return {"OutTrue": out_t, "OutFalse": out_f}


@register_op("merge_lod_tensor")
def _merge_lod_tensor(ctx, ins, attrs):
    """Inverse of split_lod_tensor (reference merge_lod_tensor_op.cc):
    out[i] = InTrue[rank_true[i]] if mask[i] else InFalse[rank_false[i]]."""
    mask = ins["Mask"][0].reshape(-1).astype(bool)
    t = ins["InTrue"][0]
    f = ins["InFalse"][0]
    n = mask.shape[0]
    rank_t = jnp.clip(jnp.cumsum(mask.astype(jnp.int32)) - 1, 0, None)
    rank_f = jnp.clip(jnp.cumsum((~mask).astype(jnp.int32)) - 1, 0, None)
    sel_t = t[jnp.clip(rank_t, 0, t.shape[0] - 1)]
    sel_f = f[jnp.clip(rank_f, 0, f.shape[0] - 1)]
    mexp = mask.reshape((-1,) + (1,) * (t.ndim - 1))
    return {"Out": jnp.where(mexp, sel_t, sel_f)}
