"""Op kernel registry + lowering context.

The reference dispatches per-op kernels by (place, layout, dtype, library)
at runtime (paddle/fluid/framework/operator.cc:508, op_registry.h). On TPU
there is exactly one backend — XLA — so an "op kernel" here is a pure
JAX-traceable function; the Executor calls kernels sequentially while
tracing, producing one fused HLO computation per block. Kernels therefore
never see devices or memory: they map named input arrays to named output
arrays.

Kernel signature::

    fn(ctx: LoweringContext,
       ins: Dict[slot, List[Array]],
       attrs: Dict[str, Any]) -> Dict[slot, List[Array] | Array]
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax

__all__ = ["register_op", "get_kernel", "has_kernel", "LoweringContext", "registered_ops"]

_KERNELS: Dict[str, Callable] = {}


def register_op(op_type: str):
    def deco(fn):
        if op_type in _KERNELS:
            raise ValueError("op %r registered twice" % op_type)
        _KERNELS[op_type] = fn
        return fn

    return deco


def get_kernel(op_type: str) -> Callable:
    try:
        return _KERNELS[op_type]
    except KeyError:
        raise NotImplementedError(
            "no TPU kernel registered for op type %r (have %d ops)"
            % (op_type, len(_KERNELS))
        )


def has_kernel(op_type: str) -> bool:
    return op_type in _KERNELS


def registered_ops() -> List[str]:
    return sorted(_KERNELS)


class LoweringContext(object):
    """Per-trace state shared by kernels: RNG derivation and var metadata.

    Deterministic RNG: every random op folds a fresh counter into the step's
    base key, so a given (program, step-key) pair is reproducible and safe to
    replay under jax.vjp.
    """

    def __init__(self, block, base_key, is_test: bool = False, seq_maxlen=None,
                 seq_buckets=None):
        self.block = block
        self._base_key = base_key
        self._rng_counter = 0
        self.is_test = is_test
        # static bucketed max sequence length for this trace (set by the
        # Executor from the fed LoD offsets); RNN kernels pad to this
        self.seq_maxlen = seq_maxlen
        # per-feed buckets keyed by lod side-band name ("x@LOD0") so ops
        # with inputs of very different raggedness (CTC: frames vs labels)
        # pad each to its own tight bucket
        self.seq_buckets = dict(seq_buckets or {})
        # set per-op by lowering.run_op; lets sequence kernels reach LoD
        # side-band entries without polluting every kernel signature
        self.op = None
        self.env: dict = {}
        # True while lowering the bf16 forward region of an AMP program:
        # deny-listed ops (lowering._AMP_F32_OPS) then compute in f32
        self.amp_region = False
        # lookup-out var name -> cotangent ("delta") leaf name for the
        # SelectedRows sparse-grad path (lowering._find_sparse_sites)
        self.sparse_sites: dict = {}

    def next_key(self):
        if self._base_key is None:
            raise RuntimeError("this execution was built without an RNG key")
        self._rng_counter += 1
        return jax.random.fold_in(self._base_key, self._rng_counter)

    def var(self, name: str):
        return self.block.var(name)

    def var_shape(self, name: str):
        return self.block.var(name).shape

    def var_dtype(self, name: str):
        return self.block.var(name).dtype
