"""paddle.v2.fluid.net_drawer (reference net_drawer.py): draw a
program's dataflow as graphviz dot."""

from .debugger import draw_block_graphviz

__all__ = ["draw_graph"]


def draw_graph(startup_program, main_program, path=None, name="network"):
    """Dot source of the main program's global block (the reference CLI
    drew ops+vars; startup is accepted for signature parity)."""
    return draw_block_graphviz(
        main_program.global_block(), path=path, name=name
    )
