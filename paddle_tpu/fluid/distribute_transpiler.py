"""DistributeTranspiler: the reference's distributed-rewrite API, mapped
onto mesh data parallelism.

Reference (python/paddle/v2/fluid/distribute_transpiler.py:132): rewrites
the program into trainer programs (split+send grad blocks) and pserver
programs (listen_and_serv + optimize blocks) wired over gRPC. On TPU the
entire mechanism collapses: gradients are aggregated by one `psum` over
ICI that XLA inserts when the executor runs the UNMODIFIED program over a
mesh. The API is kept so reference scripts run:

  t = fluid.DistributeTranspiler()
  t.transpile(trainer_id, pservers=..., trainers=N)
  exe.run(t.get_trainer_program(), ...)   # data-parallel over the mesh

get_pserver_program returns an empty program — there is no pserver role
to play; running it is a no-op so pserver-branch scripts exit cleanly.

Multi-PROCESS (DCN) training: call
`paddle_tpu.parallel.DistributedContext.initialize(...)` in every process
(TPU pods autodetect; explicit coordinator/num_processes/process_id
elsewhere), build one global mesh over jax.devices(), and feed each
process its local batch shard — the executor assembles the global batch
(executor._globalize_feeds) and XLA SPMD runs one step across the pod.
tests/test_multihost.py proves train/checkpoint/kill/resume parity with
the reference multi-node axis (RemoteParameterUpdater.h:55,
go/pserver/service.go:120-226).

ASYNC SGD (reference ParameterServer2.h:127-139 AsyncSGD,
go/pserver/service.go:285 per-gradient async updates): redesigned as
**local SGD** — `Executor.run_async_local(steps, sync_every)` gives each
'data'-axis replica its own parameter/optimizer-state copy, runs
`sync_every` purely-local optimizer steps, then averages the models
(one pmean per round). That expresses async's actual trade — staleness
for communication — in a form a globally-synchronous SPMD step can
compile (parallel/async_sgd.py has the full argument; sync_every=1
with SGD/momentum is bit-equal to the sync allreduce step).
`transpile(sync_mode=False)` records the request and warns which call
to use; plain `exe.run` still executes synchronously because per-batch
async dispatch does not exist inside one compiled step.
"""

from __future__ import annotations

import warnings

from .core.program import Program, default_main_program

__all__ = ["DistributeTranspiler", "SimpleDistributeTranspiler",
           "memory_optimize"]


class DistributeTranspiler(object):
    def __init__(self):
        self._program = None
        self._trainers = 1

    def transpile(self, optimize_ops=None, params_grads=None, trainer_id=0,
                  program=None, pservers="127.0.0.1:6174", trainers=1,
                  split_method=None, sync_mode=True, **kwargs):
        """Accepts BOTH reference calling conventions: the v0.11 form
        `transpile(optimize_ops, params_grads, pservers=..., trainers=N)`
        (e.g. benchmark/cluster/vgg16/vgg16_fluid.py) and the later
        `transpile(trainer_id[, program], pservers=..., trainers=N)`."""
        if isinstance(optimize_ops, int):
            # later convention: first positional is trainer_id, second
            # (if any) is the program
            trainer_id = optimize_ops
            if isinstance(params_grads, Program):
                program = params_grads
            elif params_grads is not None:
                raise TypeError(
                    "transpile(trainer_id, program, ...): program must be "
                    "a Program, got %r" % type(params_grads)
                )
        # v0.11's (optimize_ops, params_grads) are accepted and unused:
        # SPMD needs no graph rewrite
        self._program = program or default_main_program()
        self._trainers = int(trainers)
        self._trainer_id = int(trainer_id)
        self._pservers = pservers.split(",") if isinstance(pservers, str) else list(pservers)
        self._sync_mode = bool(sync_mode)
        if not sync_mode:
            warnings.warn(
                "sync_mode=False (AsyncSGD) requested: use "
                "Executor.run_async_local(steps, sync_every) — the "
                "local-SGD redesign of async DP (parallel/async_sgd.py); "
                "plain exe.run executes synchronously"
            )

    def get_trainer_program(self) -> Program:
        """The original program, to be run by an Executor holding a mesh
        whose 'data' axis plays the role of `trainers`."""
        import jax

        from ..parallel.mesh import get_default_mesh, make_mesh, set_default_mesh

        if not getattr(self, "_sync_mode", True):
            # fire at the point of use too — the transpile-time warning
            # may be long scrolled away
            warnings.warn(
                "AsyncSGD was requested (sync_mode=False): exe.run on "
                "this program is synchronous; drive it with "
                "Executor.run_async_local(steps, sync_every) for the "
                "local-SGD async semantics"
            )

        if get_default_mesh() is None:
            n = min(self._trainers, jax.device_count())
            if n > 1:
                set_default_mesh(make_mesh({"data": n}))
            elif self._trainers > 1:
                warnings.warn(
                    "transpile(trainers=%d) but only %d device(s) visible; "
                    "running single-device with identical global-batch math"
                    % (self._trainers, jax.device_count())
                )
        return self._program

    def get_pserver_program(self, endpoint, *args, **kwargs) -> Program:
        return Program()  # no pserver role on TPU; empty program = no-op

    def get_startup_program(self, endpoint=None, pserver_program=None):
        return Program()


class SimpleDistributeTranspiler(DistributeTranspiler):
    """reference distribute_transpiler_simple.py — same collapse."""


def memory_optimize(input_program, print_log=False, **kwargs):
    """reference memory_optimization_transpiler.py:270 rewrites var reuse
    via liveness analysis. Delegates to the real implementation: XLA's
    buffer assignment already does the reuse, and the remaining lever —
    rematerializing the forward region — is enabled here (see
    memory_optimization_transpiler.memory_optimize)."""
    from .memory_optimization_transpiler import memory_optimize as _mo

    return _mo(input_program, print_log=print_log, **kwargs)
