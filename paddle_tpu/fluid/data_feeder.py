"""DataFeeder: minibatch rows -> feed dict (reference
python/paddle/v2/fluid/data_feeder.py). Dense slots stack to one array;
lod_level-1 slots pack to ([total, ...], offsets) pairs for the packed
ragged representation (core/kernels_sequence.py)."""

from __future__ import annotations

import numpy as np

from .core.program import Variable

__all__ = ["DataFeeder"]

_DTYPE_MAP = {"float64": "float32", "int64": "int32"}


class DataFeeder(object):
    def __init__(self, feed_list, place=None, program=None):
        self.feed_list = []
        for v in feed_list:
            if isinstance(v, str):
                from .core.program import default_main_program

                v = (program or default_main_program()).global_block().var(v)
            if not isinstance(v, Variable):
                raise TypeError("feed_list must contain Variables or names")
            self.feed_list.append(v)
        self.place = place

    def feed(self, iterable):
        rows = list(iterable)
        out = {}
        for i, var in enumerate(self.feed_list):
            col = [row[i] for row in rows]
            dtype = _DTYPE_MAP.get(var.dtype, var.dtype)
            if var.lod_level == 0:
                arr = np.asarray(col, dtype=dtype)
                shape = var.shape
                if shape is not None:
                    # re-shape flat rows into the declared [-1, ...] shape
                    tail = [s for s in shape[1:]]
                    if all(s != -1 for s in tail) and arr.ndim <= 2:
                        arr = arr.reshape([len(rows)] + tail)
                out[var.name] = arr
            elif var.lod_level == 1:
                seqs = [np.asarray(s, dtype=dtype) for s in col]
                lens = [len(s) for s in seqs]
                offsets = np.cumsum([0] + lens).astype(np.int32)
                if seqs and seqs[0].ndim == 1:
                    data = np.concatenate(seqs) if seqs else np.zeros((0,), dtype)
                    data = data.reshape(-1, 1)
                else:
                    data = np.concatenate(seqs, axis=0)
                out[var.name] = (data, [offsets.tolist()])
            else:
                raise NotImplementedError(
                    "lod_level>=2 feeds land with the nested-sequence milestone"
                )
        return out
