"""DataFeeder: minibatch rows -> feed dict (reference
python/paddle/v2/fluid/data_feeder.py). Dense slots stack to one array;
lod_level-1 slots pack to ([total, ...], offsets) pairs for the packed
ragged representation (core/kernels_sequence.py)."""

from __future__ import annotations

import numpy as np

from .core.program import Variable

__all__ = ["DataFeeder"]

_DTYPE_MAP = {"float64": "float32", "int64": "int32"}


class DataFeeder(object):
    def __init__(self, feed_list, place=None, program=None):
        self.feed_list = []
        for v in feed_list:
            if isinstance(v, str):
                from .core.program import default_main_program

                v = (program or default_main_program()).global_block().var(v)
            if not isinstance(v, Variable):
                raise TypeError("feed_list must contain Variables or names")
            self.feed_list.append(v)
        self.place = place

    def feed(self, iterable):
        rows = list(iterable)
        out = {}
        for i, var in enumerate(self.feed_list):
            col = [row[i] for row in rows]
            dtype = _DTYPE_MAP.get(var.dtype, var.dtype)
            if var.lod_level == 0:
                arr = np.asarray(col, dtype=dtype)
                shape = var.shape
                if shape is not None:
                    # re-shape flat rows into the declared [-1, ...] shape
                    tail = [s for s in shape[1:]]
                    if all(s != -1 for s in tail) and arr.ndim <= 2:
                        arr = arr.reshape([len(rows)] + tail)
                out[var.name] = arr
            elif var.lod_level == 1:
                seqs = [np.asarray(s, dtype=dtype) for s in col]
                lens = [len(s) for s in seqs]
                offsets = np.cumsum([0] + lens).astype(np.int32)
                if seqs and seqs[0].ndim == 1:
                    data = np.concatenate(seqs) if seqs else np.zeros((0,), dtype)
                    data = data.reshape(-1, 1)
                else:
                    data = np.concatenate(seqs, axis=0)
                out[var.name] = (data, [offsets.tolist()])
            else:
                raise NotImplementedError(
                    "lod_level>=2 feeds land with the nested-sequence milestone"
                )
        return out

    def feed_iter(self, batches):
        """Feed dicts from an iterable of row batches — typically a
        `paddle_tpu.data.DataLoader` built with `collate_fn=list` (each
        batch is then a list of row tuples, exactly what feed() takes).
        Compose with AsyncDeviceFeeder for the full overlap stack:

            loader = data.DataLoader(ds, batch, collate_fn=list)
            for feed in AsyncDeviceFeeder(feeder.feed_iter(loader)):
                exe.run(prog, feed=feed, fetch_list=[loss])
        """
        for rows in batches:
            yield self.feed(rows)


class AsyncDeviceFeeder(object):
    """Host->device double buffering (r4 verdict #3's prefetch item; the
    reference's double-buffered DataProvider / PyDataProvider2 async
    pool, paddle/gserver/dataproviders/DataProvider.h DoubleBuffer):
    a background thread pulls feed dicts from an iterator and uploads
    every array to the device AHEAD of the training loop, so the h2d
    transfer of batch k+1 overlaps the device compute of batch k.

    Device-resident arrays pass straight through the executor's feed
    path (no second upload). Use::

        feeder = AsyncDeviceFeeder(feed_iter, capacity=2)
        for feed in feeder:            # feed dicts, arrays on device
            exe.run(prog, feed=feed, fetch_list=[loss])

    The iterator ends when `feed_iter` does; `close()` stops early.
    Exceptions in the source iterator re-raise at the consuming side.
    """

    _END = object()

    def __init__(self, feed_iter, capacity: int = 2, upload: bool = True):
        import queue
        import threading

        self._q = queue.Queue(maxsize=max(1, int(capacity)))
        self._stop = threading.Event()
        self._done = False  # terminal: END/exception delivered or closed

        def _upload(v):
            # upload=False keeps arrays host-side (multi-process DCN
            # meshes globalize feeds from HOST data — a device_put here
            # would be undone by a device->host copy per batch) while
            # still overlapping the decode
            if not upload:
                return v
            import jax

            if isinstance(v, np.ndarray):
                return jax.device_put(v)
            if isinstance(v, tuple) and len(v) == 2 and isinstance(
                v[0], np.ndarray
            ):
                # (data, lod) ragged feed: the lod offsets stay host-side
                return (jax.device_put(v[0]), v[1])
            return v

        def _producer():
            try:
                for feed in feed_iter:
                    if self._stop.is_set():
                        return
                    self._q.put({k: _upload(v) for k, v in feed.items()})
                self._q.put(self._END)
            except BaseException as e:  # surface in the consumer
                self._q.put(e)

        self._thread = threading.Thread(target=_producer, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        import queue

        while True:
            if self._done:
                raise StopIteration
            if self._stop.is_set():
                # closed: drain what's left, then stop — never block on
                # a producer that has already been told to quit
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    raise StopIteration
            else:
                item = self._q.get()
            if item is self._END:
                self._done = True  # terminal: later next() must not block
                raise StopIteration
            if isinstance(item, BaseException):
                self._done = True
                raise item
            return item

    def close(self):
        import queue
        import warnings

        self._stop.set()
        self._done = True

        def _drain():
            try:
                while True:
                    item = self._q.get_nowait()
                    if isinstance(item, BaseException):
                        # a real data-source error must not vanish just
                        # because the consumer exited for another reason
                        warnings.warn(
                            "AsyncDeviceFeeder.close() discarded a "
                            "pending reader error: %r" % item
                        )
            except queue.Empty:
                pass

        # a producer blocked in put() completes that put once the drain
        # frees a slot and only THEN sees _stop — drain, wait for the
        # thread to exit, drain the stragglers
        _drain()
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            # blocked INSIDE the source iterator (close() can only stop
            # it between batches): the daemon thread lingers until that
            # read returns — don't share one data source with a new
            # feeder while this is pending
            warnings.warn(
                "AsyncDeviceFeeder producer still blocked in the data "
                "source after close(); its prefetched buffers stay "
                "alive until the read returns"
            )
        _drain()


__all__.append("AsyncDeviceFeeder")
