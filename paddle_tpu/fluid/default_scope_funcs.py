"""paddle.v2.fluid.default_scope_funcs (reference
default_scope_funcs.py): a thread-default Scope stack with
enter/leave_local_scope, var/find_var, and the scoped_function
decorator — over this core's dict-backed Scope."""

from __future__ import annotations

import threading

from .executor import Scope, global_scope

__all__ = [
    "get_cur_scope", "enter_local_scope", "leave_local_scope", "var",
    "find_var", "scoped_function",
]

_local = threading.local()


def _stack():
    if not hasattr(_local, "stack"):
        _local.stack = [global_scope()]
    return _local.stack


def get_cur_scope() -> Scope:
    return _stack()[-1]


def enter_local_scope():
    _stack().append(Scope())


def leave_local_scope():
    stack = _stack()
    if len(stack) == 1:
        raise RuntimeError("cannot leave the global scope")
    stack.pop()


def var(name):
    """Get-or-create a variable HANDLE in the current scope (reference
    Scope.var returns a Variable whose get_tensor() is settable) —
    delegates to executor.Scope.var's _TensorView."""
    return get_cur_scope().var(name)


def find_var(name):
    """Variable handle, or None when absent anywhere on the stack
    (reference Scope.find_var semantics)."""
    for scope in reversed(_stack()):
        found = scope.find_var(name)
        if found is not None:
            return found
    return None


def scoped_function(func):
    """Run func inside a fresh local scope (reference scoped_function)."""
    enter_local_scope()
    try:
        return func()
    finally:
        leave_local_scope()
