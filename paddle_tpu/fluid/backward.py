"""append_backward: mark the program for gradient computation.

Reference parity: python/paddle/v2/fluid/backward.py + C++
framework/backward.cc:523 (AppendBackward). The reference appends one grad
op per forward op via a registry of GradOpDescMakers; here we instead
append a single `autodiff` marker op recording (loss, params, grad names).
At lowering time the marker becomes one `jax.vjp` over the forward region
(core/lowering.py), which is both exact and faster on TPU: XLA sees the
entire forward+backward+update as one computation and fuses across the
boundary, where the reference pays an interpreter step per grad op.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .core.lowering import AUTODIFF_OP
from .core.program import Parameter, Program, Variable, grad_var_name

__all__ = ["append_backward", "calc_gradient"]


def append_backward(
    loss: Variable,
    parameter_list: Optional[List[str]] = None,
    no_grad_set=None,
    callbacks=None,
) -> List[Tuple[Variable, Variable]]:
    program = loss.block.program
    block = program.global_block()
    if any(op.type == AUTODIFF_OP for op in block.ops):
        raise ValueError(
            "program already has an autodiff marker (minimize or "
            "calc_gradient); one program supports one backward"
        )

    if parameter_list is not None:
        params = [block.var(p) if isinstance(p, str) else p for p in parameter_list]
    else:
        params = [p for p in block.all_parameters() if getattr(p, "trainable", True)]
    no_grad = set()
    if no_grad_set:
        no_grad = {v.name if isinstance(v, Variable) else str(v) for v in no_grad_set}
    params = [p for p in params if p.name not in no_grad]

    params_and_grads: List[Tuple[Variable, Variable]] = []
    grad_names = []
    for p in params:
        g_name = grad_var_name(p.name)
        if g_name in block.vars:
            g = block.vars[g_name]
        else:
            g = block.create_var(
                name=g_name, shape=p.shape, dtype=p.dtype, persistable=False
            )
        g.stop_gradient = True
        params_and_grads.append((p, g))
        grad_names.append(g_name)

    block.append_op(
        type=AUTODIFF_OP,
        inputs={},
        outputs={"Grads": grad_names},
        attrs={
            "loss_name": loss.name,
            "param_names": [p.name for p in params],
            "grad_names": grad_names,
        },
    )
    return params_and_grads


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Gradients of `targets` w.r.t. arbitrary LEAF variables (feeds or
    parameters) — reference backward.py:464. Lowers to the same single
    jax.vjp the training path uses: a scalar <sum of targets (weighted
    by target_gradients)> is built with graph ops, then the autodiff
    marker records the input->grad map.

    Restrictions of the fused-vjp design: inputs must be leaves (a feed
    or parameter; gradients w.r.t. intermediates would require a second
    trace cut), and a program carries at most one autodiff marker —
    call this OR minimize, not both, on the same program.

    Divergence from the reference: for a leaf input that does not affect
    the targets the reference returns None; the fused vjp returns a
    ZEROS array of the leaf's shape (same calculus, different encoding).
    """
    from .core.program import Parameter, unique_name

    def _as_list(x):
        return list(x) if isinstance(x, (list, tuple)) else [x]

    targets = _as_list(targets)
    inputs = _as_list(inputs)
    # eager leaf validation: an intermediate input would silently fall out
    # of the vjp leaf set (lowering keeps only names already bound in the
    # scope/feed env), leaving its grad var unpopulated and failing much
    # later with an opaque fetch KeyError — reject it here instead
    for v in inputs:
        if not (
            isinstance(v, Parameter)
            or getattr(v, "is_data", False)
            or getattr(v, "persistable", False)  # scope-bound leaves
        ):
            raise NotImplementedError(
                "calc_gradient input %r is neither a Parameter nor a "
                "data (feed) variable nor a persistable; gradients "
                "w.r.t. intermediate values are not supported by the "
                "fused-vjp design — take the gradient at the leaves "
                "that produce it" % v.name
            )
    target_gradients = _as_list(target_gradients or [])
    if target_gradients and len(target_gradients) != len(targets):
        raise ValueError(
            "should have the same number of target_gradients as targets"
        )
    block = targets[0].block
    if any(op.type == AUTODIFF_OP for op in block.ops):
        raise ValueError(
            "program already has an autodiff marker (minimize or a "
            "previous calc_gradient); one program supports one backward"
        )
    input_name_set = {v.name for v in inputs}
    no_grad = {
        v.name if isinstance(v, Variable) else str(v)
        for v in (no_grad_set or [])
    }
    beyond = no_grad - input_name_set
    if beyond:
        # the fused vjp differentiates the whole forward region; cutting
        # gradient flow at an INTERMEDIATE would silently change numbers
        raise NotImplementedError(
            "no_grad_set entries that are not calc_gradient inputs are "
            "not supported (would require a stop-gradient cut inside "
            "the fused vjp): %r" % sorted(beyond)
        )

    # scalar objective: sum_i reduce_sum(target_i * tg_i). Ops append to
    # the TARGETS' block directly — layer helpers would write to the
    # current default program, which may be a different one.
    def _tmp(like, shape=None):
        return block.create_var(
            name=unique_name("calc_grad_obj"),
            shape=list(shape if shape is not None else like.shape or []),
            dtype=like.dtype,
        )

    parts = []
    for i, t in enumerate(targets):
        tg = target_gradients[i] if target_gradients else None
        term = t
        if tg is not None:
            term = _tmp(t)
            block.append_op(
                type="elementwise_mul", inputs={"X": [t], "Y": [tg]},
                outputs={"Out": [term]}, attrs={},
            )
        part = _tmp(t, shape=[1])
        block.append_op(
            type="reduce_sum", inputs={"X": [term]},
            outputs={"Out": [part]}, attrs={"reduce_all": True},
        )
        parts.append(part)
    total = parts[0]
    for p in parts[1:]:
        nxt = _tmp(total, shape=[1])
        block.append_op(
            type="elementwise_add", inputs={"X": [total], "Y": [p]},
            outputs={"Out": [nxt]}, attrs={},
        )
        total = nxt

    grads = []
    grad_names, input_names = [], []
    for v in inputs:
        if v.name in no_grad:
            grads.append(None)
            continue
        g_name = grad_var_name(v.name)
        g = block.create_var(
            name=g_name, shape=v.shape, dtype=v.dtype, persistable=False
        )
        g.stop_gradient = True
        grads.append(g)
        grad_names.append(g_name)
        input_names.append(v.name)

    block.append_op(
        type=AUTODIFF_OP,
        inputs={},
        outputs={"Grads": grad_names},
        attrs={
            "loss_name": total.name,
            "param_names": input_names,
            "grad_names": grad_names,
        },
    )
    return grads
