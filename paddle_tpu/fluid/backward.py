"""append_backward: mark the program for gradient computation.

Reference parity: python/paddle/v2/fluid/backward.py + C++
framework/backward.cc:523 (AppendBackward). The reference appends one grad
op per forward op via a registry of GradOpDescMakers; here we instead
append a single `autodiff` marker op recording (loss, params, grad names).
At lowering time the marker becomes one `jax.vjp` over the forward region
(core/lowering.py), which is both exact and faster on TPU: XLA sees the
entire forward+backward+update as one computation and fuses across the
boundary, where the reference pays an interpreter step per grad op.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .core.lowering import AUTODIFF_OP
from .core.program import Parameter, Program, Variable, grad_var_name

__all__ = ["append_backward"]


def append_backward(
    loss: Variable,
    parameter_list: Optional[List[str]] = None,
    no_grad_set=None,
    callbacks=None,
) -> List[Tuple[Variable, Variable]]:
    program = loss.block.program
    block = program.global_block()

    if parameter_list is not None:
        params = [block.var(p) if isinstance(p, str) else p for p in parameter_list]
    else:
        params = [p for p in block.all_parameters() if getattr(p, "trainable", True)]
    no_grad = set()
    if no_grad_set:
        no_grad = {v.name if isinstance(v, Variable) else str(v) for v in no_grad_set}
    params = [p for p in params if p.name not in no_grad]

    params_and_grads: List[Tuple[Variable, Variable]] = []
    grad_names = []
    for p in params:
        g_name = grad_var_name(p.name)
        if g_name in block.vars:
            g = block.vars[g_name]
        else:
            g = block.create_var(
                name=g_name, shape=p.shape, dtype=p.dtype, persistable=False
            )
        g.stop_gradient = True
        params_and_grads.append((p, g))
        grad_names.append(g_name)

    block.append_op(
        type=AUTODIFF_OP,
        inputs={},
        outputs={"Grads": grad_names},
        attrs={
            "loss_name": loss.name,
            "param_names": [p.name for p in params],
            "grad_names": grad_names,
        },
    )
    return params_and_grads
