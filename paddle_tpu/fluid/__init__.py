"""paddle_tpu.fluid — the Fluid-compatible Python frontend of the
TPU-native framework (API parity: reference python/paddle/v2/fluid/__init__.py)."""

from . import core
from . import framework
from . import layers
from . import nets
from . import optimizer
from . import backward
from . import regularizer
from . import initializer
from . import clip
from . import evaluator
from . import io
from . import profiler
from . import learning_rate_decay
from . import distribute_transpiler
from . import debugger
from . import debugger as debuger  # reference module name (sic)

from .framework import (
    Program,
    Variable,
    Parameter,
    default_main_program,
    default_startup_program,
    program_guard,
    get_var,
)
from .core import CPUPlace, CUDAPlace, TPUPlace
from .executor import (
    Executor,
    Scope,
    global_scope,
    scope_guard,
    switch_scope,
    fetch_var,
    as_numpy,
)
from .data_feeder import DataFeeder
from .distribute_transpiler import (
    DistributeTranspiler,
    SimpleDistributeTranspiler,
    memory_optimize,
)
from .param_attr import ParamAttr, WeightNormParamAttr
from .initializer import Constant, Normal, TruncatedNormal, Uniform, Xavier, MSRA
from .optimizer import (
    SGD,
    Momentum,
    Adagrad,
    Adam,
    Adamax,
    DecayedAdagrad,
    RMSProp,
    Adadelta,
    Ftrl,
    SGDOptimizer,
    MomentumOptimizer,
    AdagradOptimizer,
    AdamOptimizer,
    AdamaxOptimizer,
    DecayedAdagradOptimizer,
    RMSPropOptimizer,
    AdadeltaOptimizer,
    FtrlOptimizer,
)
from .backward import append_backward, calc_gradient
from .regularizer import L1Decay, L2Decay, L1DecayRegularizer, L2DecayRegularizer
from .clip import (
    ErrorClipByValue,
    GradientClipByValue,
    GradientClipByNorm,
    GradientClipByGlobalNorm,
)

__all__ = framework.__dict__.keys() if False else [
    "io",
    "initializer",
    "layers",
    "nets",
    "optimizer",
    "learning_rate_decay",
    "backward",
    "calc_gradient",
    "regularizer",
    "profiler",
    "clip",
    "evaluator",
    "Program",
    "Variable",
    "Parameter",
    "default_main_program",
    "default_startup_program",
    "program_guard",
    "Executor",
    "Scope",
    "global_scope",
    "scope_guard",
    "fetch_var",
    "DataFeeder",
    "ParamAttr",
    "WeightNormParamAttr",
    "CPUPlace",
    "CUDAPlace",
    "TPUPlace",
    "append_backward",
]
