"""Program introspection dumps (reference fluid/debuger.py pprint_program_
codes + draw_block_graphviz via fluid/graphviz.py, net_drawer.py)."""

from __future__ import annotations

from typing import Optional

from .core.program import Program

__all__ = ["pprint_program_codes", "draw_block_graphviz", "program_to_code"]


def program_to_code(program: Program) -> str:
    """Pseudo-code dump of every block (reference debuger.py)."""
    lines = []
    for blk in program.blocks:
        lines.append("// block %d (parent %d)" % (blk.idx, blk.parent_idx))
        for v in blk.vars.values():
            lines.append(
                "var %s : %s%s%s"
                % (
                    v.name,
                    v.dtype,
                    list(v.shape) if v.shape else "[?]",
                    "  // persistable" if v.persistable else "",
                )
            )
        for op in blk.ops:
            ins = ", ".join(
                "%s=%s" % (k, v) for k, v in sorted(op.inputs.items())
            )
            outs = ", ".join(
                "%s" % v for _, v in sorted(op.outputs.items())
            )
            lines.append("%s = %s(%s)" % (outs or "()", op.type, ins))
    return "\n".join(lines)


def pprint_program_codes(program: Program):
    print(program_to_code(program))


def draw_block_graphviz(block, path: Optional[str] = None, name="program"):
    """Emit a graphviz dot description of a block's dataflow (reference
    graphviz.py/net_drawer.py). Returns the dot source; writes it to
    `path` when given (render with `dot -Tpng` externally)."""
    lines = ["digraph %s {" % name, "  rankdir=TB;"]
    esc = lambda s: s.replace('"', "'")
    seen_vars = set()
    for i, op in enumerate(block.ops):
        op_id = "op_%d" % i
        lines.append(
            '  %s [label="%s", shape=box, style=filled, fillcolor=lightblue];'
            % (op_id, esc(op.type))
        )
        for names in op.inputs.values():
            for n in names:
                vid = "var_%s" % abs(hash(n))
                if n not in seen_vars:
                    seen_vars.add(n)
                    lines.append('  %s [label="%s", shape=ellipse];' % (vid, esc(n)))
                lines.append("  %s -> %s;" % (vid, op_id))
        for names in op.outputs.values():
            for n in names:
                vid = "var_%s" % abs(hash(n))
                if n not in seen_vars:
                    seen_vars.add(n)
                    lines.append('  %s [label="%s", shape=ellipse];' % (vid, esc(n)))
                lines.append("  %s -> %s;" % (op_id, vid))
    lines.append("}")
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot
