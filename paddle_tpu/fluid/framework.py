"""fluid.framework: re-export of the IR object model.

Mirrors reference python/paddle/v2/fluid/framework.py so user code doing
`from paddle.v2.fluid.framework import Program, program_guard` ports by
changing only the package root.
"""

from .core.program import (  # noqa: F401
    Block,
    Operator,
    Parameter,
    Program,
    Variable,
    convert_np_dtype,
    default_main_program,
    default_startup_program,
    grad_var_name,
    program_guard,
    switch_main_program,
    switch_startup_program,
    unique_name,
)


def get_var(name, program=None):
    if program is None:
        program = default_main_program()
    return program.global_block().var(name)
