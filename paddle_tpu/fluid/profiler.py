"""Profiler (reference python/paddle/v2/fluid/profiler.py:33 cuda_profiler,
:76 profiler; C++ platform/profiler.cc RecordEvent/EnableProfiler:142,
ParseEvents:198).

Two layers on TPU:

* XLA traces via jax.profiler (TensorBoard/XProf) — the deep-dive path.
* A per-op COST TABLE (reference ParseEvents parity): inside a
  ``with profiler(...)`` block the Executor switches to an interpret-mode
  timed run — each forward op executes eagerly on the device and is
  synchronised + wall-clock timed; a training program's backward+update
  then runs once through the normal fused path (one row) so update
  semantics are unchanged. On exit the sorted table prints and is
  available programmatically via ``last_profile()``.
"""

from __future__ import annotations

import contextlib
import os
import time

import jax

__all__ = [
    "cuda_profiler", "reset_profiler", "profiler", "record_event",
    "get_events", "last_profile", "active_op_collector",
]

_events = []
_last_profile = []
_active_collector = None


class OpCostCollector(object):
    """op type -> (calls, total, min, max) wall-clock seconds."""

    def __init__(self):
        self.rows = {}

    def record(self, op_type: str, seconds: float):
        row = self.rows.get(op_type)
        if row is None:
            self.rows[op_type] = [1, seconds, seconds, seconds]
        else:
            row[0] += 1
            row[1] += seconds
            row[2] = min(row[2], seconds)
            row[3] = max(row[3], seconds)

    def table(self, sorted_key=None):
        """[{Event, Calls, Total, Min, Max, Ave}] in ms, sorted like the
        reference (profiler.py sorted_key in calls/total/max/min/ave)."""
        out = [
            {
                "Event": op,
                "Calls": calls,
                "Total": total * 1e3,
                "Min": mn * 1e3,
                "Max": mx * 1e3,
                "Ave": total / calls * 1e3,
            }
            for op, (calls, total, mn, mx) in self.rows.items()
        ]
        key = {
            "calls": "Calls", "total": "Total", "max": "Max",
            "min": "Min", "ave": "Ave",
        }.get(sorted_key)
        if key:
            out.sort(key=lambda r: r[key], reverse=True)
        return out


def active_op_collector():
    """The executor checks this each run; non-None switches it to the
    interpret-mode timed path."""
    return _active_collector


def last_profile():
    """The table from the most recent profiler() block."""
    return list(_last_profile)


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    """Kept for API parity; records an XLA trace to the given directory."""
    with profiler("All", profile_path=output_file):
        yield


def reset_profiler():
    _events.clear()
    del _last_profile[:]


def _print_table(table, elapsed):
    print("\n------------------------->     Profiling Report     "
          "<-------------------------\n")
    print("Place: TPU    Total time span: %.4fs" % elapsed)
    hdr = "%-32s %8s %12s %12s %12s %12s" % (
        "Event", "Calls", "Total(ms)", "Min(ms)", "Max(ms)", "Ave(ms)")
    print(hdr)
    for r in table:
        print("%-32s %8d %12.4f %12.4f %12.4f %12.4f" % (
            r["Event"][:32], r["Calls"], r["Total"], r["Min"], r["Max"],
            r["Ave"]))


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    """Reference fluid.profiler.profiler parity: times every executor run
    in the block per-op and prints the sorted cost table on exit."""
    global _active_collector
    if state not in ["CPU", "GPU", "All", "TPU"]:
        raise ValueError("state must be 'CPU', 'GPU', 'TPU' or 'All'")
    if sorted_key not in (None, "default", "calls", "total", "max", "min",
                          "ave"):
        raise ValueError("unsupported sorted_key %r" % sorted_key)
    trace_dir = (
        profile_path if os.path.isdir(profile_path)
        else os.path.dirname(profile_path) or "/tmp"
    )
    started = False
    # XLA trace capture defaults ON, matching the behavior of this API
    # before the per-op table existed (rounds 1-2 always started a
    # trace); PADDLE_TPU_XLA_TRACE=0 opts out for op-table-only CI runs
    if os.environ.get("PADDLE_TPU_XLA_TRACE", "1") != "0":
        try:
            jax.profiler.start_trace(trace_dir)
            started = True
        except Exception:
            pass  # a trace may already be running
    prev = _active_collector
    collector = OpCostCollector()
    _active_collector = collector
    t0 = time.time()
    try:
        yield
    finally:
        elapsed = time.time() - t0
        _active_collector = prev
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
        _events.append(("profiler_span", elapsed))
        table = collector.table(
            sorted_key if sorted_key != "default" else None
        )
        del _last_profile[:]
        _last_profile.extend(table)
        _print_table(table, elapsed)


@contextlib.contextmanager
def record_event(name):
    """RAII timing (reference platform/profiler.h RecordEvent)."""
    t0 = time.time()
    try:
        yield
    finally:
        _events.append((name, time.time() - t0))


def get_events():
    return list(_events)


def device_memory_stats(device=None):
    """Per-device memory counters (bytes_in_use, peak_bytes_in_use,
    bytes_limit, ...) straight from the runtime — the observability the
    reference exposed through its allocator stats
    (memory/detail/buddy_allocator). Returns {} when the backend does
    not report memory (e.g. the CPU test fixture)."""
    import jax

    d = device if device is not None else jax.local_devices()[0]
    stats = getattr(d, "memory_stats", None)
    if stats is None:
        return {}
    try:
        return dict(stats() or {})
    except Exception:
        return {}


__all__.append("device_memory_stats")


# ---------------------------------------------------------------------
# compiled-step per-op profiling (r4): the interpret-mode table above
# times ops EAGERLY; this path reads the truth of the FUSED program —
# every scheduled HLO instruction of the compiled step is attributed
# back to the fluid op that produced it via the `op:<type>` named-scope
# tags lowering stamps into HLO metadata (core/lowering.py run_op), and
# the measured compiled-step wall time is distributed over ops by each
# instruction's memory traffic (operand + output bytes — the HBM-roof
# proxy appropriate on TPU). Backward instructions (op_name carries
# XLA's transpose(...) wrapper) land on "<op>_grad" rows, mirroring the
# reference's per-grad-op rows (platform/profiler.cc:198 ParseEvents).
# ---------------------------------------------------------------------

import re as _re

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}
_SHAPE_RE = _re.compile(r"\b([a-z]+\d*)\[([\d,]*)\]")
_INST_RE = _re.compile(r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(.*)$")
_OPNAME_RE = _re.compile(r'op_name="([^"]*)"')
_TAG_RE = _re.compile(r"op:([\w.]+)")


def _shape_bytes(type_str):
    total = 0
    for dt, dims in _SHAPE_RE.findall(type_str):
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dt, 4)
    return total


def _entry_lines(hlo_text):
    """The ENTRY computation's lines only — a computation printed AFTER
    the entry must never leak rows."""
    lines = []
    in_entry = False
    depth = 0
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY "):
            in_entry = True
            depth = line.count("{") - line.count("}")
            continue
        if in_entry:
            depth += line.count("{") - line.count("}")
            if depth <= 0:
                break
            lines.append(line)
    return lines


def _line_tag(line):
    """Op provenance tag of one HLO line ('[xla]' when untagged);
    backward instructions (op_name carries XLA's transpose(...) wrapper)
    land on '<op>_grad' rows."""
    onm = _OPNAME_RE.search(line)
    if onm:
        t = _TAG_RE.search(onm.group(1))
        if t:
            tag = t.group(1)
            if "transpose(" in onm.group(1):
                tag += "_grad"  # cotangent-pass instruction
            return tag
    return "[xla]"


def parse_hlo_op_costs(hlo_text):
    """{op_row: {'instructions': n, 'bytes': b}} from scheduled HLO text.
    Only the ENTRY computation's instructions count (fusions are single
    scheduled instructions; their internals are not separately
    scheduled). Instructions with no op tag pool under '[xla]'."""
    entry_lines = _entry_lines(hlo_text)

    # symbol table: instruction name -> result type string
    types = {}
    for line in entry_lines:
        m = _INST_RE.match(line)
        if m:
            types[m.group(1)] = m.group(2).split(" ")[0]

    rows = {}
    for line in entry_lines:
        m = _INST_RE.match(line)
        if not m:
            continue
        name, rest = m.groups()
        opcode = rest.split(" ", 1)[1].split("(")[0].strip() if " " in rest else ""
        if opcode in ("parameter", "constant", "tuple", "get-tuple-element"):
            continue
        byts = _shape_bytes(types.get(name, ""))
        for ref in _re.findall(r"%([\w.\-]+)", rest):
            if ref in types and ref != name:
                byts += _shape_bytes(types[ref])
        row = rows.setdefault(
            _line_tag(line), {"instructions": 0, "bytes": 0}
        )
        row["instructions"] += 1
        row["bytes"] += byts
    return rows


def compiled_profile(exe, program, feed, fetch_list, runs=3,
                     sorted_key="total"):
    """Per-op cost table of the COMPILED training step.

    Runs the program once to compile (and prime the executor cache),
    re-lowers the cached signature to read the scheduled HLO, times
    `runs` steps wall-clock, and splits the measured per-step time over
    op rows by attributed memory traffic. Returns (table, meta) where
    table rows follow OpCostCollector.table() ({'Event', 'Calls',
    'Total', ...} — Total in ms) and meta carries the raw bytes and the
    XLA cost-analysis flops for the step."""
    import numpy as _np

    exe._capture_avals = True
    try:
        exe.run(program, feed=feed, fetch_list=fetch_list)
        entry, avals, host_args = exe._last_exec
    finally:
        exe._capture_avals = False
        # the host snapshot is a full copy of every param: don't park it
        # on the executor past this call
        exe._last_exec = None
    lowered = entry.lower(*avals)
    compiled = lowered.compile()
    rows = parse_hlo_op_costs(compiled.as_text())

    # pure device time: fresh device args per run (the entry donates its
    # buffers), timed around the cached jitted entry with
    # block_until_ready — host feed upload / numpy fetch conversion stay
    # OUT of the op rows (ADVICE r4, profiler.py:309). Bare device_put
    # would fight a mesh-jitted entry's in_shardings, so sharded
    # executors fall back to end-to-end timing.
    dev_s = None
    if exe._resolve_mesh() is None:
        dev_s = 0.0
        for _ in range(runs):
            dev_args = jax.tree_util.tree_map(
                lambda a: jax.device_put(a) if hasattr(a, "shape") else a,
                host_args,
            )
            jax.block_until_ready(dev_args)
            t0 = time.time()
            out_dev = entry(*dev_args)
            jax.block_until_ready(out_dev)
            dev_s += time.time() - t0
        dev_s /= runs

    # end-to-end wall time (host feed + fetch included) for the meta row
    t0 = time.time()
    for _ in range(runs):
        out = exe.run(program, feed=feed, fetch_list=fetch_list)
    _np.asarray(out[0])  # sync
    e2e_s = (time.time() - t0) / runs
    step_s = dev_s if dev_s is not None else e2e_s

    total_bytes = sum(r["bytes"] for r in rows.values()) or 1
    table = [
        {
            "Event": tag,
            "Calls": r["instructions"],
            "Total": step_s * 1e3 * r["bytes"] / total_bytes,
            "Min": 0.0,
            "Max": 0.0,
            "Ave": step_s * 1e3 * r["bytes"] / total_bytes
            / max(r["instructions"], 1),
            "Bytes": r["bytes"],
        }
        for tag, r in rows.items()
    ]
    key = {"calls": "Calls", "total": "Total", "ave": "Ave"}.get(
        sorted_key, "Total"
    )
    table.sort(key=lambda r: r[key], reverse=True)
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    meta = {
        # device-only when timing_mode == "device"; end-to-end otherwise
        "step_seconds": step_s,
        "e2e_seconds": e2e_s,         # exe.run incl. host feed/fetch
        "host_overhead_seconds": (
            max(e2e_s - step_s, 0.0) if dev_s is not None else None
        ),
        "timing_mode": "device" if dev_s is not None else "e2e",
        "flops": float((ca or {}).get("flops", 0.0)),
        "bytes_attributed": total_bytes,
    }
    _print_table(table, step_s * runs)
    return table, meta


__all__ += ["compiled_profile", "parse_hlo_op_costs"]


def parse_hlo_instr_tags(hlo_text):
    """{instruction_name: op_tag} over the ENTRY computation — the join
    key between a device profiler trace (events named per HLO
    instruction) and the lowering's op provenance metadata. Shares the
    entry walk and tag extraction with parse_hlo_op_costs so the
    modeled and measured tables can never disagree about ownership."""
    tags = {}
    for line in _entry_lines(hlo_text):
        m = _INST_RE.match(line)
        if m:
            tags[m.group(1)] = _line_tag(line)
    return tags


def _parse_trace_durations(trace_dir):
    """Sum per-HLO-instruction device durations (us) from a
    jax.profiler.trace output directory. Events carry the instruction
    name verbatim ('fusion.123', 'dot_general.1'); bookkeeping events
    ('end: ...', runtime internals) are dropped by the join later."""
    import glob
    import gzip
    import json as _json

    durs = {}
    for p in glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    ):
        tr = _json.loads(gzip.open(p).read())
        for e in tr.get("traceEvents", []):
            if e.get("ph") != "X" or "dur" not in e:
                continue
            name = e.get("name", "")
            if name.startswith("end: "):
                continue
            durs[name] = durs.get(name, 0.0) + float(e["dur"])
    return durs


def trace_profile(exe, program, feed, fetch_list, runs=3):
    """Reconcile the traffic-MODELED per-op attribution against
    MEASURED per-instruction device times from a real `jax.profiler`
    trace (r4 verdict #4; the reference measured per-op times with CUDA
    events, platform/profiler.cc:142,198 — this is the TPU equivalent:
    XLA instruction events joined back to op provenance through the HLO
    metadata tags lowering stamps).

    Returns (table, meta): rows {'Event', 'measured_ms',
    'modeled_ms', 'disagreement'} sorted by measured time;
    meta['top5_max_disagreement'] is the reconciliation verdict — the
    share-of-step disagreement between the two attributions over the
    five biggest measured rows. Works on any backend with profiler
    support (CPU validates the machinery; TPU gives real device
    times)."""
    import tempfile

    import jax
    import numpy as _np

    exe._capture_avals = True
    try:
        exe.run(program, feed=feed, fetch_list=fetch_list)
        entry, avals, host_args = exe._last_exec
    finally:
        exe._capture_avals = False
        exe._last_exec = None
    compiled = entry.lower(*avals).compile()
    txt = compiled.as_text()
    tags = parse_hlo_instr_tags(txt)
    model_rows = parse_hlo_op_costs(txt)

    import shutil

    trace_dir = tempfile.mkdtemp(prefix="ptpu_trace_")
    try:
        with jax.profiler.trace(trace_dir):
            for _ in range(runs):
                out = exe.run(program, feed=feed, fetch_list=fetch_list)
            _np.asarray(out[0])  # sync inside the trace window
        durs = _parse_trace_durations(trace_dir)
    finally:
        shutil.rmtree(trace_dir, ignore_errors=True)

    # join: instruction event -> op tag. Trace event names sometimes
    # carry a '.remat'/suffix variant; exact match first, then prefix.
    measured = {}
    unmatched_us = 0.0
    for name, us in durs.items():
        tag = tags.get(name)
        if tag is None:
            base = name.split(" ")[0]
            tag = tags.get(base)
        if tag is None:
            unmatched_us += us
            continue
        measured[tag] = measured.get(tag, 0.0) + us
    total_meas = sum(measured.values()) or 1.0
    total_bytes = sum(r["bytes"] for r in model_rows.values()) or 1

    table = []
    for tag in sorted(set(measured) | set(model_rows)):
        m_us = measured.get(tag, 0.0)
        b = model_rows.get(tag, {}).get("bytes", 0)
        meas_share = m_us / total_meas
        model_share = b / total_bytes
        table.append({
            "Event": tag,
            "measured_ms": round(m_us / 1e3 / runs, 4),
            "measured_share": round(meas_share, 4),
            "modeled_share": round(model_share, 4),
            "disagreement": round(abs(meas_share - model_share), 4),
        })
    table.sort(key=lambda r: -r["measured_ms"])
    top5 = table[:5]
    meta = {
        "runs": runs,
        "measured_total_ms": round(total_meas / 1e3 / runs, 3),
        "unmatched_ms": round(unmatched_us / 1e3 / runs, 3),
        "top5_max_disagreement": max(
            (r["disagreement"] for r in top5), default=0.0
        ),
        "backend": jax.default_backend(),
    }
    return table, meta


__all__ += ["trace_profile", "parse_hlo_instr_tags"]
