"""Profiler (reference python/paddle/v2/fluid/profiler.py:33 cuda_profiler,
:76 profiler; C++ platform/profiler.cc RecordEvent/EnableProfiler:142,
ParseEvents:198).

Two layers on TPU:

* XLA traces via jax.profiler (TensorBoard/XProf) — the deep-dive path.
* A per-op COST TABLE (reference ParseEvents parity): inside a
  ``with profiler(...)`` block the Executor switches to an interpret-mode
  timed run — each forward op executes eagerly on the device and is
  synchronised + wall-clock timed; a training program's backward+update
  then runs once through the normal fused path (one row) so update
  semantics are unchanged. On exit the sorted table prints and is
  available programmatically via ``last_profile()``.
"""

from __future__ import annotations

import contextlib
import os
import time

import jax

__all__ = [
    "cuda_profiler", "reset_profiler", "profiler", "record_event",
    "get_events", "last_profile", "active_op_collector",
]

_events = []
_last_profile = []
_active_collector = None


class OpCostCollector(object):
    """op type -> (calls, total, min, max) wall-clock seconds."""

    def __init__(self):
        self.rows = {}

    def record(self, op_type: str, seconds: float):
        row = self.rows.get(op_type)
        if row is None:
            self.rows[op_type] = [1, seconds, seconds, seconds]
        else:
            row[0] += 1
            row[1] += seconds
            row[2] = min(row[2], seconds)
            row[3] = max(row[3], seconds)

    def table(self, sorted_key=None):
        """[{Event, Calls, Total, Min, Max, Ave}] in ms, sorted like the
        reference (profiler.py sorted_key in calls/total/max/min/ave)."""
        out = [
            {
                "Event": op,
                "Calls": calls,
                "Total": total * 1e3,
                "Min": mn * 1e3,
                "Max": mx * 1e3,
                "Ave": total / calls * 1e3,
            }
            for op, (calls, total, mn, mx) in self.rows.items()
        ]
        key = {
            "calls": "Calls", "total": "Total", "max": "Max",
            "min": "Min", "ave": "Ave",
        }.get(sorted_key)
        if key:
            out.sort(key=lambda r: r[key], reverse=True)
        return out


def active_op_collector():
    """The executor checks this each run; non-None switches it to the
    interpret-mode timed path."""
    return _active_collector


def last_profile():
    """The table from the most recent profiler() block."""
    return list(_last_profile)


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    """Kept for API parity; records an XLA trace to the given directory."""
    with profiler("All", profile_path=output_file):
        yield


def reset_profiler():
    _events.clear()
    del _last_profile[:]


def _print_table(table, elapsed):
    print("\n------------------------->     Profiling Report     "
          "<-------------------------\n")
    print("Place: TPU    Total time span: %.4fs" % elapsed)
    hdr = "%-32s %8s %12s %12s %12s %12s" % (
        "Event", "Calls", "Total(ms)", "Min(ms)", "Max(ms)", "Ave(ms)")
    print(hdr)
    for r in table:
        print("%-32s %8d %12.4f %12.4f %12.4f %12.4f" % (
            r["Event"][:32], r["Calls"], r["Total"], r["Min"], r["Max"],
            r["Ave"]))


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    """Reference fluid.profiler.profiler parity: times every executor run
    in the block per-op and prints the sorted cost table on exit."""
    global _active_collector
    if state not in ["CPU", "GPU", "All", "TPU"]:
        raise ValueError("state must be 'CPU', 'GPU', 'TPU' or 'All'")
    if sorted_key not in (None, "default", "calls", "total", "max", "min",
                          "ave"):
        raise ValueError("unsupported sorted_key %r" % sorted_key)
    trace_dir = (
        profile_path if os.path.isdir(profile_path)
        else os.path.dirname(profile_path) or "/tmp"
    )
    started = False
    # XLA trace capture defaults ON, matching the behavior of this API
    # before the per-op table existed (rounds 1-2 always started a
    # trace); PADDLE_TPU_XLA_TRACE=0 opts out for op-table-only CI runs
    if os.environ.get("PADDLE_TPU_XLA_TRACE", "1") != "0":
        try:
            jax.profiler.start_trace(trace_dir)
            started = True
        except Exception:
            pass  # a trace may already be running
    prev = _active_collector
    collector = OpCostCollector()
    _active_collector = collector
    t0 = time.time()
    try:
        yield
    finally:
        elapsed = time.time() - t0
        _active_collector = prev
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
        _events.append(("profiler_span", elapsed))
        table = collector.table(
            sorted_key if sorted_key != "default" else None
        )
        del _last_profile[:]
        _last_profile.extend(table)
        _print_table(table, elapsed)


@contextlib.contextmanager
def record_event(name):
    """RAII timing (reference platform/profiler.h RecordEvent)."""
    t0 = time.time()
    try:
        yield
    finally:
        _events.append((name, time.time() - t0))


def get_events():
    return list(_events)


def device_memory_stats(device=None):
    """Per-device memory counters (bytes_in_use, peak_bytes_in_use,
    bytes_limit, ...) straight from the runtime — the observability the
    reference exposed through its allocator stats
    (memory/detail/buddy_allocator). Returns {} when the backend does
    not report memory (e.g. the CPU test fixture)."""
    import jax

    d = device if device is not None else jax.local_devices()[0]
    stats = getattr(d, "memory_stats", None)
    if stats is None:
        return {}
    try:
        return dict(stats() or {})
    except Exception:
        return {}


__all__.append("device_memory_stats")
