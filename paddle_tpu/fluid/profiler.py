"""Profiler (reference python/paddle/v2/fluid/profiler.py:33 cuda_profiler,
:76 profiler; C++ platform/profiler.cc RecordEvent/EnableProfiler:142,
ParseEvents:198).

Two layers on TPU:

* XLA traces via jax.profiler (TensorBoard/XProf) — the deep-dive path.
* A per-op COST TABLE (reference ParseEvents parity): inside a
  ``with profiler(...)`` block the Executor switches to an interpret-mode
  timed run — each forward op executes eagerly on the device and is
  synchronised + wall-clock timed; a training program's backward+update
  then runs once through the normal fused path (one row) so update
  semantics are unchanged. On exit the sorted table prints and is
  available programmatically via ``last_profile()``.
"""

from __future__ import annotations

import contextlib
import os
import time

import jax

__all__ = [
    "cuda_profiler", "reset_profiler", "profiler", "record_event",
    "get_events", "last_profile", "active_op_collector",
]

_events = []
_last_profile = []
_active_collector = None


class OpCostCollector(object):
    """op type -> (calls, total, min, max) wall-clock seconds."""

    def __init__(self):
        self.rows = {}

    def record(self, op_type: str, seconds: float):
        row = self.rows.get(op_type)
        if row is None:
            self.rows[op_type] = [1, seconds, seconds, seconds]
        else:
            row[0] += 1
            row[1] += seconds
            row[2] = min(row[2], seconds)
            row[3] = max(row[3], seconds)

    def table(self, sorted_key=None):
        """[{Event, Calls, Total, Min, Max, Ave}] in ms, sorted like the
        reference (profiler.py sorted_key in calls/total/max/min/ave)."""
        out = [
            {
                "Event": op,
                "Calls": calls,
                "Total": total * 1e3,
                "Min": mn * 1e3,
                "Max": mx * 1e3,
                "Ave": total / calls * 1e3,
            }
            for op, (calls, total, mn, mx) in self.rows.items()
        ]
        key = {
            "calls": "Calls", "total": "Total", "max": "Max",
            "min": "Min", "ave": "Ave",
        }.get(sorted_key)
        if key:
            out.sort(key=lambda r: r[key], reverse=True)
        return out


def active_op_collector():
    """The executor checks this each run; non-None switches it to the
    interpret-mode timed path."""
    return _active_collector


def last_profile():
    """The table from the most recent profiler() block."""
    return list(_last_profile)


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    """Kept for API parity; records an XLA trace to the given directory."""
    with profiler("All", profile_path=output_file):
        yield


def reset_profiler():
    _events.clear()
    del _last_profile[:]


def _print_table(table, elapsed):
    print("\n------------------------->     Profiling Report     "
          "<-------------------------\n")
    print("Place: TPU    Total time span: %.4fs" % elapsed)
    hdr = "%-32s %8s %12s %12s %12s %12s" % (
        "Event", "Calls", "Total(ms)", "Min(ms)", "Max(ms)", "Ave(ms)")
    print(hdr)
    for r in table:
        print("%-32s %8d %12.4f %12.4f %12.4f %12.4f" % (
            r["Event"][:32], r["Calls"], r["Total"], r["Min"], r["Max"],
            r["Ave"]))


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    """Reference fluid.profiler.profiler parity: times every executor run
    in the block per-op and prints the sorted cost table on exit."""
    global _active_collector
    if state not in ["CPU", "GPU", "All", "TPU"]:
        raise ValueError("state must be 'CPU', 'GPU', 'TPU' or 'All'")
    if sorted_key not in (None, "default", "calls", "total", "max", "min",
                          "ave"):
        raise ValueError("unsupported sorted_key %r" % sorted_key)
    trace_dir = (
        profile_path if os.path.isdir(profile_path)
        else os.path.dirname(profile_path) or "/tmp"
    )
    started = False
    # XLA trace capture defaults ON, matching the behavior of this API
    # before the per-op table existed (rounds 1-2 always started a
    # trace); PADDLE_TPU_XLA_TRACE=0 opts out for op-table-only CI runs
    if os.environ.get("PADDLE_TPU_XLA_TRACE", "1") != "0":
        try:
            jax.profiler.start_trace(trace_dir)
            started = True
        except Exception:
            pass  # a trace may already be running
    prev = _active_collector
    collector = OpCostCollector()
    _active_collector = collector
    t0 = time.time()
    try:
        yield
    finally:
        elapsed = time.time() - t0
        _active_collector = prev
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
        _events.append(("profiler_span", elapsed))
        table = collector.table(
            sorted_key if sorted_key != "default" else None
        )
        del _last_profile[:]
        _last_profile.extend(table)
        _print_table(table, elapsed)


@contextlib.contextmanager
def record_event(name):
    """RAII timing (reference platform/profiler.h RecordEvent)."""
    t0 = time.time()
    try:
        yield
    finally:
        _events.append((name, time.time() - t0))


def get_events():
    return list(_events)


def device_memory_stats(device=None):
    """Per-device memory counters (bytes_in_use, peak_bytes_in_use,
    bytes_limit, ...) straight from the runtime — the observability the
    reference exposed through its allocator stats
    (memory/detail/buddy_allocator). Returns {} when the backend does
    not report memory (e.g. the CPU test fixture)."""
    import jax

    d = device if device is not None else jax.local_devices()[0]
    stats = getattr(d, "memory_stats", None)
    if stats is None:
        return {}
    try:
        return dict(stats() or {})
    except Exception:
        return {}


__all__.append("device_memory_stats")


# ---------------------------------------------------------------------
# compiled-step per-op profiling (r4): the interpret-mode table above
# times ops EAGERLY; this path reads the truth of the FUSED program —
# every scheduled HLO instruction of the compiled step is attributed
# back to the fluid op that produced it via the `op:<type>` named-scope
# tags lowering stamps into HLO metadata (core/lowering.py run_op), and
# the measured compiled-step wall time is distributed over ops by each
# instruction's roofline time — max(HBM time from operand+output bytes,
# MXU time from conv/dot FLOPs). Backward instructions (op_name carries
# XLA's transpose(...) wrapper) land on "<op>_grad" rows, mirroring the
# reference's per-grad-op rows (platform/profiler.cc:198 ParseEvents).
# ---------------------------------------------------------------------

import re as _re

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}
_SHAPE_RE = _re.compile(r"\b([a-z]+\d*)\[([\d,]*)\]")
_INST_RE = _re.compile(r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(.*)$")
_OPNAME_RE = _re.compile(r'op_name="([^"]*)"')
_TAG_RE = _re.compile(r"op:([\w.]+)")


def _shape_bytes(type_str):
    total = 0
    for dt, dims in _SHAPE_RE.findall(type_str):
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dt, 4)
    return total


def _shape_elems(type_str):
    """Element count of the FIRST shape in an HLO type string."""
    m = _SHAPE_RE.search(type_str)
    if not m:
        return 0
    n = 1
    if m.group(2):
        for d in m.group(2).split(","):
            n *= int(d)
    return n


# v5e ridge point (peak bf16 flops / HBM bytes per second ~= 197e12 /
# 819e9). Only the RATIO enters the modeled per-op shares below; override
# for other parts.
RIDGE_FLOPS_PER_BYTE = float(
    os.environ.get("PADDLE_TPU_RIDGE_FLOPS_PER_BYTE", "240.5")
)

_WINDOW_RE = _re.compile(r"window=\{([^}]*)\}")
_DIMLABEL_RE = _re.compile(r"dim_labels=([\w?]+_[\w?]+->[\w?]+)")
_LHS_CONTRACT_RE = _re.compile(r"lhs_contracting_dims=\{([\d,]*)\}")


def _window_fields(window_str):
    """{'size': [..], 'stride': [..], 'pad_lo'/'pad_hi': [..],
    'lhs_dilate'/'rhs_dilate': [..]} from an HLO window attribute body
    ('size=56x56 pad=55_55x55_55 lhs_dilate=2x2 rhs_reversal=1x1')."""
    out = {}
    for field in window_str.split():
        if "=" not in field:
            continue
        k, v = field.split("=", 1)
        parts = v.split("x")
        if k == "pad":
            out["pad_lo"] = [int(p.split("_")[0]) for p in parts]
            out["pad_hi"] = [int(p.split("_")[1]) for p in parts]
        elif k in ("size", "stride", "lhs_dilate", "rhs_dilate"):
            out[k] = [int(p) for p in parts]
    return out


def _conv_valid_taps(out_size, w, stride, pad_lo, pad_hi, lhs_dil, rhs_dil):
    """Sum over output positions of IN-BOUNDS, non-dilation-zero kernel
    taps along one spatial dim — the real MAC count per (batch, feature,
    contracted-channel) triple, matching XLA's cost analysis: a backward
    conv with a 56x56 window and pad=55 mostly multiplies padding and
    would otherwise be overcounted ~8x."""
    win_dil = (w - 1) * rhs_dil + 1
    base_dil = (out_size - 1) * stride + win_dil - pad_lo - pad_hi
    total = 0
    for o in range(out_size):
        start = o * stride - pad_lo
        for k in range(w):
            loc = start + k * rhs_dil
            if 0 <= loc < base_dil and loc % lhs_dil == 0:
                total += 1
    return total


def _instr_flops(name, rest, types):
    """Estimated FLOPs of one HLO instruction (convolution/dot; 0 for
    everything else — elementwise flops are noise next to HBM traffic).
    `types` is the enclosing computation's {instr: result type} table
    (operands are referenced by name, their shapes live there).

    convolution: 2 * non-spatial out elems * valid window taps *
    per-group contracted input-feature dim (read off the rhs operand
    shape via dim_labels — works for forward, grad-input (dilated) and
    grad-filter convs alike).
    dot: 2 * out_elems * prod(lhs contracting dim sizes)."""
    if " convolution(" in rest or rest.startswith("convolution("):
        dl = _DIMLABEL_RE.search(rest)
        wm = _WINDOW_RE.search(rest)
        sm_out = _SHAPE_RE.search(rest.split(" ")[0])
        if not (dl and sm_out and sm_out.group(2)):
            return 0.0
        out_dims = [int(d) for d in sm_out.group(2).split(",")]
        out_labels = dl.group(1).split("->")[1]
        spatial_pos = [i for i, c in enumerate(out_labels) if c.isdigit()]
        nonspatial = 1
        for i, d in enumerate(out_dims):
            if i not in spatial_pos:
                nonspatial *= d
        w = _window_fields(wm.group(1)) if wm else {}
        sizes = w.get("size", [1] * len(spatial_pos))
        strides = w.get("stride", [1] * len(sizes))
        pad_lo = w.get("pad_lo", [0] * len(sizes))
        pad_hi = w.get("pad_hi", [0] * len(sizes))
        lhs_dil = w.get("lhs_dilate", [1] * len(sizes))
        rhs_dil = w.get("rhs_dilate", [1] * len(sizes))
        taps = 1.0
        for j, pos in enumerate(spatial_pos):
            if j >= len(sizes):
                break
            taps *= _conv_valid_taps(
                out_dims[pos], sizes[j], strides[j], pad_lo[j], pad_hi[j],
                lhs_dil[j], rhs_dil[j],
            )
        contracted = 1
        ops = _re.findall(r"%([\w.\-]+)", rest.split("(", 1)[1])
        if len(ops) >= 2 and ops[1] in types:
            rhs_labels = dl.group(1).split("_")[1].split("->")[0]
            i_pos = rhs_labels.find("i")
            sm = _SHAPE_RE.search(types[ops[1]])
            if i_pos >= 0 and sm and sm.group(2):
                dims = [int(d) for d in sm.group(2).split(",")]
                if i_pos < len(dims):
                    contracted = dims[i_pos]
        return 2.0 * nonspatial * taps * contracted
    if " dot(" in rest or rest.startswith("dot("):
        out_elems = _shape_elems(rest.split(" ")[0])
        contracted = 1
        cm = _LHS_CONTRACT_RE.search(rest)
        ops = _re.findall(r"%([\w.\-]+)", rest.split("(", 1)[1])
        if cm and ops and ops[0] in types:
            sm = _SHAPE_RE.search(types[ops[0]])
            if sm and sm.group(2):
                dims = [int(d) for d in sm.group(2).split(",")]
                for ix in (int(x) for x in cm.group(1).split(",") if x):
                    if ix < len(dims):
                        contracted *= dims[ix]
        return 2.0 * out_elems * contracted
    return 0.0


def _computation_flops(hlo_text):
    """{computation_name: total conv/dot FLOPs} over every non-entry
    computation — so an entry `fusion(...) calls=%comp` instruction can
    be charged for the matmul work hidden inside its fused computation
    (transformer steps fuse dots; ResNet convs stay at entry level)."""
    comps = {}
    cur, types, lines = None, {}, []
    for line in hlo_text.splitlines():
        if (not line.startswith(" ") and line.rstrip().endswith("{")
                and "=" not in line.split("{")[0]):
            if line.lstrip().startswith("ENTRY"):
                # entry instructions are walked by parse_hlo_op_costs
                # itself; parsing them here would double the flops work
                cur = None
                continue
            nm = _re.match(r"\s*%?([\w.\-]+)", line)
            cur = nm.group(1) if nm else None
            types, lines = {}, []
            if cur:
                comps[cur] = {"types": types, "lines": lines}
            continue
        if cur and line.startswith(" "):
            im = _INST_RE.match(line)
            if im:
                types[im.group(1)] = im.group(2).split(" ")[0]
                lines.append((im.group(1), im.group(2)))
    out = {}
    for cname, c in comps.items():
        fl = 0.0
        for name, rest in c["lines"]:
            fl += _instr_flops(name, rest, c["types"])
        if fl:
            out[cname] = fl
    return out


def _entry_lines(hlo_text):
    """The ENTRY computation's lines only — a computation printed AFTER
    the entry must never leak rows."""
    lines = []
    in_entry = False
    depth = 0
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY "):
            in_entry = True
            depth = line.count("{") - line.count("}")
            continue
        if in_entry:
            depth += line.count("{") - line.count("}")
            if depth <= 0:
                break
            lines.append(line)
    return lines


def _line_tag(line):
    """Op provenance tag of one HLO line ('[xla]' when untagged);
    backward instructions (op_name carries XLA's transpose(...) wrapper)
    land on '<op>_grad' rows."""
    onm = _OPNAME_RE.search(line)
    if onm:
        t = _TAG_RE.search(onm.group(1))
        if t:
            tag = t.group(1)
            if "transpose(" in onm.group(1):
                tag += "_grad"  # cotangent-pass instruction
            return tag
    return "[xla]"


_CALLS_RE = _re.compile(r"calls=%?([\w.\-]+)")
_OPCODE_RE = _re.compile(r"\b([a-z][a-z0-9\-]*)\(")

# Overlapped memory-movement / bookkeeping instructions: XLA hides them
# behind compute (async weight-prefetch slices, aliasing bitcasts), so
# they carry bytes but ~zero serial time — billing them serially made
# the '[xla]' row claim 58% of the modeled step vs 22% measured on-chip
# (BENCH_r05_builder.jsonl profiler_reconciliation). Synchronous VMEM
# staging `copy`/`copy-done` instructions are NOT here: the on-chip
# trace shows they DO serialize (~25% of the ResNet step at b=32);
# `copy-start` alone stays free so the start/done pair is billed once.
_OVERLAPPED_OPCODES = {
    "copy-start", "async-start", "async-done",
    "slice-start", "slice-done", "bitcast", "bitcast-convert",
}


def _opcode(rest):
    """HLO opcode of an instruction body ('bf16[...]{...} fusion(%a)' ->
    'fusion'). Tuple-typed async instructions bury the opcode mid-line;
    the first lowercase identifier followed by '(' is it (dtype tokens
    carry digits/brackets, layout T()/S() tokens are uppercase)."""
    m = _OPCODE_RE.search(rest)
    return m.group(1) if m else ""


def parse_hlo_op_costs(hlo_text):
    """{op_row: {'instructions': n, 'bytes': b, 'flops': f, 'teq': t}}
    from scheduled HLO text. Only the ENTRY computation's instructions
    count (fusions are single scheduled instructions; their internals are
    not separately scheduled) — but conv/dot FLOPs hiding inside a fused
    computation are charged to the entry `fusion` instruction that
    `calls=` it (XLA:TPU fuses BN stats into convs, dots into transformer
    blocks). Instructions with no op tag pool under '[xla]'.

    'teq' is the roofline time proxy in byte-equivalents:
    max(bytes, flops / RIDGE_FLOPS_PER_BYTE) — a compute-bound conv is
    weighted by MXU time, a bandwidth-bound fusion by HBM time. Shares
    of `teq` are the modeled per-op time split."""
    entry_lines = _entry_lines(hlo_text)
    comp_flops = _computation_flops(hlo_text)

    # symbol table: instruction name -> result type string
    types = {}
    for line in entry_lines:
        m = _INST_RE.match(line)
        if m:
            types[m.group(1)] = m.group(2).split(" ")[0]

    rows = {}
    for line in entry_lines:
        m = _INST_RE.match(line)
        if not m:
            continue
        name, rest = m.groups()
        opcode = _opcode(rest)
        if opcode in ("parameter", "constant", "tuple", "get-tuple-element"):
            continue
        byts = _shape_bytes(types.get(name, ""))
        for ref in _re.findall(r"%([\w.\-]+)", rest):
            if ref in types and ref != name:
                byts += _shape_bytes(types[ref])
        flops = _instr_flops(name, rest, types)
        if opcode == "fusion":
            cm = _CALLS_RE.search(rest)
            if cm:
                flops += comp_flops.get(cm.group(1), 0.0)
        overlapped = opcode in _OVERLAPPED_OPCODES or (
            opcode == "custom-call"
            and ("Bitcast" in rest or "Sharding" in rest)
        )
        row = rows.setdefault(
            _line_tag(line), {"instructions": 0, "bytes": 0, "flops": 0.0,
                              "teq": 0.0}
        )
        row["instructions"] += 1
        row["bytes"] += byts
        row["flops"] += flops
        if not overlapped:
            row["teq"] += max(byts, flops / RIDGE_FLOPS_PER_BYTE)
    return rows


def compiled_profile(exe, program, feed, fetch_list, runs=3,
                     sorted_key="total"):
    """Per-op cost table of the COMPILED training step.

    Runs the program once to compile (and prime the executor cache),
    re-lowers the cached signature to read the scheduled HLO, times
    `runs` steps wall-clock, and splits the measured per-step time over
    op rows by attributed memory traffic. Returns (table, meta) where
    table rows follow OpCostCollector.table() ({'Event', 'Calls',
    'Total', ...} — Total in ms) and meta carries the raw bytes and the
    XLA cost-analysis flops for the step."""
    import numpy as _np

    exe._capture_avals = True
    try:
        exe.run(program, feed=feed, fetch_list=fetch_list)
        entry, avals, host_args = exe._last_exec
    finally:
        exe._capture_avals = False
        # the host snapshot is a full copy of every param: don't park it
        # on the executor past this call
        exe._last_exec = None
    lowered = entry.lower(*avals)
    compiled = lowered.compile()
    rows = parse_hlo_op_costs(compiled.as_text())

    # pure device time: fresh device args per run (the entry donates its
    # buffers), timed around the cached jitted entry with
    # block_until_ready — host feed upload / numpy fetch conversion stay
    # OUT of the op rows (ADVICE r4, profiler.py:309). Bare device_put
    # would fight a mesh-jitted entry's in_shardings, so sharded
    # executors fall back to end-to-end timing.
    dev_s = None
    if exe._resolve_mesh() is None:
        dev_s = 0.0
        for _ in range(runs):
            dev_args = jax.tree_util.tree_map(
                lambda a: jax.device_put(a) if hasattr(a, "shape") else a,
                host_args,
            )
            jax.block_until_ready(dev_args)
            t0 = time.time()
            out_dev = entry(*dev_args)
            jax.block_until_ready(out_dev)
            dev_s += time.time() - t0
        dev_s /= runs

    # end-to-end wall time (host feed + fetch included) for the meta row
    t0 = time.time()
    for _ in range(runs):
        out = exe.run(program, feed=feed, fetch_list=fetch_list)
    _np.asarray(out[0])  # sync
    e2e_s = (time.time() - t0) / runs
    step_s = dev_s if dev_s is not None else e2e_s

    # roofline-time split: each row's share is max(HBM time, MXU time) in
    # byte-equivalents (teq) — on-chip reconciliation against jax.profiler
    # traces showed a bytes-only split under-weighting the compute-bound
    # backward convs by ~3x (BENCH_r05_builder.jsonl profiler_reconciliation)
    total_teq = sum(r["teq"] for r in rows.values()) or 1
    table = [
        {
            "Event": tag,
            "Calls": r["instructions"],
            "Total": step_s * 1e3 * r["teq"] / total_teq,
            "Min": 0.0,
            "Max": 0.0,
            "Ave": step_s * 1e3 * r["teq"] / total_teq
            / max(r["instructions"], 1),
            "Bytes": r["bytes"],
            "Flops": r["flops"],
        }
        for tag, r in rows.items()
    ]
    key = {"calls": "Calls", "total": "Total", "ave": "Ave"}.get(
        sorted_key, "Total"
    )
    table.sort(key=lambda r: r[key], reverse=True)
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    meta = {
        # device-only when timing_mode == "device"; end-to-end otherwise
        "step_seconds": step_s,
        "e2e_seconds": e2e_s,         # exe.run incl. host feed/fetch
        "host_overhead_seconds": (
            max(e2e_s - step_s, 0.0) if dev_s is not None else None
        ),
        "timing_mode": "device" if dev_s is not None else "e2e",
        "flops": float((ca or {}).get("flops", 0.0)),
        "bytes_attributed": sum(r["bytes"] for r in rows.values()),
        "teq_attributed": total_teq,
    }
    _print_table(table, step_s * runs)
    return table, meta


__all__ += ["compiled_profile", "parse_hlo_op_costs"]


def parse_hlo_instr_tags(hlo_text):
    """{instruction_name: op_tag} over the ENTRY computation — the join
    key between a device profiler trace (events named per HLO
    instruction) and the lowering's op provenance metadata. Shares the
    entry walk and tag extraction with parse_hlo_op_costs so the
    modeled and measured tables can never disagree about ownership."""
    tags = {}
    for line in _entry_lines(hlo_text):
        m = _INST_RE.match(line)
        if m:
            tags[m.group(1)] = _line_tag(line)
    return tags


def _parse_trace_durations(trace_dir):
    """Per-plane sums of per-event durations (us) from a
    jax.profiler.trace output directory: {pid: {event_name: us}}. Events
    carry the HLO instruction name verbatim ('fusion.123',
    'dot_general.1') on the device plane; host planes carry Python /
    runtime spans that must never pollute the device accounting — the
    caller picks the plane that actually holds the compiled step's
    instructions."""
    import glob
    import gzip
    import json as _json

    planes = {}
    for p in glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    ):
        tr = _json.loads(gzip.open(p).read())
        for e in tr.get("traceEvents", []):
            if e.get("ph") != "X" or "dur" not in e:
                continue
            name = e.get("name", "")
            if name.startswith("end: "):
                continue
            durs = planes.setdefault(e.get("pid", 0), {})
            durs[name] = durs.get(name, 0.0) + float(e["dur"])
    return planes


def trace_profile(exe, program, feed, fetch_list, runs=3):
    """Reconcile the traffic-MODELED per-op attribution against
    MEASURED per-instruction device times from a real `jax.profiler`
    trace (r4 verdict #4; the reference measured per-op times with CUDA
    events, platform/profiler.cc:142,198 — this is the TPU equivalent:
    XLA instruction events joined back to op provenance through the HLO
    metadata tags lowering stamps).

    Returns (table, meta): rows {'Event', 'measured_ms',
    'modeled_ms', 'disagreement'} sorted by measured time;
    meta['top5_max_disagreement'] is the reconciliation verdict — the
    share-of-step disagreement between the two attributions over the
    five biggest measured rows. Works on any backend with profiler
    support (CPU validates the machinery; TPU gives real device
    times)."""
    import tempfile

    import jax
    import numpy as _np

    exe._capture_avals = True
    try:
        exe.run(program, feed=feed, fetch_list=fetch_list)
        entry, avals, host_args = exe._last_exec
    finally:
        exe._capture_avals = False
        exe._last_exec = None
    compiled = entry.lower(*avals).compile()
    txt = compiled.as_text()
    tags = parse_hlo_instr_tags(txt)
    model_rows = parse_hlo_op_costs(txt)

    import shutil

    trace_dir = tempfile.mkdtemp(prefix="ptpu_trace_")
    try:
        with jax.profiler.trace(trace_dir):
            for _ in range(runs):
                out = exe.run(program, feed=feed, fetch_list=fetch_list)
            _np.asarray(out[0])  # sync inside the trace window
        planes = _parse_trace_durations(trace_dir)
    finally:
        shutil.rmtree(trace_dir, ignore_errors=True)

    # join: instruction event -> op tag, on the DEVICE plane only. The
    # trace holds one plane per pid — host Python/runtime threads, the
    # dispatch queue, and the device's instruction track. Joining every
    # plane inflated unmatched_ms ~100x (host spans nest device events;
    # r5 on-chip capture). The device plane is identified, not assumed:
    # the pid whose events best match the entry's instruction names.
    # module-level / bookkeeping spans on the device plane (the whole
    # 'jit_step(...)' execution span, numeric queue ids) nest the
    # instruction events — counting them as unmatched instruction time
    # double-bills the entire step
    _instr_name = _re.compile(r"^[a-z][\w.\-]*$")

    def _match(durs):
        meas, unmatched = {}, 0.0
        for name, us in durs.items():
            tag = tags.get(name)
            if tag is None:
                tag = tags.get(name.split(" ")[0])
            if tag is None:
                base = name.split(" ")[0]
                if _instr_name.match(base) and not base.startswith("jit_"):
                    unmatched += us
                continue
            meas[tag] = meas.get(tag, 0.0) + us
        return meas, unmatched

    best = ({}, 0.0)
    for durs in planes.values():
        cand = _match(durs)
        if sum(cand[0].values()) > sum(best[0].values()):
            best = cand
    measured, unmatched_us = best
    if not measured:
        # no plane matched a single instruction tag (renamed events,
        # empty trace): surface the largest instruction-like residue
        # instead of reporting a silently-clean 0.0 join
        unmatched_us = max(
            (_match(d)[1] for d in planes.values()), default=0.0
        )
    total_meas = sum(measured.values()) or 1.0
    total_teq = sum(r["teq"] for r in model_rows.values()) or 1

    table = []
    for tag in sorted(set(measured) | set(model_rows)):
        m_us = measured.get(tag, 0.0)
        t = model_rows.get(tag, {}).get("teq", 0)
        meas_share = m_us / total_meas
        model_share = t / total_teq
        table.append({
            "Event": tag,
            "measured_ms": round(m_us / 1e3 / runs, 4),
            "measured_share": round(meas_share, 4),
            "modeled_share": round(model_share, 4),
            "disagreement": round(abs(meas_share - model_share), 4),
        })
    table.sort(key=lambda r: -r["measured_ms"])
    top5 = table[:5]
    meta = {
        "runs": runs,
        "measured_total_ms": round(total_meas / 1e3 / runs, 3),
        # leftover time on the DEVICE plane only (infeed, runtime ops)
        "unmatched_ms": round(unmatched_us / 1e3 / runs, 3),
        "top5_max_disagreement": max(
            (r["disagreement"] for r in top5), default=0.0
        ),
        "backend": jax.default_backend(),
    }
    return table, meta


__all__ += ["trace_profile", "parse_hlo_instr_tags"]
