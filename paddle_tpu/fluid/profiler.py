"""Profiler (reference python/paddle/v2/fluid/profiler.py:33 cuda_profiler,
:76 profiler; C++ platform/profiler.cc RecordEvent/EnableProfiler).

On TPU the per-op CUDA-event machinery is replaced by (a) XLA traces via
jax.profiler (viewable in TensorBoard/XProf) and (b) a host-side wall-clock
table per executor run, since a fused XLA step has no per-op boundary on
device. The context-manager API is kept."""

from __future__ import annotations

import contextlib
import os
import time

import jax

__all__ = ["cuda_profiler", "reset_profiler", "profiler"]

_events = []


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    """Kept for API parity; records an XLA trace to the given directory."""
    with profiler("All", profile_path=output_file):
        yield


def reset_profiler():
    _events.clear()


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    if state not in ["CPU", "GPU", "All", "TPU"]:
        raise ValueError("state must be 'CPU', 'GPU', 'TPU' or 'All'")
    trace_dir = profile_path if os.path.isdir(profile_path) else os.path.dirname(profile_path) or "/tmp"
    started = False
    try:
        jax.profiler.start_trace(trace_dir)
        started = True
    except Exception:
        pass  # a trace may already be running
    t0 = time.time()
    try:
        yield
    finally:
        elapsed = time.time() - t0
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
        _events.append(("profiler_span", elapsed))
        print(
            "[paddle_tpu.profiler] span=%.4fs trace_dir=%s (open with "
            "TensorBoard / xprof)" % (elapsed, trace_dir)
        )


@contextlib.contextmanager
def record_event(name):
    """RAII timing (reference platform/profiler.h RecordEvent)."""
    t0 = time.time()
    try:
        yield
    finally:
        _events.append((name, time.time() - t0))


def get_events():
    return list(_events)
