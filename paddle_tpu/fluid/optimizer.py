"""Optimizers as graph rewrites (reference python/paddle/v2/fluid/optimizer.py
and the op-level math in operators/{sgd,momentum,adagrad,adam,adamax,
decayed_adagrad,rmsprop,adadelta,ftrl}_op.cc; legacy parity:
paddle/parameter/FirstOrderOptimizer.h).

`minimize` appends the autodiff marker (backward.py), regularization +
clipping rewrites on gradient vars, then one optimizer-update op per
parameter. The whole train step — forward, vjp backward, decay, clip,
update — lowers to ONE fused XLA computation.
"""

from __future__ import annotations

from collections import defaultdict

from .backward import append_backward
from .clip import append_gradient_clip_ops, error_clip_callback
from .core.program import Program, Variable, default_main_program, default_startup_program, unique_name
from .initializer import Constant
from .layer_helper import LayerHelper
from .layers import tensor as tensor_layers
from .regularizer import append_regularization_ops

__all__ = [
    "Optimizer",
    "SGD",
    "Momentum",
    "Adagrad",
    "Adam",
    "Adamax",
    "DecayedAdagrad",
    "RMSProp",
    "Adadelta",
    "Ftrl",
    "SGDOptimizer",
    "MomentumOptimizer",
    "AdagradOptimizer",
    "AdamOptimizer",
    "AdamaxOptimizer",
    "DecayedAdagradOptimizer",
    "RMSPropOptimizer",
    "AdadeltaOptimizer",
    "FtrlOptimizer",
]


class Optimizer(object):
    def __init__(self, learning_rate, global_step=None, regularization=None):
        if not isinstance(learning_rate, (float, Variable)):
            raise TypeError("learning rate should be float or Variable")
        self._global_step = global_step
        self.regularization = regularization
        self._global_learning_rate = learning_rate
        self._learning_rate_var = None
        # {accum_name: {param_name: accum_var}}
        self._accumulators = defaultdict(dict)
        self.helper = None

    # --- learning rate --------------------------------------------------
    def _create_global_learning_rate(self):
        if isinstance(self._global_learning_rate, Variable):
            self._learning_rate_var = self._global_learning_rate
            return
        if self._learning_rate_var is None:
            self._learning_rate_var = tensor_layers.create_global_var(
                name=unique_name("learning_rate"),
                shape=[1],
                value=float(self._global_learning_rate),
                dtype="float32",
                persistable=True,
            )

    def global_learning_rate(self):
        return self._learning_rate_var

    def _create_param_lr(self, param_and_grad):
        param_lr = param_and_grad[0].optimize_attr.get("learning_rate", 1.0)
        if param_lr == 1.0:
            return self._learning_rate_var
        from .layers import ops as op_layers

        return op_layers.scale(x=self._learning_rate_var, scale=float(param_lr))

    # --- accumulators ---------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self, block):
        pass

    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0, shape=None):
        if param.name in self._accumulators[name]:
            raise RuntimeError("accumulator %s for %s already exists" % (name, param.name))
        if shape is None:
            shape = param.shape
        assert self.helper is not None
        var = self.helper.create_global_variable(
            name=unique_name(name + "_" + param.name),
            persistable=True,
            dtype=dtype or param.dtype,
            shape=shape,
        )
        self.helper.set_variable_initializer(
            var, initializer=Constant(value=float(fill_value))
        )
        # tensor parallelism: a same-shaped optimizer slot of a sharded
        # parameter must live on the same mesh spec (parallel/mesh.py
        # shard_parameter) — inherit it so users annotate only the param
        prog = var.block.program
        spec = prog.shardings.get(param.name)
        if spec is not None and tuple(shape) == tuple(param.shape):
            prog.shardings[var.name] = spec
        self._accumulators[name][param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    def _increment_global_step(self, block):
        if self._global_step is None:
            return
        block.append_op(
            type="increment",
            inputs={"X": [self._global_step]},
            outputs={"Out": [self._global_step]},
            attrs={"step": 1.0},
        )

    # --- main entry points ---------------------------------------------
    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def create_optimization_pass(self, parameters_and_grads, loss, startup_program=None):
        program = loss.block.program
        self.helper = LayerHelper(self.__class__.__name__)
        self._create_accumulators(
            loss.block, [p[0] for p in parameters_and_grads if p[0].trainable]
        )
        self._create_global_learning_rate()

        optimize_ops = []
        for param_and_grad in parameters_and_grads:
            if param_and_grad[0].trainable and param_and_grad[1] is not None:
                optimize_ops.append(
                    self._append_optimize_op(loss.block, param_and_grad)
                )
        self._finish_update(loss.block)
        self._increment_global_step(loss.block)
        return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        params_grads = append_backward(
            loss, parameter_list, no_grad_set, [error_clip_callback]
        )
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads, self.regularization)
        optimize_ops = self.create_optimization_pass(
            params_grads, loss, startup_program
        )
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            type="sgd",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param_and_grad[0]]},
        )


class MomentumOptimizer(Optimizer):
    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = bool(use_nesterov)

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity_acc = self._get_accumulator(self._velocity_acc_str, param_and_grad[0])
        return block.append_op(
            type="momentum",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "Velocity": [velocity_acc],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={
                "ParamOut": [param_and_grad[0]],
                "VelocityOut": [velocity_acc],
            },
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class AdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, epsilon=1.0e-6, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "adagrad"
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment_acc = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        return block.append_op(
            type="adagrad",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "Moment": [moment_acc],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param_and_grad[0]], "MomentOut": [moment_acc]},
            attrs={"epsilon": self._epsilon},
        )


class AdamOptimizer(Optimizer):
    _moment1_acc_str = "moment1"
    _moment2_acc_str = "moment2"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "adam"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._beta1_pow_acc = None
        self._beta2_pow_acc = None

    def _create_accumulators(self, block, parameters):
        main_block = block.program.global_block()
        self._beta1_pow_acc = self.helper.create_global_variable(
            name=unique_name("beta1_pow_acc"),
            dtype="float32",
            shape=[1],
            persistable=True,
        )
        self.helper.set_variable_initializer(
            self._beta1_pow_acc, initializer=Constant(self._beta1)
        )
        self._beta2_pow_acc = self.helper.create_global_variable(
            name=unique_name("beta2_pow_acc"),
            dtype="float32",
            shape=[1],
            persistable=True,
        )
        self.helper.set_variable_initializer(
            self._beta2_pow_acc, initializer=Constant(self._beta2)
        )
        for p in parameters:
            self._add_accumulator(self._moment1_acc_str, p)
            self._add_accumulator(self._moment2_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment1 = self._get_accumulator(self._moment1_acc_str, param_and_grad[0])
        moment2 = self._get_accumulator(self._moment2_acc_str, param_and_grad[0])
        return block.append_op(
            type="adam",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "LearningRate": [self._create_param_lr(param_and_grad)],
                "Moment1": [moment1],
                "Moment2": [moment2],
                "Beta1Pow": [self._beta1_pow_acc],
                "Beta2Pow": [self._beta2_pow_acc],
            },
            outputs={
                "ParamOut": [param_and_grad[0]],
                "Moment1Out": [moment1],
                "Moment2Out": [moment2],
            },
            attrs={
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
            },
        )

    def _finish_update(self, block):
        """beta^t bookkeeping after all param updates (reference
        optimizer.py:437)."""
        block.append_op(
            type="scale",
            inputs={"X": [self._beta1_pow_acc]},
            outputs={"Out": [self._beta1_pow_acc]},
            attrs={"scale": self._beta1},
        )
        block.append_op(
            type="scale",
            inputs={"X": [self._beta2_pow_acc]},
            outputs={"Out": [self._beta2_pow_acc]},
            attrs={"scale": self._beta2},
        )


class AdamaxOptimizer(Optimizer):
    _moment_acc_str = "moment"
    _inf_norm_acc_str = "inf_norm"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "adamax"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._beta1_pow_acc = None

    def _create_accumulators(self, block, parameters):
        self._beta1_pow_acc = self.helper.create_global_variable(
            name=unique_name("beta1_pow_acc"),
            dtype="float32",
            shape=[1],
            persistable=True,
        )
        self.helper.set_variable_initializer(
            self._beta1_pow_acc, initializer=Constant(self._beta1)
        )
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)
            self._add_accumulator(self._inf_norm_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        inf_norm = self._get_accumulator(self._inf_norm_acc_str, param_and_grad[0])
        return block.append_op(
            type="adamax",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "LearningRate": [self._create_param_lr(param_and_grad)],
                "Moment": [moment],
                "InfNorm": [inf_norm],
                "Beta1Pow": [self._beta1_pow_acc],
            },
            outputs={
                "ParamOut": [param_and_grad[0]],
                "MomentOut": [moment],
                "InfNormOut": [inf_norm],
            },
            attrs={
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
            },
        )

    def _finish_update(self, block):
        block.append_op(
            type="scale",
            inputs={"X": [self._beta1_pow_acc]},
            outputs={"Out": [self._beta1_pow_acc]},
            attrs={"scale": self._beta1},
        )


class DecayedAdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, decay=0.95, epsilon=1.0e-6, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "decayed_adagrad"
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment_acc = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        return block.append_op(
            type="decayed_adagrad",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "Moment": [moment_acc],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param_and_grad[0]], "MomentOut": [moment_acc]},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
        )


class RMSPropOptimizer(Optimizer):
    _moment_acc_str = "momentum"
    _mean_square_acc_str = "mean_square"

    def __init__(self, learning_rate, rho=0.95, epsilon=1.0e-6, momentum=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "rmsprop"
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)
            self._add_accumulator(self._mean_square_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        momentum_acc = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        mean_square_acc = self._get_accumulator(self._mean_square_acc_str, param_and_grad[0])
        return block.append_op(
            type="rmsprop",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "Moment": [momentum_acc],
                "MeanSquare": [mean_square_acc],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={
                "ParamOut": [param_and_grad[0]],
                "MomentOut": [momentum_acc],
                "MeanSquareOut": [mean_square_acc],
            },
            attrs={
                "epsilon": self._epsilon,
                "decay": self._rho,
                "momentum": self._momentum,
            },
        )


class AdadeltaOptimizer(Optimizer):
    _avg_squared_grad_acc_str = "_avg_squared_grad"
    _avg_squared_update_acc_str = "_avg_squared_update"

    def __init__(self, learning_rate, epsilon=1.0e-6, rho=0.95, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "adadelta"
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._avg_squared_grad_acc_str, p)
            self._add_accumulator(self._avg_squared_update_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        avg_squared_grad = self._get_accumulator(
            self._avg_squared_grad_acc_str, param_and_grad[0]
        )
        avg_squared_update = self._get_accumulator(
            self._avg_squared_update_acc_str, param_and_grad[0]
        )
        return block.append_op(
            type="adadelta",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "AvgSquaredGrad": [avg_squared_grad],
                "AvgSquaredUpdate": [avg_squared_update],
            },
            outputs={
                "ParamOut": [param_and_grad[0]],
                "AvgSquaredGradOut": [avg_squared_grad],
                "AvgSquaredUpdateOut": [avg_squared_update],
            },
            attrs={"epsilon": self._epsilon, "rho": self._rho},
        )


class FtrlOptimizer(Optimizer):
    _squared_acc_str = "squared"
    _linear_acc_str = "linear"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "ftrl"
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._squared_acc_str, p)
            self._add_accumulator(self._linear_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        squared_acc = self._get_accumulator(self._squared_acc_str, param_and_grad[0])
        linear_acc = self._get_accumulator(self._linear_acc_str, param_and_grad[0])
        return block.append_op(
            type="ftrl",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "SquaredAccumulator": [squared_acc],
                "LinearAccumulator": [linear_acc],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={
                "ParamOut": [param_and_grad[0]],
                "SquaredAccumOut": [squared_acc],
                "LinearAccumOut": [linear_acc],
            },
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
RMSProp = RMSPropOptimizer
Adadelta = AdadeltaOptimizer
Ftrl = FtrlOptimizer


class ModelAverage(object):
    """Averaged parameters (reference parameter/AverageOptimizer.cpp and
    the trainer's catchUp/apply/restore dance, v2/trainer.py:130):
    evaluation and export use a sliding-window arithmetic mean of the
    weight iterates rather than the last SGD iterate.

    TRUE reference semantics (r4 verdict item #6 — previously an EMA
    approximation): three per-param sum accumulators + counters updated
    INSIDE the fused train step by the `average_accumulates` op
    (core/kernels_optim.py — branchless jnp.where form of
    AverageOptimizer.cpp:60-115). The averaged value is the exact mean
    of the last [W, 2W] iterates where W = clamp(num_updates *
    average_window, min_average_window, max_average_window) — the
    window guarantee TrainerConfig.proto:70-75 documents.

    `average_window` is the RATE of updates to average (reference
    optConfig.average_window, e.g. 0.15); `apply()` is a context
    manager that swaps (sum_1+sum_2+sum_3)/(num+old_num) into the scope
    for eval/save and restores the live weights after.

    Call `build(program)` AFTER optimizer.minimize, inside the same
    program_guard. Inside `apply()` run a for_test clone (or any
    inference program): running the TRAINING program there would train
    onward from the averaged weights.
    """

    SUM_SUFFIXES = ("@SUM_1", "@SUM_2", "@SUM_3")
    CNT_SUFFIXES = ("@NUM_ACC", "@OLD_NUM_ACC", "@NUM_UPD")

    @classmethod
    def from_spec(cls, spec):
        """Build from a settings-object spec (tch/v2 ModelAverage). The
        specs carry no min knob; the reference derives it as
        min(10000, max_average_window) (AverageOptimizer.cpp:47-49)."""
        max_w = getattr(spec, "max_average_window", None) or 10000
        return cls(
            average_window=getattr(spec, "average_window", 0.15),
            min_average_window=min(10000, int(max_w)),
            max_average_window=max_w,
        )

    def __init__(self, average_window=0.15, min_average_window=100,
                 max_average_window=10000):
        self.average_window = float(average_window)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self._param_names = []
        self._steps_name = None

    def _slot(self, pname, suffix):
        return pname + suffix

    def build(self, program=None):
        program = program or default_main_program()
        if program is not default_main_program():
            # the var initializers land in the CURRENT guard's programs;
            # a mismatched program would get ops whose vars live (and
            # initialize) elsewhere
            raise ValueError(
                "ModelAverage.build must run inside program_guard of the "
                "program it averages"
            )
        block = program.global_block()
        steps = tensor_layers.create_global_var(
            name=unique_name("model_average_steps"), shape=[1], value=0.0,
            dtype="float32", persistable=True,
        )
        self._steps_name = steps.name
        block.append_op(
            type="increment", inputs={"X": [steps]},
            outputs={"Out": [steps]}, attrs={"step": 1.0},
        )
        for p in block.all_parameters():
            # ParamAttr(do_model_average=False) opts a parameter out
            if not p.trainable or getattr(p, "do_model_average", True) is False:
                continue
            spec = program.shardings.get(p.name)
            sums = []
            for sfx in self.SUM_SUFFIXES:
                v = tensor_layers.create_global_var(
                    name=self._slot(p.name, sfx), shape=list(p.shape),
                    value=0.0, dtype=p.dtype, persistable=True,
                )
                # sum slots of sharded params live on the param's spec
                if spec is not None:
                    program.shardings[v.name] = spec
                sums.append(v)
            cnts = [
                tensor_layers.create_global_var(
                    name=self._slot(p.name, sfx), shape=[1], value=0,
                    dtype="int32", persistable=True,
                )
                for sfx in self.CNT_SUFFIXES
            ]
            self._param_names.append(p.name)
            block.append_op(
                type="average_accumulates",
                inputs={
                    "Param": [p],
                    "InSum1": [sums[0]], "InSum2": [sums[1]],
                    "InSum3": [sums[2]],
                    "InNumAccumulates": [cnts[0]],
                    "InOldNumAccumulates": [cnts[1]],
                    "InNumUpdates": [cnts[2]],
                },
                outputs={
                    "OutSum1": [sums[0]], "OutSum2": [sums[1]],
                    "OutSum3": [sums[2]],
                    "OutNumAccumulates": [cnts[0]],
                    "OutOldNumAccumulates": [cnts[1]],
                    "OutNumUpdates": [cnts[2]],
                },
                attrs={
                    "average_window": self.average_window,
                    "min_average_window": self.min_average_window,
                    "max_average_window": self.max_average_window,
                },
            )
        return self

    def attach(self, scope):
        """Adopt the averaging slots of a LOADED scope (a checkpoint
        trained with averaging) so apply() works without rebuilding the
        training graph. Returns self; slots may be empty if the
        checkpoint carried none."""
        sfx = self.SUM_SUFFIXES[0]
        self._param_names = sorted(
            k[: -len(sfx)] for k in scope.keys() if k.endswith(sfx)
        )
        # bind the steps counter by its exact name family
        # ("model_average_steps" + unique_name suffix). A scope holding
        # MORE than one such var (e.g. a program rebuilt twice into one
        # scope) is ambiguous — binding the wrong counter would silently
        # skew the average, so refuse instead of guessing.
        steps = sorted(
            k for k in scope.keys()
            if k == "model_average_steps"
            or k.startswith("model_average_steps_")
        )
        if len(steps) > 1:
            raise ValueError(
                "scope holds %d model_average_steps counters (%r); "
                "cannot tell which matches the averaged slots — load a "
                "checkpoint produced by a single minimize(), or delete "
                "the stale counters" % (len(steps), steps)
            )
        self._steps_name = steps[0] if steps else None
        return self

    def apply(self, scope=None, need_restore=True):
        """Context manager: swap window-averaged weights into the scope
        (eval/save run on averages), restore live weights on exit.
        Average = (sum_1+sum_2+sum_3)/(num_accumulates +
        old_num_accumulates) — AverageOptimizer.cpp:117 apply()."""
        import contextlib

        import numpy as _np

        from .executor import global_scope

        @contextlib.contextmanager
        def _ctx():
            sc = scope or global_scope()
            t = float(_np.ravel(_np.asarray(sc.get(self._steps_name)))[0])
            if t < 1.0:
                raise RuntimeError(
                    "ModelAverage.apply before any training step: the "
                    "averages are still zero"
                )
            saved = {}
            for pname in self._param_names:
                saved[pname] = sc.get(pname)
                s = sum(
                    _np.asarray(
                        sc.get(self._slot(pname, sfx)), dtype=_np.float64
                    )
                    for sfx in self.SUM_SUFFIXES
                )
                n = int(
                    _np.ravel(sc.get(self._slot(pname, "@NUM_ACC")))[0]
                ) + int(
                    _np.ravel(sc.get(self._slot(pname, "@OLD_NUM_ACC")))[0]
                )
                live = _np.asarray(saved[pname])
                sc.set(pname, (s / max(n, 1)).astype(live.dtype))
            try:
                yield
            finally:
                if need_restore:
                    for pname, val in saved.items():
                        sc.set(pname, val)

        return _ctx()


__all__.append("ModelAverage")


class StaticPruning(object):
    """Static magnitude pruning hook (reference
    parameter/ParameterUpdaterHook.cpp:39 StaticPruningHook /
    HookAttr(type='pruning', sparsity_ratio=...)): a fixed mask keeps the
    largest-|w| (1 - sparsity) fraction of each hooked parameter; every
    update re-applies the mask so pruned weights stay exactly zero.

    TPU-first form: the mask is computed IN the startup program (abs ->
    top_k threshold -> compare), stored as a persistable `@PRUNE_MASK`
    slot, applied once at init and then by graph ops appended after the
    optimizer update — all inside the fused step, no host work.

    Call build(program, startup_program) AFTER minimize and BEFORE
    running the startup program, inside the same program_guard. Parameters are discovered from their
    ParamAttr(update_hook=...) spec (any object with type='pruning' and
    sparsity_ratio), or passed explicitly via `targets`.
    """

    MASK_SUFFIX = "@PRUNE_MASK"

    def __init__(self, sparsity_ratio=None):
        self.sparsity_ratio = sparsity_ratio
        self.masks = {}
        self._built_ratio = {}

    DEFAULT_RATIO = 0.6  # reference ParameterUpdaterHookConfig default

    @staticmethod
    def _hook_ratio(p):
        hook = getattr(p, "update_hook", None)
        if hook is None:
            return None
        hooks = hook if isinstance(hook, (list, tuple)) else [hook]
        for h in hooks:
            if getattr(h, "type", None) == "pruning":
                r = getattr(h, "sparsity_ratio", None)
                return (
                    float(r) if r is not None
                    else StaticPruning.DEFAULT_RATIO
                )
        return None

    def build(self, program=None, startup_program=None, targets=None):
        import numpy as _np

        program = program or default_main_program()
        startup = startup_program or default_startup_program()
        block = program.global_block()
        sblock = startup.global_block()

        if targets is not None:
            if self.sparsity_ratio is None:
                raise ValueError(
                    "build(targets=...) needs StaticPruning("
                    "sparsity_ratio=...)"
                )
            plan = [(p, float(self.sparsity_ratio)) for p in targets]
        else:
            plan = [
                (p, r)
                for p in block.all_parameters()
                for r in [self._hook_ratio(p)]
                if r is not None
            ]
        for p, ratio in plan:
            if not (0.0 < ratio < 1.0):
                raise ValueError(
                    "sparsity_ratio must be in (0, 1), got %r for %s"
                    % (ratio, p.name)
                )
            numel = int(_np.prod(p.shape))
            keep = max(1, int(round(numel * (1.0 - ratio))))
            self._built_ratio[p.name] = ratio
            mask = block.create_var(
                name=p.name + self.MASK_SUFFIX, shape=list(p.shape),
                dtype=p.dtype, persistable=True,
            )
            # mirror into startup so its ops may write it there
            smask = sblock.create_var(
                name=mask.name, shape=list(p.shape), dtype=p.dtype,
                persistable=True,
            )
            def stmp(suffix, shape, dtype=p.dtype):
                return sblock.create_var(
                    name=unique_name(p.name + suffix), shape=list(shape),
                    dtype=dtype,
                )

            # |w| -> flat [1, numel] -> top_k(keep) -> threshold
            a = stmp("@abs", p.shape)
            sblock.append_op(type="abs", inputs={"X": [p.name]},
                             outputs={"Out": [a]}, attrs={})
            flat = stmp("@flat", [1, numel])
            sblock.append_op(type="reshape", inputs={"X": [a]},
                             outputs={"Out": [flat]},
                             attrs={"shape": [1, numel]})
            vals = stmp("@topk", [1, keep])
            idx = stmp("@topki", [1, keep], dtype="int32")
            sblock.append_op(type="top_k", inputs={"X": [flat]},
                             outputs={"Out": [vals], "Indices": [idx]},
                             attrs={"k": keep})
            # mask by INDEX (exactly `keep` survivors even under ties —
            # a threshold compare would keep every tied value)
            zeros = stmp("@zeros", [numel])
            sblock.append_op(type="fill_constant", inputs={},
                             outputs={"Out": [zeros]},
                             attrs={"shape": [numel], "value": 0.0,
                                    "dtype": p.dtype})
            ones = stmp("@ones", [keep])
            sblock.append_op(type="fill_constant", inputs={},
                             outputs={"Out": [ones]},
                             attrs={"shape": [keep], "value": 1.0,
                                    "dtype": p.dtype})
            maskf = stmp("@maskf", [numel])
            sblock.append_op(type="scatter",
                             inputs={"X": [zeros], "Ids": [idx],
                                     "Updates": [ones]},
                             outputs={"Out": [maskf]}, attrs={})
            sblock.append_op(type="reshape", inputs={"X": [maskf]},
                             outputs={"Out": [smask]},
                             attrs={"shape": list(p.shape)})
            # sparsify the initial weights too
            pruned0 = stmp("@p0", p.shape)
            sblock.append_op(type="elementwise_mul",
                             inputs={"X": [p.name], "Y": [smask]},
                             outputs={"Out": [pruned0]}, attrs={})
            sblock.append_op(type="assign", inputs={"X": [pruned0]},
                             outputs={"Out": [p.name]}, attrs={})

            # main program: re-apply after every optimizer update
            t = block.create_var(
                name=unique_name(p.name + "@pruned"), shape=list(p.shape),
                dtype=p.dtype,
            )
            block.append_op(type="elementwise_mul",
                            inputs={"X": [p.name], "Y": [mask]},
                            outputs={"Out": [t]}, attrs={})
            block.append_op(type="assign", inputs={"X": [t]},
                            outputs={"Out": [p.name]}, attrs={})
            self.masks[p.name] = mask.name
        return self

    def recompute(self, scope):
        """Rebuild masks from the CURRENT scope values (host-side) and
        sparsify — for weights loaded from a checkpoint AFTER startup
        ran (the in-startup mask would reflect the discarded random
        init)."""
        import numpy as _np

        for pname, mname in self.masks.items():
            if pname not in scope:
                continue
            w = _np.asarray(scope.get(pname))
            flat = _np.abs(w).ravel()
            keep = max(1, int(round(
                flat.size * (1.0 - self._built_ratio[pname])
            )))
            idx = _np.argpartition(-flat, keep - 1)[:keep]
            mask = _np.zeros_like(flat)
            mask[idx] = 1.0
            mask = mask.reshape(w.shape)
            scope.set(mname, mask.astype(w.dtype))
            scope.set(pname, (w * mask).astype(w.dtype))
        return self


__all__.append("StaticPruning")
