"""Learning-rate decay schedules as graph ops (reference
python/paddle/v2/fluid/learning_rate_decay.py: exponential_decay,
natural_exp_decay, inverse_time_decay, polynomial_decay, piecewise_decay).
Each returns a Variable computed from a float global_step Variable, fed to
Optimizer(learning_rate=...)."""

from __future__ import annotations

from . import layers

__all__ = [
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
]


def _step_f(global_step):
    return layers.cast(x=global_step, dtype="float32")


def exponential_decay(learning_rate, global_step, decay_steps, decay_rate, staircase=False):
    div = layers.elementwise_div(
        x=_step_f(global_step),
        y=layers.fill_constant(shape=[1], dtype="float32", value=float(decay_steps)),
    )
    if staircase:
        div = layers.floor(x=div)
    pow_v = layers.elementwise_pow(
        x=layers.fill_constant(shape=[1], dtype="float32", value=float(decay_rate)),
        y=div,
    )
    return layers.scale(x=pow_v, scale=float(learning_rate))


def natural_exp_decay(learning_rate, global_step, decay_steps, decay_rate, staircase=False):
    div = layers.elementwise_div(
        x=_step_f(global_step),
        y=layers.fill_constant(shape=[1], dtype="float32", value=float(decay_steps)),
    )
    if staircase:
        div = layers.floor(x=div)
    exp_v = layers.exp(x=layers.scale(x=div, scale=-float(decay_rate)))
    return layers.scale(x=exp_v, scale=float(learning_rate))


def inverse_time_decay(learning_rate, global_step, decay_steps, decay_rate, staircase=False):
    div = layers.elementwise_div(
        x=_step_f(global_step),
        y=layers.fill_constant(shape=[1], dtype="float32", value=float(decay_steps)),
    )
    if staircase:
        div = layers.floor(x=div)
    denom = layers.scale(x=div, scale=float(decay_rate), bias=1.0)
    lr = layers.fill_constant(shape=[1], dtype="float32", value=float(learning_rate))
    return layers.elementwise_div(x=lr, y=denom)


def polynomial_decay(learning_rate, global_step, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    gs = _step_f(global_step)
    ds = layers.fill_constant(shape=[1], dtype="float32", value=float(decay_steps))
    if cycle:
        ratio = layers.ceil(x=layers.elementwise_div(
            x=layers.elementwise_max(
                x=gs, y=layers.fill_constant(shape=[1], dtype="float32", value=1.0)
            ),
            y=ds,
        ))
        ds = layers.elementwise_mul(x=ds, y=ratio)
    else:
        gs = layers.elementwise_min(x=gs, y=ds)
    frac = layers.elementwise_div(x=gs, y=ds)
    one_minus = layers.scale(x=frac, scale=-1.0, bias=1.0)
    poly = layers.elementwise_pow(
        x=one_minus,
        y=layers.fill_constant(shape=[1], dtype="float32", value=float(power)),
    )
    return layers.scale(
        x=poly, scale=float(learning_rate) - float(end_learning_rate),
        bias=float(end_learning_rate),
    )


def piecewise_decay(global_step, boundaries, values):
    """Piecewise-constant schedule: sum of indicator-masked constants —
    branch-free (no lax.cond) so it fuses into the step."""
    if len(values) - len(boundaries) != 1:
        raise ValueError("len(values) must be len(boundaries) + 1")
    gs = _step_f(global_step)
    total = None
    prev_bound = None
    for i, v in enumerate(values):
        if i == 0:
            cond = layers.cast(
                x=gs < float(boundaries[0]), dtype="float32"
            )
        elif i == len(values) - 1:
            cond = layers.cast(
                x=gs >= float(boundaries[-1]), dtype="float32"
            )
        else:
            below = layers.cast(x=gs < float(boundaries[i]), dtype="float32")
            at_or_above = layers.cast(x=gs >= float(boundaries[i - 1]), dtype="float32")
            cond = layers.elementwise_mul(x=below, y=at_or_above)
        term = layers.scale(x=cond, scale=float(v))
        total = term if total is None else layers.elementwise_add(x=total, y=term)
    return total
