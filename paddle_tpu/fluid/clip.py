"""Gradient / error clipping (reference python/paddle/v2/fluid/clip.py:
ErrorClipByValue, GradientClipByValue, GradientClipByNorm,
GradientClipByGlobalNorm, set_gradient_clip, append_gradient_clip_ops)."""

from __future__ import annotations

from . import layers

__all__ = [
    "ErrorClipByValue",
    "GradientClipByValue",
    "GradientClipByNorm",
    "GradientClipByGlobalNorm",
    "BaseGradientClipAttr",
    "NullGradientClipAttr",
    "append_gradient_clip_ops",
    "error_clip_callback",
    "set_gradient_clip",
]


class BaseErrorClipAttr(object):
    def append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        min = -max if min is None else float(min)
        self.max, self.min = max, min

    def append_clip_op(self, block, grad_name):
        block.append_op(
            type="clip",
            inputs={"X": [grad_name]},
            outputs={"Out": [grad_name]},
            attrs={"min": self.min, "max": self.max},
        )


def error_clip_callback(block, context):
    pass  # activation-gradient clipping is folded into the vjp lowering


class BaseGradientClipAttr(object):
    def process_context(self, context, param, grad):
        pass

    def create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        min = -max if min is None else float(min)
        self.max, self.min = max, min

    def create_operators(self, param, grad):
        new_grad = layers.clip(x=grad, min=self.min, max=self.max)
        return param, new_grad


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def create_operators(self, param, grad):
        new_grad = layers.clip_by_norm(x=grad, max_norm=self.clip_norm)
        return param, new_grad


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = clip_norm
        self.group_name = group_name

    def process_context(self, context, param, grad):
        if self.group_name not in context:
            context[self.group_name] = []
            context[self.group_name + "_clip_value"] = self.clip_norm
        context[self.group_name].append(
            layers.reduce_sum(input=layers.pow(x=grad, factor=2.0))
        )

    def create_operators(self, param, grad):
        # the group scale lives in the per-minimize context dict (NOT on
        # the instance) so one clip object can serve several programs
        group_scale_name = self.group_name + "_scale"
        if group_scale_name not in self._context:
            group_norm = layers.sums(input=self._context[self.group_name])
            group_norm = layers.sqrt(x=group_norm)
            clip_var = layers.fill_constant(
                shape=[1], dtype="float32", value=self.clip_norm
            )
            self._context[group_scale_name] = layers.elementwise_div(
                x=clip_var,
                y=layers.elementwise_max(x=clip_var, y=group_norm),
            )
        new_grad = layers.elementwise_mul(x=grad, y=self._context[group_scale_name])
        return param, new_grad


def set_gradient_clip(clip, param_list=None, program=None):
    from .core.program import default_main_program
    from .param_attr import ParamAttr

    if program is None:
        program = default_main_program()
    if param_list is None:
        param_list = program.global_block().all_parameters()
    param_list = [
        program.global_block().var(p) if isinstance(p, str) else p for p in param_list
    ]
    for param in param_list:
        param.gradient_clip_attr = clip


def append_gradient_clip_ops(param_grad):
    context = {}
    for p, g in param_grad:
        clip_attr = getattr(p, "gradient_clip_attr", None) or NullGradientClipAttr()
        clip_attr.process_context(context=context, param=p, grad=g)
    res = []
    for p, g in param_grad:
        clip_attr = getattr(p, "gradient_clip_attr", None) or NullGradientClipAttr()
        clip_attr._context = context
        res.append(clip_attr.create_operators(param=p, grad=g))
    return res
