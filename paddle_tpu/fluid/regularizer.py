"""Weight-decay regularizers (reference python/paddle/v2/fluid/regularizer.py
+ legacy paddle/parameter/Regularizer.cpp). Applied as graph rewrites on the
gradient vars between the autodiff marker and the optimizer ops — XLA fuses
them into the update."""

from __future__ import annotations

from .core.program import grad_var_name

__all__ = [
    "append_regularization_ops",
    "WeightDecayRegularizer",
    "L1Decay",
    "L2Decay",
    "L1DecayRegularizer",
    "L2DecayRegularizer",
]


class WeightDecayRegularizer(object):
    def append_regularization_op(self, param, grad, block):
        raise NotImplementedError


class L2Decay(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def append_regularization_op(self, param, grad, block):
        decay = block.create_var(dtype=param.dtype, shape=param.shape)
        block.append_op(
            type="scale",
            inputs={"X": [param]},
            outputs={"Out": [decay]},
            attrs={"scale": self._regularization_coeff},
        )
        return decay


class L1Decay(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def append_regularization_op(self, param, grad, block):
        sign = block.create_var(dtype=param.dtype, shape=param.shape)
        block.append_op(type="sign", inputs={"X": [param]}, outputs={"Out": [sign]})
        decay = block.create_var(dtype=param.dtype, shape=param.shape)
        block.append_op(
            type="scale",
            inputs={"X": [sign]},
            outputs={"Out": [decay]},
            attrs={"scale": self._regularization_coeff},
        )
        return decay


L1DecayRegularizer = L1Decay
L2DecayRegularizer = L2Decay


def append_regularization_ops(params_grads, regularization=None):
    """grad += decay(param) for every param that has a regularizer attached
    (param-level regularizer wins over the optimizer-level default) —
    reference regularizer.py append_regularization_ops."""
    out = []
    for param, grad in params_grads:
        regularization_term = None
        reg = param.regularizer if param.regularizer is not None else regularization
        if grad is None or reg is None:
            out.append((param, grad))
            continue
        block = grad.block
        regularization_term = reg.append_regularization_op(param, grad, block)
        new_grad = block.create_var(
            name=grad.name + ".reg", dtype=param.dtype, shape=param.shape
        )
        block.append_op(
            type="sum",
            inputs={"X": [grad, regularization_term]},
            outputs={"Out": [new_grad]},
        )
        out.append((param, new_grad))
    return out
