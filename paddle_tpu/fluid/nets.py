"""Composite network helpers (reference python/paddle/v2/fluid/nets.py:
simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention)."""

from __future__ import annotations

from . import layers

__all__ = [
    "simple_img_conv_pool",
    "sequence_conv_pool",
    "glu",
    "img_conv_group",
    "scaled_dot_product_attention",
]


def simple_img_conv_pool(
    input,
    num_filters,
    filter_size,
    pool_size,
    pool_stride,
    act,
    param_attr=None,
    pool_type="max",
    use_cudnn=True,
    use_mkldnn=False,
):
    conv_out = layers.conv2d(
        input=input,
        num_filters=num_filters,
        filter_size=filter_size,
        param_attr=param_attr,
        act=act,
    )
    return layers.pool2d(
        input=conv_out,
        pool_size=pool_size,
        pool_type=pool_type,
        pool_stride=pool_stride,
    )


def img_conv_group(
    input,
    conv_num_filter,
    pool_size,
    conv_padding=1,
    conv_filter_size=3,
    conv_act=None,
    param_attr=None,
    conv_with_batchnorm=False,
    conv_batchnorm_drop_rate=0.0,
    pool_stride=1,
    pool_type=None,
    use_cudnn=True,
    use_mkldnn=False,
):
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def __extend_list__(obj):
        if not hasattr(obj, "__len__"):
            return [obj] * len(conv_num_filter)
        return list(obj)

    conv_padding = __extend_list__(conv_padding)
    conv_filter_size = __extend_list__(conv_filter_size)
    param_attr = __extend_list__(param_attr)
    conv_with_batchnorm = __extend_list__(conv_with_batchnorm)
    conv_batchnorm_drop_rate = __extend_list__(conv_batchnorm_drop_rate)

    for i in range(len(conv_num_filter)):
        local_conv_act = conv_act
        if conv_with_batchnorm[i]:
            local_conv_act = None
        tmp = layers.conv2d(
            input=tmp,
            num_filters=conv_num_filter[i],
            filter_size=conv_filter_size[i],
            padding=conv_padding[i],
            param_attr=param_attr[i],
            act=local_conv_act,
        )
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            drop_rate = conv_batchnorm_drop_rate[i]
            if abs(drop_rate) > 1e-5:
                tmp = layers.dropout(x=tmp, dropout_prob=drop_rate)

    return layers.pool2d(
        input=tmp, pool_size=pool_size, pool_type=pool_type, pool_stride=pool_stride
    )


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None, act="sigmoid", pool_type="max"):
    conv_out = layers.sequence_conv(
        input=input,
        num_filters=num_filters,
        filter_size=filter_size,
        param_attr=param_attr,
        act=act,
    )
    return layers.sequence_pool(input=conv_out, pool_type=pool_type)


def glu(input, dim=-1):
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    act_b = layers.sigmoid(x=b)
    return layers.elementwise_mul(x=a, y=act_b)


def scaled_dot_product_attention(queries, keys, values, num_heads=1, dropout_rate=0.0):
    """Multi-head scaled dot-product attention (reference nets.py). Inputs
    [batch, len, dim]; heads split/recombined around one batched matmul so
    XLA keeps everything on the MXU."""
    if queries.shape[-1] != keys.shape[-1]:
        raise ValueError("queries and keys must have the same hidden width")
    if keys.shape[-2] != values.shape[-2] if len(values.shape) > 2 else False:
        raise ValueError("keys and values must agree on sequence length")

    def __split_heads(x, num_heads):
        if num_heads == 1:
            return x
        hidden_size = x.shape[-1]
        reshaped = layers.reshape(
            x=x, shape=list(x.shape[:-1]) + [num_heads, hidden_size // num_heads]
        )
        return layers.transpose(x=reshaped, perm=[0, 2, 1, 3])

    def __combine_heads(x):
        if len(x.shape) == 3:
            return x
        trans = layers.transpose(x, perm=[0, 2, 1, 3])
        return layers.reshape(
            x=trans, shape=[trans.shape[0], trans.shape[1], trans.shape[2] * trans.shape[3]]
        )

    q = __split_heads(queries, num_heads)
    k = __split_heads(keys, num_heads)
    v = __split_heads(values, num_heads)

    key_dim_per_head = keys.shape[-1] // num_heads
    scaled_q = layers.scale(x=q, scale=key_dim_per_head ** -0.5)
    product = layers.matmul(x=scaled_q, y=k, transpose_y=True)
    weights = layers.reshape(
        x=product,
        shape=[-1, product.shape[-1]],
    )
    weights = layers.softmax(x=weights)
    weights = layers.reshape(x=weights, shape=list(product.shape))
    if dropout_rate:
        weights = layers.dropout(x=weights, dropout_prob=dropout_rate, is_test=False)
    ctx_multiheads = layers.matmul(weights, v)
    return __combine_heads(ctx_multiheads)
