"""TPU-native input pipeline (the reference's Go-master data plane as a
first-class subsystem).

The reference fed trainers through three cooperating pieces: the
recordio library chunked records on disk, the Go master leased chunks to
trainers with timeout/retry (go/master/service.go), and the C++
DataProvider double-buffered host decode under device compute. This
package is that stack rebuilt with modern loader idioms:

  record_shard  RecordShard chunked shard format (length-prefixed
                records in CRC-checked chunks, atomic-commit writer)
  dataset       ShardedDataset: chunk index + deterministic per-epoch
                shuffles (seed folded with epoch/chunk)
  loader        DataLoader: prefetch threads, ordered reassembly,
                bounded queue, device_put overlap, exact mid-epoch
                state_dict resume; CoordinatedChunkSource leases chunks
                from distributed.Coordinator for elastic multi-worker
                sharding with offset-aware re-leases
  metrics       DataMetrics: batches/s, queue depth, loader-wait
                fraction (O(1) running stats)
"""

from .record_shard import (MAGIC, RecordShard, ShardWriter, from_recordio,
                           write_shard)
from .dataset import ChunkRef, ShardedDataset
from .loader import (CoordinatedChunkSource, DataLoader, LeaseLost,
                     LocalChunkSource, default_collate)
from .metrics import DataMetrics

__all__ = [
    "MAGIC",
    "RecordShard",
    "ShardWriter",
    "write_shard",
    "from_recordio",
    "ChunkRef",
    "ShardedDataset",
    "DataLoader",
    "LocalChunkSource",
    "CoordinatedChunkSource",
    "LeaseLost",
    "default_collate",
    "DataMetrics",
]
