"""ShardedDataset: a logical dataset over RecordShard files, indexed at
chunk granularity — the unit the loader prefetches and the coordinator
leases (the Go master's partition-by-chunk, go/master/service.go:106).

Determinism contract (what makes exact mid-epoch resume possible): for a
fixed (seed, epoch) the chunk visitation order and the record order
within every chunk are pure functions — `epoch_order(epoch)` and
`record_order(epoch, chunk)` fold the epoch (and chunk id) into the seed
— so any position in an epoch's record stream is fully described by a
(chunk cursor, record offset) pair and can be re-entered exactly after a
crash, on any process.
"""

from __future__ import annotations

import zlib
from typing import Callable, List, Optional

import numpy as np

from .record_shard import RecordShard

__all__ = ["ChunkRef", "ShardedDataset"]


def _fold(seed: int, *vals) -> int:
    """Deterministic 32-bit fold of (seed, *vals) — stable across
    processes and runs (unlike hash(), which is salted)."""
    key = ("%d|" % seed) + "|".join(str(v) for v in vals)
    return zlib.crc32(key.encode()) & 0xFFFFFFFF


class ChunkRef(object):
    """One leasable unit of work: chunk `chunk` of shard `shard`."""

    __slots__ = ("shard", "chunk", "records")

    def __init__(self, shard: str, chunk: int, records: int):
        self.shard = shard
        self.chunk = chunk
        self.records = records

    def __repr__(self):
        return "ChunkRef(%r, %d, records=%d)" % (
            self.shard, self.chunk, self.records)


class ShardedDataset(object):
    """Index of every chunk across `shard_paths`, plus the deterministic
    shuffles and the decode hook.

    decode_fn(record_bytes) -> item   per-record decode (pickle.loads,
                                      np.frombuffer, ...); None = raw
    seed                              folds with the epoch (and chunk id)
                                      for the per-epoch shuffles
    shuffle_chunks / shuffle_records  both default True; turning both
                                      off gives storage order
    quarantine_path                   the sentinel's poisoned-chunk
                                      journal (distributed.sentinel):
                                      journaled chunk ids are skipped by
                                      the chunk sources on every pass.
                                      Quarantined chunks stay IN
                                      `epoch_order` — a loader cursor's
                                      `pos` keeps meaning the same chunk
                                      before and after a quarantine, so
                                      rollback resume stays exact; the
                                      skip happens at delivery time.
    """

    def __init__(self, shard_paths: List[str],
                 decode_fn: Optional[Callable] = None, seed: int = 0,
                 shuffle_chunks: bool = True, shuffle_records: bool = True,
                 quarantine_path: Optional[str] = None):
        if isinstance(shard_paths, str):
            shard_paths = [shard_paths]
        self.shard_paths = list(shard_paths)
        self.decode_fn = decode_fn
        self.seed = int(seed)
        self.shuffle_chunks = shuffle_chunks
        self.shuffle_records = shuffle_records
        self.quarantine_path = quarantine_path
        self._quarantined = frozenset()
        self._readers = {p: RecordShard(p) for p in self.shard_paths}
        self.chunks: List[ChunkRef] = []
        for p in self.shard_paths:
            for k, n in enumerate(self._readers[p].record_counts):
                self.chunks.append(ChunkRef(p, k, n))
        if quarantine_path:
            self.reload_quarantine()

    @property
    def num_chunks(self) -> int:
        return len(self.chunks)

    @property
    def num_records(self) -> int:
        return sum(c.records for c in self.chunks)

    # --- poisoned-data quarantine (distributed.sentinel) ---------------
    @property
    def quarantined(self) -> frozenset:
        """Global chunk indices currently quarantined (never delivered)."""
        return self._quarantined

    def is_quarantined(self, chunk_index: int) -> bool:
        return int(chunk_index) in self._quarantined

    def reload_quarantine(self) -> frozenset:
        """Re-read the quarantine journal (the sentinel appends to it at
        trip time; every worker re-reads on its next resume, so the
        skip set is identical fleet-wide and across reruns)."""
        if self.quarantine_path:
            from ..distributed.sentinel import quarantined_chunks

            self._quarantined = quarantined_chunks(self.quarantine_path)
        return self._quarantined

    # --- deterministic per-epoch shuffles -----------------------------
    def epoch_order(self, epoch: int) -> List[int]:
        """Global chunk indices in this epoch's visitation order."""
        idx = np.arange(len(self.chunks))
        if self.shuffle_chunks:
            np.random.RandomState(
                _fold(self.seed, "chunks", epoch)).shuffle(idx)
        return idx.tolist()

    def record_order(self, epoch: int, chunk_index: int) -> List[int]:
        """Record positions within chunk `chunk_index` (global index) in
        this epoch's order."""
        n = self.chunks[chunk_index].records
        if not self.shuffle_records:
            return list(range(n))
        return np.random.RandomState(
            _fold(self.seed, "records", epoch, chunk_index)
        ).permutation(n).tolist()

    # --- chunk loading -------------------------------------------------
    def load_chunk(self, chunk_index: int, epoch: int = 0, skip: int = 0):
        """The records of one chunk in epoch order, minus the first
        `skip` (already delivered before a resume / re-lease), decoded.
        CRC failures surface as IOError from the shard reader."""
        ref = self.chunks[chunk_index]
        raw = self._readers[ref.shard].read_chunk(ref.chunk)
        order = self.record_order(epoch, chunk_index)
        out = [raw[i] for i in order[skip:]]
        if self.decode_fn is not None:
            out = [self.decode_fn(r) for r in out]
        return out

    # --- coordinator integration --------------------------------------
    def payloads(self) -> List[dict]:
        """JSON-serializable chunk descriptions for
        `Coordinator.set_dataset` — `chunk` is the global index into
        `self.chunks`, which every worker reconstructs identically from
        the same shard list."""
        return [
            {"chunk": i, "shard": c.shard, "records": c.records}
            for i, c in enumerate(self.chunks)
        ]
