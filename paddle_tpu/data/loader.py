"""DataLoader: prefetching, resumable batch pipeline over a
ShardedDataset.

The capability being rebuilt is the reference's second-generation input
path end to end (PAPER.md §Go cloud layer): the Go master leased
RecordIO chunks to trainers while the C++ DataProvider double-buffered
decode under compute. Here both live in one loader with modern idioms:

  - **prefetch threads** decode whole chunks off the training thread
    (`num_workers`; 0 = fully synchronous, the measured baseline of
    `bench.py input_pipeline`);
  - **ordered reassembly**: chunks decode in parallel but batches are
    assembled in plan order, so the delivered record stream is
    IDENTICAL for every `num_workers` — parallelism never changes what
    the model sees;
  - **bounded queue** (`prefetch_batches`) for backpressure, and
    optional `device_put=True` so the host->device transfer of batch
    k+1 overlaps the consumer's compute on batch k (the
    AsyncDeviceFeeder double-buffer, now fed by the chunk pipeline);
  - **exact mid-epoch resume**: `state_dict()` is a
    (epoch, chunk cursor, record offset) position in the deterministic
    per-epoch shuffle; `load_state_dict()` re-enters at exactly the
    next undelivered record. It rides `distributed.checkpoint`'s
    `stateful=` hook, so a supervisor restart resumes the data stream
    with the model state;
  - **elastic multi-worker sharding** via `CoordinatedChunkSource`:
    chunks are leased from the `distributed.Coordinator` task queue
    (at-least-once, lease-timeout requeue) and every lease carries a
    committed record offset, so a re-leased chunk resumes where the
    previous holder's last `commit()` left it instead of replaying
    delivered records.

Exactly-once accounting (coordinated mode): completion acks and offset
progress are buffered per batch and flushed by `commit()` — call it
right after the trainer's checkpoint commits, so the coordinator's view
never runs ahead of durable state. Crash windows: uncommitted acks ride
in `state_dict()` and are re-flushed on resume (the supervisor_worker
`pending_ack` discipline); a lease that expired anyway requeues with
the committed offset, so the next holder — the resumed victim or a
peer — continues without replaying committed records. Every lease
carries a **generation (fencing token)**: a zombie holder's
progress/finish/fail calls against a re-issued lease are refused by the
server, and `commit()` surfaces the refusal as `LeaseLost` (poisoning
the iteration) instead of silently double-delivering. The residual
window is the PR-1 one: batches a zombie delivered — and its trainer
checkpointed — between its lease expiring and its next commit() are
also re-delivered by the new holder; on `LeaseLost` restart from the
checkpoint BEFORE the refused batch, or size lease timeouts above the
worst-case checkpoint+commit interval so the window never opens.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

from .dataset import ShardedDataset
from .metrics import DataMetrics

__all__ = ["DataLoader", "LocalChunkSource", "CoordinatedChunkSource",
           "LeaseLost", "default_collate"]


class LeaseLost(RuntimeError):
    """The in-flight chunk's coordinator lease expired and was requeued:
    records past the last committed offset may be delivered by another
    worker. The iteration is poisoned; restart it from the last
    checkpoint (whose state no longer claims the lease)."""


class _Plan(object):
    """One chunk scheduled for delivery. `lease` is the coordinator's
    lease generation (fencing token): every ack/progress call presents
    it, so a zombie holder can never touch a re-issued lease."""

    __slots__ = ("chunk_index", "epoch", "skip", "task_id", "pos",
                 "records", "lease")

    def __init__(self, chunk_index, epoch, skip, task_id, pos, records,
                 lease=None):
        self.chunk_index = chunk_index
        self.epoch = epoch
        self.skip = skip
        self.task_id = task_id
        self.pos = pos
        self.records = records
        self.lease = lease


class LocalChunkSource(object):
    """Single-worker plan: the dataset's deterministic per-epoch chunk
    permutation, re-enterable at any (cursor, offset)."""

    mode = "local"

    def plans(self, dataset: ShardedDataset, epoch: int, pos: int,
              offset: int, inflight):
        order = dataset.epoch_order(epoch)
        for i in range(pos, len(order)):
            skip = offset if i == pos else 0
            ci = order[i]
            if dataset.is_quarantined(ci):
                continue  # sentinel-quarantined: never delivered again
            n = dataset.chunks[ci].records
            if skip >= n:
                continue  # resumed exactly at this chunk's end
            yield _Plan(ci, epoch, skip, None, i, n)

    def finish(self, task_id, lease=None):  # no queue to ack
        pass

    def progress(self, task_id, offset, lease=None):
        return True


class CoordinatedChunkSource(object):
    """Elastic multi-worker plan: chunks leased from a
    `distributed.Coordinator` (in-process or RemoteCoordinator — same
    API). `idle_grace_s` keeps polling an apparently-empty queue so a
    dead peer's lease can time out and requeue to us (set it past the
    coordinator's lease timeout in fault-tolerant jobs)."""

    mode = "coordinated"

    def __init__(self, coordinator, idle_grace_s: float = 0.0,
                 poll_s: float = 0.1):
        self.coordinator = coordinator
        self.idle_grace_s = idle_grace_s
        self.poll_s = poll_s
        # leases this source holds whose records are still upstream of
        # the consumer (decoded/buffered but not yet delivered+acked),
        # task_id -> lease generation. Idle waits renew them
        # (task_progress doubles as a keepalive — offset 0 can never
        # lower the server's committed offset), so a tail wait for a
        # dead peer's requeue cannot starve our own leases into expiry.
        # Size lease timeouts to cover the decode lookahead (~2x
        # num_workers chunks) regardless.
        self._held = {}

    def publish(self, dataset: ShardedDataset):
        """Register the dataset's chunks as the shared task queue. Call
        ONCE per job (set_dataset is idempotent only while the queue is
        non-empty)."""
        self.coordinator.set_dataset(dataset.payloads())

    def plans(self, dataset: ShardedDataset, epoch: int, pos: int,
              offset: int, inflight):
        if inflight is not None:
            # reclaim our checkpointed lease first: deliver the rest of
            # the chunk from the committed offset
            ci = int(inflight["chunk"])
            if dataset.is_quarantined(ci):
                # quarantined since the checkpoint was taken (sentinel
                # rollback): never deliver its tail — ack so the queue
                # drains (every holder reads the same journal, so the
                # decision is identical fleet-wide)
                self.coordinator.task_finished(
                    inflight["task_id"], lease=inflight.get("lease"))
            else:
                self._held[inflight["task_id"]] = inflight.get("lease")
                yield _Plan(ci, int(inflight["epoch"]),
                            int(inflight["offset"]), inflight["task_id"],
                            -1, dataset.chunks[ci].records,
                            lease=inflight.get("lease"))
        idle_since = None
        while True:
            task = self.coordinator.get_task(epoch_limit=epoch)
            if task is None:
                if self.idle_grace_s <= 0:
                    return
                if idle_since is None:
                    idle_since = time.monotonic()
                if time.monotonic() - idle_since > self.idle_grace_s:
                    return
                for tid, lease in list(self._held.items()):
                    # keepalive, see __init__
                    self.coordinator.task_progress(tid, 0, lease=lease)
                time.sleep(self.poll_s)
                continue
            idle_since = None
            if task.task_id in self._held:
                # our own lease expired and came back while its records
                # are still buffered: delivering it again would
                # duplicate them. Loud failure beats silent corruption —
                # the config needs a longer lease timeout.
                raise LeaseLost(
                    "task %d re-leased to this worker while still held "
                    "(lease timeout shorter than the decode pipeline)"
                    % task.task_id)
            ci = int(task.payload["chunk"])
            skip = int(getattr(task, "offset", 0))
            n = dataset.chunks[ci].records
            lease = getattr(task, "lease", None)
            if dataset.is_quarantined(ci):
                # sentinel-quarantined chunk leased to us: never deliver
                # it; finish the lease so the pass can still drain
                self.coordinator.task_finished(task.task_id, lease=lease)
                continue
            if skip >= n:
                # a previous holder delivered (and committed) the whole
                # chunk but its finish ack was lost: nothing to deliver
                self.coordinator.task_finished(task.task_id, lease=lease)
                continue
            self._held[task.task_id] = lease
            yield _Plan(ci, task.epoch, skip, task.task_id, -1, n,
                        lease=lease)

    def abort(self):
        """The loader dropped any buffered chunks (iteration abort):
        orphaned leases simply expire and requeue at their committed
        offsets — forget them so a later requeue is not misread as a
        duplicate-delivery hazard."""
        self._held.clear()

    def finish(self, task_id, lease=None):
        self._held.pop(task_id, None)
        self.coordinator.task_finished(task_id, lease=lease)

    def progress(self, task_id, offset, lease=None):
        r = self.coordinator.task_progress(task_id, offset, lease=lease)
        return bool(r.get("held")) if isinstance(r, dict) else True


def default_collate(items):
    """Stack a batch: arrays stack along a new axis, tuples/lists/dicts
    collate per field, numbers become arrays, anything else stays a
    list."""
    first = items[0]
    if isinstance(first, np.ndarray):
        return np.stack(items)
    if isinstance(first, tuple):
        return tuple(default_collate([it[i] for it in items])
                     for i in range(len(first)))
    if isinstance(first, list):
        return [default_collate([it[i] for it in items])
                for i in range(len(first))]
    if isinstance(first, dict):
        return {k: default_collate([it[k] for it in items]) for k in first}
    if isinstance(first, (int, float, np.integer, np.floating, bool)):
        return np.asarray(items)
    return list(items)


class _EndOfEpoch(Exception):
    pass


class DataLoader(object):
    """Iterate batches; one `iter()` pass = one epoch (resuming from the
    current cursor, so `break` + re-`iter()` continues mid-epoch).

    Arguments:
      dataset            ShardedDataset (decode_fn applies per record)
      batch_size         records per delivered batch
      source             LocalChunkSource (default) or
                         CoordinatedChunkSource
      num_workers        chunk-decode threads; 0 = synchronous inline
      prefetch_batches   bounded batch queue depth (backpressure)
      collate_fn         batch assembly; default stacks per field; pass
                         `list` for raw row lists (DataFeeder.feed rows)
      device_put         jax.device_put each batch on the producer side
                         (h2d of batch k+1 overlaps compute on batch k)
      drop_last          drop the epoch's final partial batch
      auto_commit        flush coordinator acks on every batch (True);
                         checkpointing trainers set False and call
                         commit() after their checkpoint commits, plus
                         once after the epoch ends (trailing completion
                         acks for chunks whose records all rode earlier
                         batches surface at epoch end)
    """

    def __init__(self, dataset: ShardedDataset, batch_size: int,
                 source=None, num_workers: int = 2,
                 prefetch_batches: int = 4, collate_fn=default_collate,
                 device_put: bool = False, drop_last: bool = False,
                 auto_commit: bool = True):
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.source = source if source is not None else LocalChunkSource()
        self.num_workers = int(num_workers)
        self.prefetch_batches = max(1, int(prefetch_batches))
        self.collate_fn = collate_fn if collate_fn is not None else list
        self.device_put = device_put
        self.drop_last = drop_last
        self.auto_commit = auto_commit
        self.metrics = DataMetrics()
        # cursor (captured by state_dict at batch boundaries). The
        # whole cursor is CONSUMER-thread state: the producer thread
        # communicates through the bounded queue only and never touches
        # it — lock_lint enforces the split via the `consumer` domain
        # ('# thread: producer' methods must not mutate these).
        self._epoch = 0        # guarded-by: consumer
        self._pos = 0          # guarded-by: consumer
        self._offset = 0       # guarded-by: consumer
        self._inflight = None  # guarded-by: consumer
        self._records_epoch = 0   # guarded-by: consumer
        self._batches_total = 0   # guarded-by: consumer
        # uncommitted coordinator acks (flushed by commit())
        self._pending_finish = []       # guarded-by: consumer
        self._pending_progress = None   # guarded-by: consumer
        self._batches_since_load = 0    # guarded-by: consumer
        self._lease_lost = False        # guarded-by: consumer
        self._exhausted = False         # guarded-by: consumer
        # iteration machinery (consumer-owned: the producer receives
        # q/stop as call arguments and only READS self._pool to submit
        # decodes; the consumer replaces/tears down _pool only after
        # joining the producer — a producer outliving the 5 s join
        # deadline in _abort_iteration is abandoned, not raced)
        self._pool = None      # guarded-by: consumer
        # inline generator (num_workers == 0)
        self._gen = None       # guarded-by: consumer
        self._q = None         # guarded-by: consumer
        self._thread = None    # guarded-by: consumer
        self._stop = None      # guarded-by: consumer

    # --- epoch / cursor ------------------------------------------------
    @property
    def epoch(self) -> int:
        return self._epoch

    def state_dict(self) -> dict:
        """JSON-serializable cursor: everything needed to re-enter the
        record stream at exactly the next undelivered record (plus any
        coordinator acks not yet flushed, re-flushed on resume)."""
        return {
            "version": 1,
            "mode": self.source.mode,
            "epoch": self._epoch,
            "pos": self._pos,
            "offset": self._offset,
            "inflight": dict(self._inflight) if self._inflight else None,
            "records_epoch": self._records_epoch,
            "batches_total": self._batches_total,
            "pending": {
                "finish": list(self._pending_finish),
                "progress": dict(self._pending_progress)
                if self._pending_progress else None,
            },
        }

    def load_state_dict(self, state: dict):
        if state.get("mode") != self.source.mode:
            raise ValueError(
                "loader state has mode %r but the source is %r"
                % (state.get("mode"), self.source.mode))
        self._abort_iteration()
        self._epoch = int(state["epoch"])
        self._pos = int(state["pos"])
        self._offset = int(state["offset"])
        self._inflight = (dict(state["inflight"])
                          if state.get("inflight") else None)
        self._records_epoch = int(state.get("records_epoch", 0))
        self._batches_total = int(state.get("batches_total", 0))
        pending = state.get("pending") or {}
        self._pending_finish = list(pending.get("finish") or [])
        self._pending_progress = (dict(pending["progress"])
                                  if pending.get("progress") else None)
        self._batches_since_load = 0
        self._lease_lost = False
        self._exhausted = False

    # --- iteration -----------------------------------------------------
    def __iter__(self):
        self._abort_iteration()
        self._exhausted = False
        self._start_iteration()
        return self

    def _start_iteration(self):
        epoch, pos, offset = self._epoch, self._pos, self._offset
        inflight = dict(self._inflight) if self._inflight else None
        if self.num_workers == 0:
            self._gen = self._assemble(
                epoch, pos, offset,
                ((p, self._load_plan(p)) for p in self.source.plans(
                    self.dataset, epoch, pos, offset, inflight)))
            return
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self.num_workers,
                thread_name_prefix="ptpu-data")
        self._q = queue.Queue(maxsize=self.prefetch_batches)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._produce,
            args=(epoch, pos, offset, inflight, self._q, self._stop),
            daemon=True)
        self._thread.start()

    def _abort_iteration(self):
        if self._stop is not None:
            self._stop.set()
        if self._q is not None:
            try:  # unblock a producer parked in put()
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._gen = None
        self._q = None
        self._thread = None
        self._stop = None
        abort = getattr(self.source, "abort", None)
        if abort is not None:
            abort()

    def close(self):
        self._abort_iteration()
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _load_plan(self, plan: _Plan):
        return self.dataset.load_chunk(plan.chunk_index, epoch=plan.epoch,
                                       skip=plan.skip)

    def _pipelined_chunks(self, plans, stop):  # thread: producer
        """(plan, items) with up to ~2x num_workers chunk decodes in
        flight, results consumed strictly in plan order — parallel
        decode, deterministic delivery."""
        lookahead = max(2, self.num_workers * 2)
        pending = collections.deque()
        it = iter(plans)
        exhausted = False
        while True:
            while (not exhausted and len(pending) < lookahead
                   and not stop.is_set()):
                try:
                    p = next(it)
                except StopIteration:
                    exhausted = True
                    break
                pending.append((p, self._pool.submit(self._load_plan, p)))
            if not pending or stop.is_set():
                return
            p, fut = pending.popleft()
            yield p, fut.result()

    def _assemble(self, epoch, pos, offset, chunks):
        """Slice an in-order (plan, items) stream into batches, tracking
        the exact after-batch cursor. Yields ("batch", payload, meta)."""
        buf = []
        finished = []
        cur = None

        def emit():
            payload = self.collate_fn(list(buf))
            if self.device_put:
                payload = _to_device(payload)
            meta = {
                "pos": pos,
                "offset": offset,
                "finished": [[p.task_id, p.lease] for p in finished
                             if p.task_id is not None],
                "inflight": (
                    {"task_id": cur.task_id, "chunk": cur.chunk_index,
                     "epoch": cur.epoch, "offset": offset,
                     "lease": cur.lease}
                    if cur is not None and cur.task_id is not None
                    else None),
                "n": len(buf),
            }
            del buf[:]
            del finished[:]
            return ("batch", payload, meta)

        for plan, items in chunks:
            cur = plan
            # sync the cursor to the chunk actually being consumed: a
            # resume whose offset landed exactly on a chunk boundary
            # starts at plan.pos > pos (the boundary chunk was skipped),
            # and stamping batches with the stale pos would make a
            # SECOND resume replay this chunk
            if plan.pos >= 0:
                pos = plan.pos
            offset = plan.skip
            for item in items:
                buf.append(item)
                offset += 1
                if len(buf) == self.batch_size:
                    yield emit()
            finished.append(plan)
            cur = None
            pos = plan.pos + 1 if plan.pos >= 0 else pos
            offset = 0
        if buf and not self.drop_last:
            yield emit()
        elif finished:
            # acks for trailing chunks whose records all landed in
            # already-emitted batches (or were dropped by drop_last)
            yield ("acks", None, {
                "pos": pos, "offset": 0,
                "finished": [[p.task_id, p.lease] for p in finished
                             if p.task_id is not None],
                "inflight": None, "n": 0})

    def _produce(self, epoch, pos, offset, inflight, q, stop):  # thread: producer
        try:
            plans = self.source.plans(self.dataset, epoch, pos, offset,
                                      inflight)
            for ev in self._assemble(
                    epoch, pos, offset,
                    self._pipelined_chunks(plans, stop)):
                if not _put_stoppable(q, ev, stop):
                    return
            _put_stoppable(q, ("end", None, None), stop)
        except BaseException as e:  # surfaced at the consumer
            _put_stoppable(q, ("error", e, None), stop)

    def __next__(self):
        if self._lease_lost:
            raise LeaseLost(
                "the in-flight chunk lease was lost; restart iteration "
                "from the last checkpoint")
        if self._exhausted:
            # an exhausted iterator stays exhausted (iterator protocol);
            # only iter() starts the next epoch
            raise StopIteration
        if self._gen is None and self._q is None:
            self._start_iteration()
        t0 = time.monotonic()
        while True:
            if self.num_workers == 0:
                try:
                    kind, payload, meta = next(self._gen)
                except StopIteration:
                    kind, payload, meta = "end", None, None
                except BaseException:
                    # mirror the threaded error path: abort so the dead
                    # generator cannot masquerade as a clean epoch end
                    # on a retried next() (cursor intact — a retry
                    # resumes from the last delivered batch)
                    self._abort_iteration()
                    raise
                depth = 0
            else:
                depth = self._q.qsize()
                kind, payload, meta = self._q.get()
            if kind == "error":
                self._abort_iteration()
                raise payload
            if kind == "end":
                self._end_epoch()
                raise StopIteration
            # batch or trailing acks: apply the cursor + pending acks
            self._pos = meta["pos"]
            self._offset = meta["offset"]
            self._inflight = meta["inflight"]
            self._pending_finish.extend(meta["finished"])
            self._pending_progress = (dict(meta["inflight"])
                                      if meta["inflight"] else None)
            if kind == "acks":
                if self.auto_commit:
                    self.commit()
                continue  # not a consumer-visible batch
            self._records_epoch += meta["n"]
            self._batches_total += 1
            self._batches_since_load += 1
            self.metrics.batch_delivered(
                meta["n"], time.monotonic() - t0, depth)
            if self.auto_commit:
                self.commit()
            return payload

    def _end_epoch(self):
        # the producer ended the epoch; trailing acks (if any) were
        # delivered as an "acks" event before the end sentinel
        self._gen = None
        self._q = None
        self._thread = None
        self._stop = None
        self._epoch += 1
        self._pos = 0
        self._offset = 0
        self._inflight = None
        self._records_epoch = 0
        self._exhausted = True
        self.metrics.epoch_completed()

    # --- coordinator transaction boundary ------------------------------
    def commit(self) -> bool:
        """Flush buffered completion acks and offset progress to the
        chunk source. Call after the trainer's checkpoint commits (or
        leave auto_commit=True when there is no checkpoint to sync
        with). Returns False when the in-flight lease is gone — the
        loader drops it and aborts any running producer (which may have
        already reclaimed the lost lease's plan); if batches were
        already delivered this incarnation the iteration is poisoned
        (next() raises LeaseLost), otherwise (resume-time re-flush) the
        next iteration simply starts without the reclaimed chunk."""
        for tid, lease in self._pending_finish:
            self.source.finish(tid, lease)
        self._pending_finish = []
        prog = self._pending_progress
        self._pending_progress = None
        if prog is None:
            return True
        if self.source.progress(prog["task_id"], prog["offset"],
                                prog.get("lease")):
            return True
        self._inflight = None
        self._abort_iteration()  # the producer may hold the dead plan
        if self._batches_since_load > 0:
            self._lease_lost = True
        return False


def _to_device(payload):
    import jax

    if isinstance(payload, np.ndarray):
        return jax.device_put(payload)
    if isinstance(payload, tuple):
        return tuple(_to_device(v) for v in payload)
    if isinstance(payload, list):
        return [_to_device(v) for v in payload]
    if isinstance(payload, dict):
        return {k: _to_device(v) for k, v in payload.items()}
    return payload


def _put_stoppable(q, item, stop) -> bool:
    """put() that a consumer-side stop can always unblock."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False
