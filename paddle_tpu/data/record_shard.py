"""RecordShard: the chunked on-disk shard format of the input pipeline.

The reference's Go master dispatches RecordIO *chunks* — not files and
not single records — because a chunk is the smallest unit that can be
leased, retried, and CRC-verified independently (go/master/service.go
partitions by chunk index). This module reproduces that capability for
the TPU stack as a pure-Python format (no toolchain needed, unlike the
native recordio in `paddle_tpu.native`, which this format maps onto —
`from_recordio` converts, and both sides speak "iterable of raw record
bytes"):

    shard  := chunk*
    chunk  := header payload
    header := '<IIII'  magic | num_records | payload_len | crc32(payload)
    payload:= ('<I' record_len ++ record_bytes)*

Properties the loader relies on:
  - the chunk index (offsets + record counts) is recoverable by a
    header-only scan, so a dataset over many shards indexes in O(chunks)
    reads without touching payload bytes;
  - every chunk carries its own CRC32, so a torn write or bit flip is
    detected at the chunk that contains it (load_chunk raises IOError),
    mirroring the checkpoint module's corrupt-shard rejection;
  - writers commit via atomic rename, so a reader never sees a partial
    shard (same discipline as checkpoint.py / the coordinator snapshot).
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterable, List

__all__ = ["MAGIC", "ShardWriter", "RecordShard", "write_shard",
           "from_recordio"]

MAGIC = 0x52534844  # "RSHD"
_HEADER = struct.Struct("<IIII")
_LEN = struct.Struct("<I")


class ShardWriter(object):
    """Append records, flush them as CRC-checked chunks, commit the shard
    atomically on close(). An exception inside the `with` block aborts
    (the temp file is removed; the target path is never touched)."""

    def __init__(self, path: str, records_per_chunk: int = 256):
        if records_per_chunk < 1:
            raise ValueError("records_per_chunk must be >= 1")
        self.path = path
        self.records_per_chunk = int(records_per_chunk)
        self._tmp = path + ".tmp"
        self._f = open(self._tmp, "wb")
        self._buf: List[bytes] = []
        self.num_records = 0
        self.num_chunks = 0

    def write(self, record: bytes):
        self._buf.append(bytes(record))
        self.num_records += 1
        if len(self._buf) >= self.records_per_chunk:
            self._flush_chunk()

    def _flush_chunk(self):
        if not self._buf:
            return
        payload = b"".join(_LEN.pack(len(r)) + r for r in self._buf)
        self._f.write(_HEADER.pack(MAGIC, len(self._buf), len(payload),
                                   zlib.crc32(payload) & 0xFFFFFFFF))
        self._f.write(payload)
        self.num_chunks += 1
        self._buf = []

    def close(self):
        if self._f is None:
            return
        self._flush_chunk()
        self._f.close()
        self._f = None
        os.replace(self._tmp, self.path)  # atomic commit

    def abort(self):
        if self._f is None:
            return
        self._f.close()
        self._f = None
        try:
            os.remove(self._tmp)
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *a):
        if exc_type is not None:
            self.abort()
        else:
            self.close()


class RecordShard(object):
    """Reader over one shard: indexes chunk headers on open, serves
    whole CRC-verified chunks by index."""

    def __init__(self, path: str):
        self.path = path
        # [(payload_file_offset, num_records, payload_len, crc32)]
        self._chunks: List[tuple] = []
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            pos = 0
            while pos < size:
                head = f.read(_HEADER.size)
                if len(head) < _HEADER.size:
                    raise IOError(
                        "%s: truncated chunk header at %d (shard commits "
                        "are atomic — this file is corrupt)" % (path, pos))
                magic, n_rec, p_len, crc = _HEADER.unpack(head)
                if magic != MAGIC:
                    raise IOError(
                        "%s: bad chunk magic 0x%08x at offset %d"
                        % (path, magic, pos))
                payload_at = pos + _HEADER.size
                if payload_at + p_len > size:
                    raise IOError(
                        "%s: chunk at %d claims %d payload bytes past EOF"
                        % (path, pos, p_len))
                self._chunks.append((payload_at, n_rec, p_len, crc))
                pos = payload_at + p_len
                f.seek(pos)

    @property
    def num_chunks(self) -> int:
        return len(self._chunks)

    @property
    def record_counts(self) -> List[int]:
        return [n for _, n, _, _ in self._chunks]

    @property
    def num_records(self) -> int:
        return sum(n for _, n, _, _ in self._chunks)

    def read_chunk(self, k: int) -> List[bytes]:
        off, n_rec, p_len, crc = self._chunks[k]
        with open(self.path, "rb") as f:
            f.seek(off)
            payload = f.read(p_len)
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            raise IOError(
                "%s: chunk %d failed its CRC check (corrupt payload)"
                % (self.path, k))
        records, pos = [], 0
        for _ in range(n_rec):
            (ln,) = _LEN.unpack_from(payload, pos)
            pos += _LEN.size
            records.append(payload[pos:pos + ln])
            pos += ln
        return records

    def iter_records(self) -> Iterable[bytes]:
        for k in range(self.num_chunks):
            for rec in self.read_chunk(k):
                yield rec


def write_shard(path: str, records: Iterable[bytes],
                records_per_chunk: int = 256) -> RecordShard:
    """Write `records` to one shard and return a reader over it."""
    with ShardWriter(path, records_per_chunk=records_per_chunk) as w:
        for rec in records:
            w.write(rec)
    return RecordShard(path)


def from_recordio(src_path: str, dst_path: str,
                  records_per_chunk: int = 256) -> RecordShard:
    """Convert a native record file (paddle_tpu.native RecordWriter
    format, e.g. bench.py's `_ensure_recordio` output) into a
    RecordShard — the bridge from the flat native record stream to the
    chunk-leasable shard format."""
    from .. import native

    return write_shard(dst_path, native.read_records(src_path),
                       records_per_chunk=records_per_chunk)
