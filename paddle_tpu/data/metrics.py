"""DataMetrics: loader-side counters in the ServingMetrics running-stat
style — O(1) memory however long the job runs (the same trap
`utils.stat.RunningStat` documents: a loader lives for the whole
training run and records one value per batch).

The headline quantity is the **loader-wait fraction**: of the consumer's
wall time, how much was spent blocked waiting for the next batch (input
bound) vs. doing its own work between `next()` calls (compute bound).
With prefetch overlapping host decode under device compute the fraction
should approach 0; `bench.py input_pipeline` records it with prefetch
on vs. off.
"""

from __future__ import annotations

import time

from ..utils.stat import RunningStat as _RunningStat

__all__ = ["DataMetrics"]


class DataMetrics(object):
    def __init__(self):
        self.batches = 0
        self.records = 0
        self.epochs_completed = 0
        self.wait_s = _RunningStat()        # blocked inside next()
        self.step_s = _RunningStat()        # consumer time between next()s
        self.queue_depth = _RunningStat()   # prefetch queue depth at next()
        self._t0 = None                     # first activity (monotonic)
        self._t1 = None                     # latest activity
        self._last_return = None            # when next() last returned

    # -- recording (called by the loader) -------------------------------
    def batch_delivered(self, n_records: int, wait_seconds: float,
                        queue_depth: int):
        now = time.monotonic()
        if self._t0 is None:
            self._t0 = now - wait_seconds
        self._t1 = now
        if self._last_return is not None:
            # consumer-side time since the previous batch was handed out,
            # minus the time we just spent blocked = the consumer's step
            self.step_s.append(
                max(0.0, (now - self._last_return) - wait_seconds))
        self._last_return = now
        self.batches += 1
        self.records += int(n_records)
        self.wait_s.append(wait_seconds)
        self.queue_depth.append(queue_depth)

    def epoch_completed(self):
        self.epochs_completed += 1

    # -- derived --------------------------------------------------------
    @property
    def wall_s(self) -> float:
        if self._t0 is None:
            return 0.0
        return (self._t1 or self._t0) - self._t0

    @property
    def wait_fraction(self):
        """Blocked-on-input share of the consumer's measured time."""
        denom = self.wait_s.total + self.step_s.total
        if denom <= 0:
            return None
        return self.wait_s.total / denom

    def report(self) -> dict:
        def _mean(st):
            return round(st.mean, 6) if st.count else None

        wall = self.wall_s
        wf = self.wait_fraction
        return {
            "batches": self.batches,
            "records": self.records,
            "epochs_completed": self.epochs_completed,
            "batches_per_sec": round(self.batches / wall, 2) if wall else None,
            "records_per_sec": round(self.records / wall, 1) if wall else None,
            "mean_wait_s": _mean(self.wait_s),
            "max_wait_s": round(self.wait_s.max, 6)
            if self.wait_s.count else None,
            "mean_step_s": _mean(self.step_s),
            "wait_fraction": round(wf, 4) if wf is not None else None,
            "mean_queue_depth": _mean(self.queue_depth),
            "wall_s": round(wall, 4),
        }
