"""Reader creators from storage (reference python/paddle/v2/reader/
creator.py: np_array, text_file, recordio). The recordio variant streams
through the native C++ prefetch queue (paddle_tpu.native)."""

from __future__ import annotations

import pickle

__all__ = ["np_array", "text_file", "recordio", "pickled_records"]


def np_array(x):
    def reader():
        for e in x:
            yield e

    return reader


def text_file(path):
    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths, buf_size=100):
    """Raw-bytes reader over record files via the native async prefetcher."""
    if isinstance(paths, str):
        paths = paths.split(",")

    def reader():
        from ... import native

        yield from native.PrefetchReader(list(paths), capacity=buf_size)

    return reader


def pickled_records(paths, buf_size=100):
    """recordio + pickle.loads per record (the common case: each record is
    one training instance tuple)."""
    base = recordio(paths, buf_size)

    def reader():
        for raw in base():
            yield pickle.loads(raw)

    return reader
