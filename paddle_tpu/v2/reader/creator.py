"""Reader creators from storage (reference python/paddle/v2/reader/
creator.py: np_array, text_file, recordio). The recordio variant streams
through the native C++ prefetch queue (paddle_tpu.native)."""

from __future__ import annotations

import pickle

__all__ = ["np_array", "text_file", "recordio", "pickled_records"]


def np_array(x):
    def reader():
        for e in x:
            yield e

    return reader


def text_file(path):
    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths, buf_size=100):
    """Raw-bytes reader over record files via the native async prefetcher."""
    if isinstance(paths, str):
        paths = paths.split(",")

    def reader():
        from ... import native

        yield from native.PrefetchReader(list(paths), capacity=buf_size)

    return reader


def pickled_records(paths, buf_size=100):
    """recordio + pickle.loads per record (the common case: each record is
    one training instance tuple)."""
    base = recordio(paths, buf_size)

    def reader():
        for raw in base():
            yield pickle.loads(raw)

    return reader


def record_shard(paths, decode_fn=None):
    """Raw-bytes (or decoded) reader over RecordShard chunked shards
    (paddle_tpu.data.record_shard) — the v2-reader face of the input-
    pipeline subsystem's storage format; for prefetching/sharding use
    `paddle_tpu.data.DataLoader` directly."""
    if isinstance(paths, str):
        paths = paths.split(",")

    def reader():
        from ...data.record_shard import RecordShard

        for p in paths:
            for rec in RecordShard(p).iter_records():
                yield decode_fn(rec) if decode_fn is not None else rec

    return reader


__all__.append("record_shard")


def cloud_reader(paths, etcd_endpoints=None, timeout_sec=5, buf_size=64):
    """Records dispatched through the master/coordinator task queue
    (reference creator.py cloud_reader over the Go master + etcd; the
    Coordinator service provides the same lease/retry semantics).
    `etcd_endpoints` may be a coordinator "host:port" (shared queue
    across workers) or None for an in-process coordinator. Records are
    pickled python objects, as written by v2.dataset.common.convert —
    exactly the reference's cPickle.loads contract. Each call of the
    returned reader consumes one pass (coordinator epoch)."""
    import pickle

    from ..master import client as master_client

    if isinstance(paths, str):
        paths = [paths]
    c = master_client(etcd_endpoints, timeout_sec, buf_size)
    c.set_dataset(list(paths))

    def reader():
        while True:
            r = c.next_record()
            if r is None:
                break
            yield pickle.loads(r)

    return reader


__all__.append("cloud_reader")
