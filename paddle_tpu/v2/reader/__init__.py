"""Composable reader decorators (reference python/paddle/v2/reader/
decorator.py: shuffle:51, compose:118, chain:86, buffered:165,
map_readers:29, firstn:208, xmap_readers:236).

A *reader* is a zero-arg callable returning an iterable of data instances;
a *reader creator* returns readers. Pure host-side Python — the device
never sees this layer."""

from __future__ import annotations

import itertools
import queue as _queue
import random
import threading

from . import creator  # noqa: F401

__all__ = [
    "creator",
    "map_readers",
    "buffered",
    "compose",
    "chain",
    "shuffle",
    "firstn",
    "xmap_readers",
    "ComposeNotAligned",
]


class ComposeNotAligned(ValueError):
    pass


def map_readers(func, *readers):
    """Reader whose items are func(items-of-each-reader...)."""

    def reader():
        rs = [r() for r in readers]
        for e in map(func, *rs):
            yield e

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle: read buf_size items, shuffle, yield."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """Concatenate readers: all of r1, then all of r2, ..."""

    def reader():
        rs = [r() for r in readers]
        for e in itertools.chain(*rs):
            yield e

    return reader


def compose(*readers, **kwargs):
    """Zip readers item-wise into flattened tuples."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned"
                    )
                yield sum(list(map(make_tuple, outputs)), ())

    return reader


def buffered(reader, size):
    """Async prefetch into a bounded queue on a worker thread (the
    PyDataProvider2-style double buffer, reference decorator.py:165)."""

    class _End(object):
        pass

    def data_reader():
        r = reader()
        q = _queue.Queue(maxsize=size)

        def read_worker():
            for d in r:
                q.put(d)
            q.put(_End())

        t = threading.Thread(target=read_worker)
        t.daemon = True
        t.start()
        e = q.get()
        while not isinstance(e, _End):
            yield e
            e = q.get()

    return data_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads (reference uses
    threads too, decorator.py:236)."""
    end = object()
    end_count = [0]

    def read_worker(r, in_q):
        for i, d in enumerate(r):
            in_q.put((i, d) if order else d)
        in_q.put(end)

    def handle_worker(in_q, out_q):
        sample = in_q.get()
        while sample is not end:
            if order:
                i, d = sample
                out_q.put((i, mapper(d)))
            else:
                out_q.put(mapper(sample))
            sample = in_q.get()
        in_q.put(end)
        out_q.put(end)

    def xreader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)
        t = threading.Thread(target=read_worker, args=(reader(), in_q))
        t.daemon = True
        t.start()
        workers = []
        for _ in range(process_num):
            w = threading.Thread(target=handle_worker, args=(in_q, out_q))
            w.daemon = True
            w.start()
            workers.append(w)

        finished = 0
        if order:
            buf = {}
            next_i = 0
            while finished < process_num:
                sample = out_q.get()
                if sample is end:
                    finished += 1
                    continue
                i, d = sample
                buf[i] = d
                while next_i in buf:
                    yield buf.pop(next_i)
                    next_i += 1
        else:
            while finished < process_num:
                sample = out_q.get()
                if sample is end:
                    finished += 1
                    continue
                yield sample

    return xreader


class PipeReader(object):
    """Stream records from a shell command's stdout (reference
    reader/decorator.py PipeReader): `get_line` yields lines (or
    fixed-size chunks when line splitting is off) — the HDFS/S3/curl
    ingestion hook."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        if not isinstance(command, str):
            raise TypeError("PipeReader needs a command string")
        self.command = command
        self.bufsize = int(bufsize)
        if file_type not in ("plain", "gzip"):
            raise TypeError("file_type must be 'plain' or 'gzip'")
        self.file_type = file_type

    def get_line(self, cut_lines=True, line_break="\n"):
        import subprocess
        import zlib

        proc = subprocess.Popen(
            self.command, shell=True, bufsize=self.bufsize,
            stdout=subprocess.PIPE,
        )
        dec = zlib.decompressobj(32 + zlib.MAX_WBITS) \
            if self.file_type == "gzip" else None
        remained = b""
        try:
            while True:
                buff = proc.stdout.read(self.bufsize)
                if not buff:
                    break
                if dec is not None:
                    buff = dec.decompress(buff)
                if not cut_lines:
                    if buff:
                        yield buff
                    continue
                remained += buff
                parts = remained.split(line_break.encode())
                remained = parts.pop()
                for line in parts:
                    yield line.decode(errors="replace")
            if cut_lines and remained:
                yield remained.decode(errors="replace")
        finally:
            proc.stdout.close()
            rc = proc.wait()
        if rc != 0:
            raise RuntimeError(
                "PipeReader command %r exited with status %d"
                % (self.command, rc)
            )


__all__.append("PipeReader")
