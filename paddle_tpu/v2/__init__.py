"""paddle.v2-compatible API surface (reference python/paddle/v2/__init__.py).

`import paddle_tpu.v2 as paddle` gives the classic v2 workflow:

    paddle.init(use_gpu=False, trainer_count=1)
    x = paddle.layer.data(name='x', type=paddle.data_type.dense_vector(13))
    y = paddle.layer.fc(input=x, size=1)
    ...
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(cost=cost, parameters=params,
                                 update_equation=paddle.optimizer.Momentum(...))
    trainer.train(paddle.batch(paddle.reader.shuffle(...), 128), ...)

The engine underneath is the fluid Program + XLA executor — `init`'s
use_gpu/trainer_count map to the TPU chip / mesh data axis."""

from . import activation  # noqa: F401
from . import attr  # noqa: F401
from . import networks  # noqa: F401
from . import pooling  # noqa: F401
from . import data_type  # noqa: F401
from . import dataset  # noqa: F401
from . import evaluator  # noqa: F401
from . import event  # noqa: F401
from . import layer  # noqa: F401
from . import master  # noqa: F401
from . import plot  # noqa: F401
from . import minibatch  # noqa: F401
from . import optimizer  # noqa: F401
from . import parameters  # noqa: F401
from . import reader  # noqa: F401
from . import trainer  # noqa: F401
from .minibatch import batch  # noqa: F401
from .trainer import infer  # noqa: F401

# `import paddle.v2.fluid as fluid` parity: the fluid package is shared
from .. import fluid  # noqa: F401
from ..fluid import (  # noqa: F401
    default_main_program,
    default_startup_program,
)

__all__ = [
    "init", "batch", "infer", "layer", "activation", "data_type", "dataset",
    "evaluator", "event", "minibatch", "optimizer", "parameters", "reader",
    "trainer", "attr", "pooling", "networks",
    "default_main_program", "default_startup_program",
    "master", "plot",
    "fluid",
]


def init(**kwargs):
    """Accepted for API parity: use_gpu / trainer_count / log levels. On
    TPU the device exists from process start (XLA owns it) and
    trainer_count maps to the mesh data axis configured via
    paddle_tpu.parallel."""
    return None
