"""paddle.batch (reference python/paddle/v2/minibatch.py)."""

__all__ = ["batch"]


def batch(reader, batch_size):
    """Group a per-instance reader into lists of batch_size instances."""

    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b:
            yield b

    return batch_reader
