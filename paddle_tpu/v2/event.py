"""Training events delivered to user handlers (reference
python/paddle/v2/event.py)."""

__all__ = [
    "EndIteration",
    "BeginIteration",
    "BeginPass",
    "EndPass",
    "TestResult",
    "EndForwardBackward",
]


class WithMetric(object):
    def __init__(self, evaluator=None):
        self.evaluator = evaluator

    @property
    def metrics(self):
        if isinstance(self.evaluator, dict):
            return self.evaluator
        return {}


class TestResult(WithMetric):
    def __init__(self, evaluator=None, cost=None):
        super().__init__(evaluator)
        self.cost = cost


class BeginPass(object):
    def __init__(self, pass_id):
        self.pass_id = pass_id


class EndPass(WithMetric):
    def __init__(self, pass_id, evaluator=None, gm=None):
        super().__init__(evaluator)
        self.pass_id = pass_id
        self.gm = gm


class BeginIteration(object):
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndForwardBackward(object):
    def __init__(self, pass_id, batch_id, gm=None):
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.gm = gm


class EndIteration(WithMetric):
    def __init__(self, pass_id, batch_id, cost, evaluator=None, gm=None):
        super().__init__(evaluator)
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost
        self.gm = gm
