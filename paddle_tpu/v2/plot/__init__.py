"""Training-curve plotting (reference python/paddle/v2/plot/plot.py:32
Ploter). Uses matplotlib when importable and a DISPLAY-less Agg backend;
otherwise silently records values so training scripts run anywhere."""

from __future__ import annotations

__all__ = ["Ploter"]


class PlotData(object):
    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


class Ploter(object):
    def __init__(self, *args):
        self.__args__ = args
        self.__plot_data__ = {t: PlotData() for t in args}
        try:
            import matplotlib

            matplotlib.use("Agg")
            import matplotlib.pyplot as plt

            self._plt = plt
        except Exception:
            self._plt = None

    def __getitem__(self, title):
        return self.__plot_data__[title]

    def append(self, title, step, value):
        self.__plot_data__[title].append(step, value)

    def plot(self, path=None):
        if self._plt is None:
            return
        self._plt.figure()
        for title in self.__args__:
            d = self.__plot_data__[title]
            if d.step:
                self._plt.plot(d.step, d.value, label=title)
        self._plt.legend()
        if path:
            self._plt.savefig(path)
        self._plt.close()

    def reset(self):
        for d in self.__plot_data__.values():
            d.reset()
