"""paddle.v2.pooling (reference python/paddle/v2/pooling.py): pooling
type markers, shared with the config DSL."""

from ..trainer_config_helpers import (  # noqa: F401
    AvgPooling,
    BasePoolingType,
    CudnnAvgPooling,
    CudnnMaxPooling,
    MaxPooling,
    MaxWithMaskPooling,
    SquareRootNPooling,
    SumPooling,
)

Max = MaxPooling
Avg = AvgPooling
Sum = SumPooling
SquareRootN = SquareRootNPooling

__all__ = ["Max", "Avg", "Sum", "SquareRootN", "MaxPooling",
           "AvgPooling", "SumPooling", "SquareRootNPooling",
           "BasePoolingType", "CudnnAvgPooling", "CudnnMaxPooling",
           "MaxWithMaskPooling"]
