"""v2 layer DSL (reference python/paddle/v2/layer.py wrapping
trainer_config_helpers/layers.py's 137 layer functions).

The reference builds a ModelConfig protobuf interpreted by the C++
GradientMachine; here each DSL call records a lazy graph node and
`topology.Topology` (used by parameters.create / trainer.SGD) replays the
node DAG into a fluid Program — one modern core under both API surfaces
(SURVEY.md §7.1)."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from . import activation as act_mod
from . import data_type as dt

__all__ = [
    "data",
    "fc",
    "embedding",
    "concat",
    "img_conv",
    "img_pool",
    "batch_norm",
    "lstmemory",
    "simple_lstm",
    "gru",
    "pooling",
    "last_seq",
    "first_seq",
    "max_id",
    "classification_cost",
    "cross_entropy_cost",
    "mse_cost",
    "regression_cost",
    "dropout",
    "Layer",
    "parse_network",
]


class Layer(object):
    """A lazy DSL node. `name` is stable (auto-generated per type) so
    parameters and feeds can address it."""

    _counters: Dict[str, int] = {}
    _seq = 0  # global creation order (legacy provider slots bind to data
    #           layers by DECLARATION order, not graph-traversal order)

    def __init__(self, kind: str, name: Optional[str], parents: List["Layer"],
                 attrs: Dict[str, Any]):
        self.kind = kind
        if name is None:
            i = Layer._counters.get(kind, 0)
            Layer._counters[kind] = i + 1
            name = "__%s_%d__" % (kind, i)
        self.name = name
        self.parents = parents
        self.attrs = attrs
        Layer._seq += 1
        self.created_at = Layer._seq
        if Layer._registry is not None:
            Layer._registry[self.name] = self
        if Layer._step_nodes is not None:
            Layer._step_nodes.append(self)

    # when not None, every created node is recorded by name — the legacy
    # config path (trainer_config_helpers.reset_config) uses this so
    # Outputs("layer_name") can resolve names to nodes
    _registry: Optional[Dict[str, "Layer"]] = None
    # when not None, created nodes are ALSO appended here — used by
    # recurrent_group to capture side-effect nodes of a step function
    # (e.g. a get_output_layer that closes a memory cycle but is not on
    # the path to the step output)
    _step_nodes: Optional[List["Layer"]] = None

    def __repr__(self):
        return "v2.Layer(%s, %r)" % (self.kind, self.name)


def _as_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _act_name(act):
    if act is None:
        return None
    if isinstance(act, type):
        act = act()
    return act.name


def data(name, type, **kwargs):
    return Layer("data", name, [], {"type": type})


def fc(input, size, act=None, param_attr=None, bias_attr=None, name=None,
       layer_attr=None, **kwargs):
    return Layer("fc", name, _as_list(input), {
        "size": size, "act": _act_name(act), "param_attr": param_attr,
        "bias_attr": bias_attr,
    })


def embedding(input, size, param_attr=None, name=None, **kwargs):
    return Layer("embedding", name, _as_list(input), {
        "size": size, "param_attr": param_attr,
    })


def concat(input, name=None, **kwargs):
    return Layer("concat", name, _as_list(input), {})


def img_conv(input, filter_size, num_filters, num_channels=None, stride=1,
             padding=0, act=None, bias_attr=None, param_attr=None, name=None,
             **kwargs):
    return Layer("img_conv", name, _as_list(input), {
        "filter_size": filter_size, "num_filters": num_filters,
        "num_channels": num_channels, "stride": stride, "padding": padding,
        "act": _act_name(act),
    })


def img_pool(input, pool_size, stride=1, padding=0, pool_type=None, name=None,
             **kwargs):
    ptype = "max"
    if pool_type is not None:
        ptype = getattr(pool_type, "name", str(pool_type)).lower()
        ptype = "avg" if "avg" in ptype else "max"
    return Layer("img_pool", name, _as_list(input), {
        "pool_size": pool_size, "stride": stride, "padding": padding,
        "pool_type": ptype,
    })


def batch_norm(input, act=None, name=None, **kwargs):
    return Layer("batch_norm", name, _as_list(input), {"act": _act_name(act)})


def lstmemory(input, size=None, reverse=False, act=None, name=None, **kwargs):
    return Layer("lstmemory", name, _as_list(input), {
        "size": size, "reverse": reverse,
    })


def simple_lstm(input, size, name=None, **kwargs):
    """fc(4*size) + lstmemory (reference trainer_config_helpers
    simple_lstm). `size` is the hidden width H throughout the DSL."""
    f = fc(input=input, size=size * 4, name=None)
    return Layer("lstmemory", name, [f], {"size": size, "reverse": False})


def gru(input, size, reverse=False, name=None, **kwargs):
    return Layer("gru", name, _as_list(input), {"size": size, "reverse": reverse})


def pooling(input, pooling_type=None, name=None, **kwargs):
    ptype = "max"
    if pooling_type is not None:
        n = type(pooling_type).__name__.lower() if not isinstance(
            pooling_type, str) else pooling_type.lower()
        for cand in ("max", "avg", "sum", "sqrt"):
            if cand in n:
                ptype = cand
    return Layer("seq_pool", name, _as_list(input), {"pool_type": ptype})


def last_seq(input, name=None, **kwargs):
    return Layer("last_seq", name, _as_list(input), {})


def first_seq(input, name=None, **kwargs):
    return Layer("first_seq", name, _as_list(input), {})


def max_id(input, name=None, **kwargs):
    return Layer("max_id", name, _as_list(input), {})


def classification_cost(input, label, name=None, **kwargs):
    return Layer("classification_cost", name, [input, label], {})


def cross_entropy_cost(input, label, name=None, **kwargs):
    return Layer("cross_entropy_cost", name, [input, label], {})


def mse_cost(input, label, name=None, **kwargs):
    return Layer("mse_cost", name, [input, label], {})


regression_cost = mse_cost


def dropout(input, dropout_rate, name=None, **kwargs):
    return Layer("dropout", name, _as_list(input), {"rate": dropout_rate})


def parse_network(*outputs):
    """Topological node order covering `outputs` (reference layer.py
    parse_network returns the pruned ModelConfig)."""
    seen: Dict[int, Layer] = {}
    order: List[Layer] = []

    def visit(node: Layer):
        if id(node) in seen:
            return
        seen[id(node)] = node
        for p in node.parents:
            visit(p)
        order.append(node)

    for o in outputs:
        visit(o)
    return order
