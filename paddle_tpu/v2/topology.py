"""Topology: replay a v2 layer DAG into a fluid Program (reference
python/paddle/v2/topology.py builds a ModelConfig protobuf; here the
single core is the fluid Program and its XLA executor)."""

from __future__ import annotations

from typing import Dict, List

from .. import fluid
from . import data_type as dt
from .layer import Layer, parse_network

__all__ = ["Topology"]


def _user_attr(pa, default_name):
    """fluid ParamAttr from a legacy user attribute: a user name override
    (the legacy weight-sharing mechanism) plus is_static freezing
    (reference ParameterConfig.is_static — the parameter never updates)."""
    return fluid.ParamAttr(
        name=getattr(pa, "name", None) or default_name,
        trainable=not getattr(pa, "is_static", False),
        update_hook=getattr(pa, "update_hooks", None),
    )


class Topology(object):
    def __init__(self, layers, extra_layers=None):
        if not isinstance(layers, (list, tuple)):
            layers = [layers]
        extra = list(extra_layers or [])
        self.output_layers = list(layers)
        self.extra_layers = extra
        self.order = parse_network(*(list(layers) + extra))

        self.main_program = fluid.Program()
        self.startup_program = fluid.Program()
        self.var_of: Dict[str, object] = {}  # layer name -> fluid Variable
        self._scopes: List[Dict[str, object]] = []  # recurrent sub-scopes
        self._data_layers: List[Layer] = []
        with fluid.program_guard(self.main_program, self.startup_program):
            for node in self.order:
                self.var_of[node.name] = self._emit(node)
        # provider slots bind positionally to data layers in DECLARATION
        # order (reference config_parser input order), not traversal order
        self._data_layers.sort(key=lambda n: getattr(n, "created_at", 0))

    # ------------------------------------------------------------------
    def _var(self, name):
        for scope in reversed(self._scopes):
            if name in scope:
                return scope[name]
        return self.var_of[name]

    def _bind(self, name, var):
        (self._scopes[-1] if self._scopes else self.var_of)[name] = var

    def _in(self, node, i=0):
        return self._var(node.parents[i].name)

    def _ins(self, node):
        return [self._var(p.name) for p in node.parents]

    def _emit(self, node: Layer):
        L = fluid.layers
        a = node.attrs
        if node.kind == "data":
            t = a["type"]
            self._data_layers.append(node)
            lod = 1 if t.seq_type != 0 else 0
            if t.type == dt.DataType.Index:
                shape, dtype = [1], "int64"
            else:
                shape, dtype = [t.dim], "float32"
            return L.data(name=node.name, shape=shape, dtype=dtype,
                          lod_level=lod)
        if node.kind == "fc":
            # deterministic parameter names derived from the layer name
            # (reference convention "___fc_0__.w0") so Parameters re-bind
            # across replays of the same DAG; a user ParamAttr(name=...)
            # overrides them, which is how legacy configs SHARE weights
            # (e.g. sample_trainer_config.conf's 'sharew')
            user = a.get("param_attr")
            user_list = (
                list(user) if isinstance(user, (list, tuple))
                else ([user] if user is not None else [])
            )
            attrs = []
            for i in range(len(node.parents)):
                if i < len(user_list):
                    ua = user_list[i]
                elif len(user_list) == 1:
                    ua = user_list[0]  # single attr broadcasts (reference)
                else:
                    ua = None
                attrs.append(
                    fluid.ParamAttr(
                        name=(getattr(ua, "name", None) if i < len(user_list)
                              else None) or "%s.w%d" % (node.name, i),
                        # legacy is_static: the parameter never updates
                        trainable=not getattr(ua, "is_static", False),
                        update_hook=getattr(ua, "update_hooks", None),
                    )
                )
            bias = a.get("bias_attr")
            if bias is False:
                bias_attr = False
            else:
                bias_attr = _user_attr(bias, node.name + ".wbias")
            return L.fc(input=self._ins(node), size=a["size"], act=a["act"],
                        param_attr=attrs, bias_attr=bias_attr)
        if node.kind == "embedding":
            t = node.parents[0].attrs["type"]
            pa = a.get("param_attr")
            return L.embedding(
                input=self._in(node),
                size=[t.dim, a["size"]],
                # legacy ParamAttr(sparse_update=True) -> SelectedRows
                is_sparse=bool(getattr(pa, "sparse_update", False)),
                param_attr=_user_attr(pa, node.name + ".w0"),
            )
        if node.kind == "concat":
            return L.concat(input=self._ins(node), axis=1)
        if node.kind == "img_conv":
            return L.conv2d(
                input=self._in(node), num_filters=a["num_filters"],
                filter_size=a["filter_size"], stride=a["stride"],
                padding=a["padding"], act=a["act"],
                groups=a.get("groups", 1) or 1,
                param_attr=fluid.ParamAttr(name=node.name + ".w0"),
                bias_attr=(
                    False if not a.get("bias", True)
                    else fluid.ParamAttr(name=node.name + ".wbias")
                ),
            )
        if node.kind == "im_reshape":
            c, h, w = a["shape"]
            return L.reshape(x=self._in(node), shape=[-1, c, h, w])
        if node.kind == "lrn":
            return L.lrn(
                input=self._in(node), n=a["size"], k=1.0,
                alpha=a.get("scale", 1e-4), beta=a.get("power", 0.75),
            )
        if node.kind == "addto":
            out = L.sums(input=self._ins(node))
            act = a.get("act")
            if act:
                out = getattr(L, act)(out)
            return out
        if node.kind == "img_pool":
            return L.pool2d(
                input=self._in(node), pool_size=a["pool_size"],
                pool_stride=a["stride"], pool_padding=a["padding"],
                pool_type=a["pool_type"],
                ceil_mode=a.get("ceil_mode", False),
            )
        if node.kind == "batch_norm":
            return L.batch_norm(input=self._in(node), act=a["act"])
        if node.kind == "lstmemory":
            # v2 semantics: `size` is the hidden width H and the input must
            # be 4H wide (fluid dynamic_lstm's `size` argument is 4H)
            size = a["size"]
            if size is None:
                size = int(self._in(node).shape[1]) // 4
            hidden, _ = L.dynamic_lstm(
                input=self._in(node), size=size * 4,
                is_reverse=a.get("reverse", False),
                param_attr=fluid.ParamAttr(name=node.name + ".w0"),
                bias_attr=fluid.ParamAttr(name=node.name + ".wbias"),
            )
            return hidden
        if node.kind == "gru":
            return L.dynamic_gru(
                input=self._in(node), size=a["size"],
                is_reverse=a.get("reverse", False),
                param_attr=fluid.ParamAttr(name=node.name + ".w0"),
            )
        if node.kind == "seq_pool":
            return L.sequence_pool(input=self._in(node),
                                   pool_type=a["pool_type"])
        if node.kind == "last_seq":
            return L.sequence_last_step(input=self._in(node))
        if node.kind == "first_seq":
            return L.sequence_first_step(input=self._in(node))
        if node.kind == "max_id":
            _, idx = L.topk(self._in(node), k=1)
            return idx
        if node.kind == "classification_cost":
            ins = self._ins(node)
            pred, label = ins[0], ins[1]
            # reference classification_cost = softmax output + CE cost; the
            # DSL's `input` already went through act=Softmax
            cost = L.cross_entropy(input=pred, label=label)
            if a.get("weighted") and len(ins) > 2:
                wgt = ins[2]
                num = L.reduce_sum(L.elementwise_mul(x=cost, y=wgt))
                den = L.reduce_sum(wgt)
                return L.elementwise_div(
                    x=L.reshape(x=num, shape=[1]),
                    y=L.reshape(x=den, shape=[1]),
                )
            return L.mean(x=cost)
        if node.kind == "cross_entropy_cost":
            pred, label = self._ins(node)
            return L.mean(x=L.cross_entropy(input=pred, label=label))
        if node.kind == "mse_cost":
            pred, label = self._ins(node)
            return L.mean(x=L.square_error_cost(input=pred, label=label))
        if node.kind == "dropout":
            return L.dropout(x=self._in(node), dropout_prob=a["rate"])
        if node.kind == "classification_error_evaluator":
            pred, label = self._ins(node)
            acc = L.accuracy(input=pred, label=label,
                             k=a.get("top_k", 1) or 1)
            one = L.fill_constant(shape=[1], dtype="float32", value=1.0)
            return L.elementwise_sub(x=one, y=acc)  # error = 1 - accuracy
        if node.kind == "auc_evaluator":
            pred, label = self._ins(node)
            return L.auc(input=pred, label=label)
        if node.kind == "sum_evaluator":
            return L.reduce_sum(self._in(node))
        if node.kind == "column_sum_evaluator":
            return L.reduce_sum(self._in(node), dim=0)
        if node.kind == "mixed":
            return self._emit_mixed(node)
        if node.kind == "recurrent_group":
            return self._emit_recurrent_group(node)
        if node.kind == "beam_gen":
            return self._emit_beam_gen(node)
        if node.kind in _BREADTH_EMITTERS:
            return _BREADTH_EMITTERS[node.kind](self, node)
        if node.kind == "seq_expand":
            x, y = self._ins(node)
            return L.sequence_expand(x, y)
        if node.kind == "eos":
            # 1.0 where the id equals eos_id (reference EosIdCheckLayer)
            x = self._in(node)
            eos = L.fill_constant(shape=[1], dtype="int64",
                                  value=a["eos_id"])
            return L.cast(L.equal(x=x, y=eos), "float32")
        raise NotImplementedError("v2 layer kind %r" % node.kind)

    # ------------------------------------------------------------------
    def _width(self, var, node: Layer):
        """Feature width of a layer's output: the fluid var's static last
        dim when known, else derived from the DSL node (many tmp vars
        carry no static shape)."""
        if getattr(var, "shape", None):
            d = var.shape[-1]
            if d is not None and int(d) > 0:
                return int(d)
        w = self._node_width(node)
        if w is None:
            raise ValueError(
                "cannot determine feature width of layer %r (%s)"
                % (node.name, node.kind)
            )
        return w

    def _node_width(self, node: Layer):
        a = node.attrs
        if node.kind in ("fc", "embedding", "mixed"):
            return int(a["size"])
        if node.kind in ("lstmemory", "gru"):
            return int(a["size"]) if a.get("size") else None
        if node.kind == "data":
            return int(a["type"].dim)
        if node.kind == "rg_memory":
            if a.get("size"):
                return int(a["size"])
            boot = getattr(node, "_boot_layer", None)
            return self._node_width(boot) if boot is not None else None
        if node.kind == "rg_gen_in":
            return int(a["size"])
        if node.kind in ("lstm_step", "gru_step"):
            if a.get("size"):
                return int(a["size"])
            return self._node_width(node.parents[1])
        if node.kind == "get_output":
            return self._node_width(node.parents[0])
        if node.kind in ("rg_step_in", "rg_static_in"):
            return self._node_width(node._outer)
        if node.parents:
            return self._node_width(node.parents[0])
        return None

    def _as_image(self, var, proj):
        """Reshape a flat [N, C*H*W] var to NCHW for conv projections,
        using the DSL node's image geometry."""
        if var.shape is not None and len(var.shape) == 4:
            return var
        shape = getattr(proj.input, "im_shape", None)
        if shape is None:
            c = proj.attrs.get("num_channels") or 3
            import math as _math

            size = self._node_width(proj.input)
            hw = int(round(_math.sqrt(size // c)))
            shape = (c, hw, hw)
        c, h, w = shape
        return fluid.layers.reshape(x=var, shape=[-1, c, h, w])

    def _emit_mixed(self, node: Layer):
        """mixed_layer = sum of projection outputs (+bias, act) — the
        reference MixedLayer with full_matrix/trans/identity/table/
        context/dotmul/scaling projections (gserver/layers/projections)."""
        L = fluid.layers
        a = node.attrs
        size = int(a["size"])
        terms = []
        for k, proj in enumerate(a["projections"]):
            x = self._var(proj.input.name)
            pa = proj.attrs.get("param_attr")
            pname = getattr(pa, "name", None) or "%s.w%d" % (node.name, k)
            if proj.ptype == "full_matrix":
                in_dim = self._width(x, proj.input)
                w = L.create_parameter([in_dim, size], "float32", attr=pname)
                terms.append(L.mul(x=x, y=w))
            elif proj.ptype == "trans_full_matrix":
                # y = x @ W^T; W is [size, in_dim] — the transposed view of
                # a full_matrix/fc weight, enabling weight sharing
                in_dim = self._width(x, proj.input)
                w = L.create_parameter([size, in_dim], "float32", attr=pname)
                terms.append(L.matmul(x=x, y=w, transpose_y=True))
            elif proj.ptype == "identity":
                off = proj.attrs.get("offset")
                if off is not None:
                    psize = proj.attrs.get("size") or size
                    terms.append(
                        L.slice(x, axes=[1], starts=[off], ends=[off + psize])
                    )
                else:
                    terms.append(x)
            elif proj.ptype == "table":
                t = proj.input.attrs.get("type")
                dict_dim = t.dim if t is not None else self._width(x, proj.input)
                terms.append(L.embedding(
                    input=x, size=[dict_dim, size],
                    param_attr=fluid.ParamAttr(name=pname),
                ))
            elif proj.ptype == "context":
                cl = int(proj.attrs["context_len"])
                cs = proj.attrs.get("context_start")
                terms.append(L.sequence_context(
                    input=x, context_length=cl,
                    context_start=-(cl // 2) if cs is None else int(cs),
                ))
            elif proj.ptype == "dotmul":
                in_dim = self._width(x, proj.input)
                w = L.create_parameter([in_dim], "float32", attr=pname)
                terms.append(L.elementwise_mul(x=x, y=w))
            elif proj.ptype == "scaling":
                w = L.create_parameter([1], "float32", attr=pname)
                terms.append(L.elementwise_mul(x=x, y=w))
            elif proj.ptype == "slice":
                parts = [
                    L.slice(x, axes=[1], starts=[a], ends=[b])
                    for a, b in proj.attrs["slices"]
                ]
                terms.append(
                    parts[0] if len(parts) == 1
                    else L.concat(input=parts, axis=1)
                )
            elif proj.ptype == "conv_proj":
                # learned-filter conv inside mixed (reference
                # ConvProjection): output flattened to [N, C*H*W] so it
                # sums with the other projection terms
                nf = int(proj.attrs["num_filters"])
                x = self._as_image(x, proj)
                conv = L.conv2d(
                    input=x, num_filters=nf,
                    filter_size=proj.attrs["filter_size"],
                    stride=proj.attrs.get("stride", 1),
                    padding=proj.attrs.get("padding", 0),
                    groups=proj.attrs.get("groups", 1) or 1,
                    param_attr=fluid.ParamAttr(name=pname),
                    bias_attr=False,
                )
                terms.append(L.reshape(x=conv, shape=[0, -1]))
            elif proj.ptype == "conv_op":
                # dynamic-filter conv (reference ConvOperator): the
                # filter layer's (first-row) values ARE the weights
                f = self._var(proj.extra_inputs[0].name)
                nf = int(proj.attrs["num_filters"])
                fs = int(proj.attrs["filter_size"])
                x = self._as_image(x, proj)
                nc = proj.attrs.get("num_channels") or int(x.shape[1])
                w = L.reshape(
                    x=L.slice(f, axes=[0], starts=[0], ends=[1]),
                    shape=[nf, nc, fs, fs],
                )
                helper = fluid.layer_helper.LayerHelper("conv2d")
                ov = helper.create_tmp_variable("float32")
                helper.append_op(
                    type="conv2d",
                    inputs={"Input": [x], "Filter": [w]},
                    outputs={"Output": [ov]},
                    attrs={
                        "strides": [proj.attrs.get("stride", 1)] * 2,
                        "paddings": [proj.attrs.get("padding", 0)] * 2,
                        "dilations": [1, 1], "groups": 1,
                    },
                )
                terms.append(L.reshape(x=ov, shape=[0, -1]))
            elif proj.ptype == "dotmul_op":
                b = self._var(proj.extra_inputs[0].name)
                term = L.elementwise_mul(x=x, y=b)
                sc = proj.attrs.get("scale", 1.0)
                if sc != 1.0:
                    term = L.scale(x=term, scale=sc)
                terms.append(term)
            else:
                raise NotImplementedError("projection %r" % proj.ptype)
        out = terms[0] if len(terms) == 1 else L.sums(input=terms)
        if a.get("bias_attr") not in (None, False):
            b = L.create_parameter(
                [size], "float32", attr=node.name + ".wbias", is_bias=True
            )
            out = L.elementwise_add(x=out, y=b)
        act = a.get("act")
        if act:
            out = getattr(L, act)(out)
        return out

    # ------------------------------------------------------------------
    def _emit_beam_gen(self, node: Layer):
        """Legacy beam_search generation (reference
        RecurrentGradientMachine::generateSequence:307/beamSearch:309):
        lowered to the fluid While + beam_search + beam_search_decode
        machinery, which compiles to peel + ONE lax.fori_loop
        (core/kernels_control.py). The step replays per iteration with
        the GeneratedInput placeholder bound to the embedding of the
        previous step's selected words and StaticInputs expanded to the
        live beam width. Returns the decoded sentence-id layer
        (reference default output "__beam_search_predict__"); when
        num_results_per_sample < beam_size the decode keeps each
        source's top-n rows by cumulative score."""
        from .layer import parse_network

        L = fluid.layers
        a = node.attrs
        if a["mems"]:
            raise NotImplementedError(
                "beam_search step functions with memory() are not "
                "supported yet; carry state through the generated words"
            )
        gen = a["gen"]
        placeholders = a["placeholders"]
        statics = a["static_phs"]
        if statics:
            anchor = self._var(statics[0]._outer.name)
        else:
            raise ValueError(
                "beam_search needs at least one StaticInput to size the "
                "generation batch (reference: a Memory must have a boot "
                "layer when generating)"
            )

        from ..fluid.layer_helper import LayerHelper

        helper = LayerHelper("beam_gen")
        init_ids = helper.create_tmp_variable(dtype="int64")
        init_scores = helper.create_tmp_variable(dtype="float32")
        helper.append_op(
            type="beam_init", inputs={"X": [anchor]},
            outputs={"Ids": [init_ids], "Scores": [init_scores]},
            attrs={"bos_id": a["bos_id"]},
        )

        max_len = L.fill_constant(shape=[1], dtype="int64",
                                  value=a["max_length"])
        counter = L.zeros(shape=[1], dtype="int64", force_cpu=True)
        ids_array = L.create_array("int64")
        scores_array = L.create_array("float32")
        L.array_write(init_ids, array=ids_array, i=counter)
        L.array_write(init_scores, array=scores_array, i=counter)

        cond = L.less_than(x=counter, y=max_len)
        while_op = L.While(cond=cond)
        ph_ids = {id(p) for p in placeholders}
        with while_op.block():
            pre_ids = L.array_read(array=ids_array, i=counter)
            pre_score = L.array_read(array=scores_array, i=counter)
            emb = L.embedding(
                input=pre_ids,
                size=[gen.size, gen.embedding_size],
                param_attr=fluid.ParamAttr(name=gen.embedding_name),
            )
            local: Dict[str, object] = {}
            self._scopes.append(local)
            try:
                for ph in placeholders:
                    if ph.kind == "rg_gen_in":
                        local[ph.name] = emb
                    else:  # static: expand to the live beam width
                        local[ph.name] = L.sequence_expand(
                            self._var(ph._outer.name), pre_score
                        )
                for sub in parse_network(a["step_out"]):
                    if id(sub) in ph_ids or sub.name in local:
                        continue
                    local[sub.name] = self._emit(sub)
                out_var = local[a["step_out"].name]
            finally:
                self._scopes.pop()
            # topk width: twice the beam, capped at the vocab size
            k = min(int(gen.size), 2 * a["beam_size"])
            topk_scores, topk_idx = L.topk(out_var, k=k)
            selected_ids, selected_scores = L.beam_search(
                pre_ids, topk_idx, topk_scores, a["beam_size"],
                end_id=a["eos_id"],
            )
            L.increment(x=counter, value=1, in_place=True)
            L.array_write(selected_ids, array=ids_array, i=counter)
            L.array_write(selected_scores, array=scores_array, i=counter)
            L.less_than(x=counter, y=max_len, cond=cond)

        sentence_ids, sentence_scores = L.beam_search_decode(
            ids=ids_array, scores=scores_array,
            beam_width=a["beam_size"],
            num_results_per_sample=a.get("num_results_per_sample", 0),
        )
        self._bind(node.name + ".scores", sentence_scores)
        return sentence_ids  # carries .lens_name for per-row true lengths

    # ------------------------------------------------------------------
    def _emit_recurrent_group(self, node: Layer):
        """recurrent_group -> fluid DynamicRNN: the step sub-DAG replays
        inside rnn.block() with placeholders bound to step/static inputs
        and memory() nodes to rnn.memory() (reference
        RecurrentGradientMachine; here one lax.scan, kernels_control)."""
        from .layer import parse_network

        L = fluid.layers
        a = node.attrs
        step_out = a["step_out"]
        placeholders = a["placeholders"]
        mems = a["mems"]
        reverse = bool(a.get("reverse"))

        rnn = L.DynamicRNN()
        ph_ids = {id(p) for p in placeholders} | {id(m) for m in mems}
        # outer-block vars resolved (and, for a reversed group,
        # time-flipped) BEFORE entering the step sub-block: a reversed
        # group = forward scan over the flipped sequences, output
        # un-flipped below (reference RecurrentLayer reversed_=true
        # walks t = len-1 .. 0)
        outer_vars = {}
        for ph in placeholders:
            outer = self._var(ph._outer.name)
            if reverse and ph.kind == "rg_step_in":
                outer = L.sequence_reverse(outer)
            outer_vars[id(ph)] = outer
        with rnn.block():
            local: Dict[str, object] = {}
            self._scopes.append(local)
            try:
                for ph in placeholders:
                    outer = outer_vars[id(ph)]
                    if ph.kind == "rg_step_in":
                        local[ph.name] = rnn.step_input(outer)
                    else:
                        local[ph.name] = rnn.static_input(outer)
                mem_pre = {}
                for m in mems:
                    boot = m.attrs.get("boot_name")
                    if boot is not None:
                        pre = rnn.memory(init=self._var(boot))
                    else:
                        size = m.attrs.get("size")
                        if size is None:
                            # reference RecurrentLayer: the state is as
                            # wide as the step input
                            seq_phs = [
                                p for p in placeholders
                                if p.kind == "rg_step_in"
                            ]
                            size = self._node_width(seq_phs[0])
                        pre = rnn.memory(shape=[int(size)], value=0.0)
                    local[m.name] = pre
                    mem_pre[m.attrs["ref_name"]] = pre
                # replay the step sub-DAG (placeholders/memories
                # excluded), PLUS any side-effect node that closes a
                # memory cycle without being on the output path (e.g.
                # get_output_layer of an lstm_step's cell)
                mem_closers = [
                    n for n in node.attrs.get("step_nodes", [])
                    if n.name in mem_pre
                ]
                targets = [step_out] + mem_closers
                for sub in parse_network(*targets):
                    if id(sub) in ph_ids or sub.name in local:
                        continue
                    local[sub.name] = self._emit(sub)
                    if sub.name in mem_pre:
                        rnn.update_memory(mem_pre[sub.name], local[sub.name])
                rnn.output(local[step_out.name])
            finally:
                self._scopes.pop()
        out = rnn()
        if reverse:
            out = L.sequence_reverse(out)
        return out

    # ------------------------------------------------------------------
    def data_layers(self) -> Dict[str, Layer]:
        return {n.name: n for n in self._data_layers}

    def data_type(self):
        return [(n.name, n.attrs["type"]) for n in self._data_layers]

    def get_layer_proto(self, name):
        return None


# ---------------------------------------------------------------------------
# breadth-wrapper lowerings (trainer_config_helpers breadth layers; each a
# thin mapping onto fluid layers/kernels — reference layers.py semantics)
# ---------------------------------------------------------------------------


def _L():
    return fluid.layers


def _act_apply(out, act):
    return getattr(_L(), act)(out) if act else out


def _emit_cos_sim(t, node):
    a, b = t._ins(node)
    out = _L().cos_sim(X=a, Y=b)
    s = node.attrs.get("scale", 1.0)
    return _L().scale(x=out, scale=float(s)) if s != 1.0 else out


def _emit_trans(t, node):
    return _L().transpose(t._in(node), [1, 0])


def _emit_power(t, node):
    x, w = t._ins(node)
    return _L().elementwise_pow(x=x, y=w)


def _emit_scaling(t, node):
    x, w = t._ins(node)
    return _L().elementwise_mul(x=x, y=w)


def _emit_interpolation(t, node):
    a, b, w = t._ins(node)
    one_minus_w = _L().scale(x=w, scale=-1.0, bias=1.0)
    wa = _L().elementwise_mul(x=a, y=w)
    wb = _L().elementwise_mul(x=b, y=one_minus_w)
    return _L().elementwise_add(x=wa, y=wb)


def _emit_slope_intercept(t, node):
    return _L().scale(x=t._in(node), scale=node.attrs["slope"],
                      bias=node.attrs["intercept"])


def _emit_sum_to_one_norm(t, node):
    x = t._in(node)
    s = _L().reduce_sum(x, dim=1, keep_dim=True)
    return _L().elementwise_div(x=x, y=s)


def _emit_row_l2_norm(t, node):
    return _L().l2_normalize(x=t._in(node), axis=1)


def _emit_dot_prod(t, node):
    a, b = t._ins(node)
    return _L().reduce_sum(_L().elementwise_mul(x=a, y=b), dim=1,
                           keep_dim=True)


def _emit_out_prod(t, node):
    a, b = t._ins(node)
    da = t._width(a, node.parents[0])
    db = t._width(b, node.parents[1])
    a3 = _L().reshape(x=a, shape=[-1, da, 1])
    b3 = _L().reshape(x=b, shape=[-1, 1, db])
    return _L().reshape(x=_L().elementwise_mul(x=a3, y=b3),
                        shape=[-1, da * db])


def _emit_l2_distance(t, node):
    a, b = t._ins(node)
    d = _L().elementwise_sub(x=a, y=b)
    return _L().sqrt(_L().reduce_sum(_L().square(d), dim=1, keep_dim=True))


def _emit_pad_img(t, node):
    a = node.attrs
    x = t._in(node)  # [N, C, H, W]
    pads = [0, 0] + list(a["pad_c"]) + list(a["pad_h"]) + list(a["pad_w"])
    return _L().pad(x=x, paddings=pads)


def _emit_clip(t, node):
    return _L().clip(x=t._in(node), min=node.attrs["min"],
                     max=node.attrs["max"])


def _emit_multiplex(t, node):
    ins = t._ins(node)
    return _L().multiplex(inputs=ins[1:], index=ins[0])


def _emit_row_conv(t, node):
    # legacy context_len counts the current step + lookahead; fluid's
    # future_context_size counts lookahead only
    out = _L().row_conv(input=t._in(node),
                        future_context_size=node.attrs["context_len"] - 1)
    return _act_apply(out, node.attrs.get("act"))


def _emit_maxout(t, node):
    return _L().maxout(x=t._in(node), groups=node.attrs["groups"])


def _emit_block_expand(t, node):
    a = node.attrs
    return _L().im2sequence(
        input=t._in(node), filter_size=a["block"], stride=a["stride"],
        padding=a["padding"],
    )


def _emit_seq_reshape(t, node):
    return _L().sequence_reshape(input=t._in(node),
                                 new_dim=node.attrs["new_dim"])


def _emit_repeat(t, node):
    return _L().expand(x=t._in(node),
                       expand_times=[1, node.attrs["num_repeats"]])


def _emit_recurrent_step(t, node):
    """Inner step of recurrent_layer: act(x_t + W h_prev)."""
    x, h = t._ins(node)
    width = t._width(x, node.parents[0])
    pa = node.attrs.get("param_attr")
    pname = getattr(pa, "name", None) or node.name + ".w0"
    w = _L().create_parameter([width, width], "float32", attr=pname)
    out = _L().elementwise_add(x=x, y=_L().mul(x=h, y=w))
    return _act_apply(out, node.attrs.get("act"))


def _emit_ctc_cost(t, node):
    pred, label = t._ins(node)
    cost = _L().warpctc(input=pred, label=label,
                        blank=node.attrs["blank"],
                        norm_by_times=node.attrs.get("norm_by_times", False))
    return _L().mean(x=cost)


def _emit_crf_cost(t, node):
    pred, label = t._ins(node)
    pa = node.attrs.get("param_attr")
    attr = _user_attr(pa, node.name + ".w0")
    cost = _L().linear_chain_crf(input=pred, label=label, param_attr=attr)
    return _L().mean(x=cost)


def _emit_crf_decode(t, node):
    pred = t._in(node)
    pa = node.attrs.get("param_attr")
    pname = getattr(pa, "name", None) or node.name + ".w0"
    blk = fluid.default_main_program().global_block()
    if not blk.has_var(pname):
        # standalone decode (legacy crf_decoding_layer creates its own
        # transition parameter): [size+2, size] like linear_chain_crf
        size = t._width(pred, node.parents[0])
        _L().create_parameter([size + 2, size], "float32", attr=pname)
    return _L().crf_decoding(input=pred, param_attr=fluid.ParamAttr(name=pname))


def _emit_nce_cost(t, node):
    L = _L()
    ins = t._ins(node)
    weighted = node.attrs.get("weighted")
    sample_weight = ins[-1] if weighted else None
    label = ins[-2] if weighted else ins[-1]
    feats = ins[:-2] if weighted else ins[:-1]
    # multi-input NCE: separate per-input weight matrices in the
    # reference sum into one concatenated feature (same math)
    x = feats[0] if len(feats) == 1 else L.concat(input=feats, axis=1)
    cost = L.nce(input=x, label=label,
                 num_total_classes=node.attrs["num_classes"],
                 num_neg_samples=node.attrs["num_neg_samples"],
                 sample_weight=sample_weight,
                 neg_distribution=node.attrs.get("neg_distribution"))
    if weighted:
        # same convention as weighted classification_cost:
        # sum(w * cost_i) / sum(w) — the kernel already applied w
        den = L.reduce_sum(sample_weight)
        return L.elementwise_div(
            x=L.reshape(x=L.reduce_sum(cost), shape=[1]),
            y=L.reshape(x=den, shape=[1]),
        )
    return L.mean(x=cost)


def _emit_hsigmoid_cost(t, node):
    ins = t._ins(node)
    cost = _L().hsigmoid(input=ins[0], label=ins[-1],
                         num_classes=node.attrs["num_classes"])
    return _L().mean(x=cost)


def _emit_rank_cost(t, node):
    from ..fluid.layer_helper import LayerHelper

    left, right, label = t._ins(node)
    helper = LayerHelper("rank_loss")
    out = helper.create_tmp_variable(dtype="float32")
    helper.append_op(
        type="rank_loss",
        inputs={"Left": [left], "Right": [right], "Label": [label]},
        outputs={"Out": [out]},
    )
    return _L().mean(x=out)


def _emit_huber_cost(t, node):
    from ..fluid.layer_helper import LayerHelper

    x, y = t._ins(node)
    helper = LayerHelper("huber_loss")
    out = helper.create_tmp_variable(dtype="float32")
    resid = helper.create_tmp_variable(dtype="float32")
    helper.append_op(
        type="huber_loss",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out], "Residual": [resid]},
        attrs={"delta": node.attrs["delta"]},
    )
    return _L().mean(x=out)


def _emit_multi_binary_ce(t, node):
    # the legacy layer takes already-sigmoid-activated PROBABILITIES
    # (reference multi_binary_label_cross_entropy docs) — plain BCE
    p, label = t._ins(node)
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("log_loss")
    out = helper.create_tmp_variable(dtype="float32")
    helper.append_op(
        type="log_loss",
        inputs={"Predicted": [p], "Labels": [label]},
        outputs={"Loss": [out]},
    )
    return _L().mean(x=out)


def _emit_smooth_l1_cost(t, node):
    x, y = t._ins(node)
    return _L().mean(x=_L().smooth_l1(x=x, y=y))


def _emit_sum_cost(t, node):
    return _L().reduce_sum(t._in(node))


def _emit_scale_shift(t, node):
    x = t._in(node)
    pa = node.attrs.get("param_attr")
    w = _L().create_parameter(
        [1], "float32",
        attr=getattr(pa, "name", None) or node.name + ".w0",
        default_initializer=fluid.initializer.Constant(1.0),
    )
    ba = node.attrs.get("bias_attr")
    b = _L().create_parameter(
        [1], "float32",
        attr=getattr(ba, "name", None) or node.name + ".wbias",
        is_bias=True,
    )
    return _L().elementwise_add(x=_L().elementwise_mul(x=x, y=w), y=b)


def _emit_elem_mul(t, node):
    a, b = t._ins(node)
    return _L().elementwise_mul(x=a, y=b)


_BREADTH_EMITTERS = {
    "cos_sim": _emit_cos_sim,
    "trans": _emit_trans,
    "power": _emit_power,
    "scaling": _emit_scaling,
    "interpolation": _emit_interpolation,
    "slope_intercept": _emit_slope_intercept,
    "sum_to_one_norm": _emit_sum_to_one_norm,
    "row_l2_norm": _emit_row_l2_norm,
    "dot_prod": _emit_dot_prod,
    "out_prod": _emit_out_prod,
    "l2_distance": _emit_l2_distance,
    "pad_img": _emit_pad_img,
    "clip": _emit_clip,
    "multiplex": _emit_multiplex,
    "row_conv": _emit_row_conv,
    "maxout": _emit_maxout,
    "block_expand": _emit_block_expand,
    "seq_reshape": _emit_seq_reshape,
    "repeat": _emit_repeat,
    "recurrent_step": _emit_recurrent_step,
    "ctc_cost": _emit_ctc_cost,
    "crf_cost": _emit_crf_cost,
    "crf_decode": _emit_crf_decode,
    "nce_cost": _emit_nce_cost,
    "hsigmoid_cost": _emit_hsigmoid_cost,
    "rank_cost": _emit_rank_cost,
    "huber_cost": _emit_huber_cost,
    "multi_binary_ce": _emit_multi_binary_ce,
    "smooth_l1_cost": _emit_smooth_l1_cost,
    "sum_cost": _emit_sum_cost,
    "scale_shift": _emit_scale_shift,
    "elem_mul": _emit_elem_mul,
}


def _emit_sampling_id(t, node):
    return _L().sampling_id(t._in(node))


def _emit_bilinear_interp(t, node):
    return _L().bilinear_interp(t._in(node), out_h=node.attrs["out_h"],
                                out_w=node.attrs["out_w"])


def _emit_conv_shift(t, node):
    a, b = t._ins(node)
    return _L().conv_shift(x=a, y=b)


def _emit_switch_order(t, node):
    c, h, w = node.attrs["shape"]
    out = _L().transpose(t._in(node), [0, 2, 3, 1])  # NCHW -> NHWC
    return _L().reshape(x=out, shape=[-1, h * w * c])


def _emit_spp(t, node):
    c, h, w = node.attrs["im_shape"]
    ptype = node.attrs["pool_type"]
    flats = []
    for level in range(int(node.attrs["pyramid_height"])):
        bins = 2 ** level
        kh, kw = -(-h // bins), -(-w // bins)  # ceil
        pooled = _L().pool2d(
            input=t._in(node), pool_size=[kh, kw],
            pool_stride=[kh, kw], pool_type=ptype, ceil_mode=True,
        )
        # ceil-mode pooling yields ceil(h/kh) x ceil(w/kw) bins — equal to
        # bins x bins only when 2^level tiles the map (the common SPP
        # geometry); size the flat from the ACTUAL output
        obh, obw = -(-h // kh), -(-w // kw)
        flats.append(_L().reshape(x=pooled, shape=[-1, c * obh * obw]))
    return flats[0] if len(flats) == 1 else _L().concat(input=flats, axis=1)


def _emit_factorization_machine(t, node):
    x = t._in(node)
    in_dim = t._width(x, node.parents[0])
    f = int(node.attrs["factor_size"])
    pa = node.attrs.get("param_attr")
    v = _L().create_parameter(
        [in_dim, f], "float32",
        attr=getattr(pa, "name", None) or node.name + ".w0",
    )
    xv = _L().mul(x=x, y=v)                       # [N, F]
    x2v2 = _L().mul(x=_L().square(x), y=_L().square(v))
    diff = _L().elementwise_sub(x=_L().square(xv), y=x2v2)
    return _L().scale(x=_L().reduce_sum(diff, dim=1, keep_dim=True),
                      scale=0.5)


def _emit_huber_cls_cost(t, node):
    x, label = t._ins(node)
    # labels in {0,1} -> y in {-1,+1}; margin m = y*x
    y = _L().scale(x=_L().cast(label, "float32"), scale=2.0, bias=-1.0)
    m = _L().elementwise_mul(x=x, y=y)
    # piecewise: m>=1 -> 0; |m|<1 -> (1-m)^2; m<=-1 -> -4m
    # == clip(1-m, 0, 2)^2 + 4*clip(-1-m, 0, inf)
    t1 = _L().clip(x=_L().scale(x=m, scale=-1.0, bias=1.0), min=0.0, max=2.0)
    t2 = _L().clip(x=_L().scale(x=m, scale=-1.0, bias=-1.0), min=0.0,
                   max=3.4e38)
    loss = _L().elementwise_add(x=_L().square(t1),
                                y=_L().scale(x=t2, scale=4.0))
    return _L().mean(x=loss)


_BREADTH_EMITTERS.update({
    "sampling_id": _emit_sampling_id,
    "bilinear_interp": _emit_bilinear_interp,
    "conv_shift": _emit_conv_shift,
    "switch_order": _emit_switch_order,
    "spp": _emit_spp,
    "factorization_machine": _emit_factorization_machine,
    "huber_cls_cost": _emit_huber_cls_cost,
})


def _emit_seq_slice(t, node):
    x = t._in(node)
    a = node.attrs
    L = _L()
    if not a["has_ends"]:
        raise NotImplementedError(
            "seq_slice_layer without ends=: pass explicit end indices"
        )
    idx = 1
    if a["has_starts"]:
        starts = L.cast(t._var(node.parents[idx].name), "int32")
        idx += 1
        ends = L.cast(t._var(node.parents[idx].name), "int32")
    else:
        # begin of each sequence: a per-SEQUENCE zeros tensor, shaped
        # like `ends` (one row per sequence, not per token)
        ends = L.cast(t._var(node.parents[idx].name), "int32")
        starts = L.scale(x=ends, scale=0.0)
    length = L.elementwise_sub(x=ends, y=starts)
    return L.sequence_slice(input=x, offset=starts, length=length)


def _emit_sub_seq(t, node):
    x, offsets, sizes = t._ins(node)
    L = _L()
    return L.sequence_slice(input=x, offset=L.cast(offsets, "int32"),
                            length=L.cast(sizes, "int32"))


def _emit_lstm_step(t, node):
    x, c_prev = t._ins(node)
    from ..fluid.layer_helper import LayerHelper

    H = t._width(c_prev, node.parents[1])
    helper = LayerHelper("lstm_unit")
    c = helper.create_tmp_variable(dtype="float32", shape=(-1, H))
    h = helper.create_tmp_variable(dtype="float32", shape=(-1, H))
    helper.append_op(
        type="lstm_unit",
        inputs={"X": [x], "C_prev": [c_prev]},
        outputs={"C": [c], "H": [h]},
        attrs={"forget_bias": 0.0},
    )
    t._bind(node.name + "@out_state", c)
    return h


def _emit_gru_step(t, node):
    x, h_prev = t._ins(node)
    size = node.attrs.get("size") or t._width(h_prev, node.parents[1])
    pa = node.attrs.get("param_attr")
    # the existing fluid gru_unit wrapper creates weight + bias + outputs
    # (reference GruStepLayer includes the gate bias)
    ba = node.attrs.get("bias_attr")
    hidden, _, _ = _L().gru_unit(
        input=x, hidden=h_prev, size=3 * int(size),
        param_attr=_user_attr(pa, node.name + ".w0"),
        bias_attr=_user_attr(ba, node.name + ".wbias"),
    )
    return hidden


def _emit_get_output(t, node):
    return t._var(node.parents[0].name + "@out_state")


def _emit_tensor(t, node):
    a, b = t._ins(node)
    da = t._width(a, node.parents[0])
    db = t._width(b, node.parents[1])
    k = int(node.attrs["size"])
    pa = node.attrs.get("param_attr")
    w = _L().create_parameter(
        [da, k * db], "float32",
        attr=getattr(pa, "name", None) or node.name + ".w0",
    )
    aw = _L().reshape(x=_L().mul(x=a, y=w), shape=[-1, k, db])  # [N,K,db]
    b3 = _L().reshape(x=b, shape=[-1, 1, db])
    out = _L().reduce_sum(_L().elementwise_mul(x=aw, y=b3), dim=2)
    act = node.attrs.get("act")
    return _act_apply(out, act)


_BREADTH_EMITTERS.update({
    "seq_slice": _emit_seq_slice,
    "sub_seq": _emit_sub_seq,
    "lstm_step": _emit_lstm_step,
    "gru_step": _emit_gru_step,
    "get_output": _emit_get_output,
    "tensor": _emit_tensor,
})


def _emit_identity(t, node):
    return t._in(node)


def _emit_resize(t, node):
    return _L().reshape(x=t._in(node), shape=[-1, node.attrs["size"]])


def _emit_rotate(t, node):
    # reference RotateLayer is CLOCKWISE: out(c, H-1-r) = in(r, c) —
    # transpose H/W then flip the (new) W axis
    out = _L().transpose(t._in(node), [0, 1, 3, 2])
    return _L().reverse(out, axis=[3])


def _emit_cross_channel_norm(t, node):
    x = t._in(node)
    c = int(node.attrs["channels"])
    pa = node.attrs.get("param_attr")
    scale = _L().create_parameter(
        [1, c, 1, 1], "float32",
        attr=getattr(pa, "name", None) or node.name + ".w0",
        default_initializer=fluid.initializer.Constant(1.0),
    )
    sq = _L().reduce_sum(_L().square(x), dim=1, keep_dim=True)
    norm = _L().sqrt(_L().scale(x=sq, scale=1.0, bias=1e-10))
    return _L().elementwise_mul(x=_L().elementwise_div(x=x, y=norm),
                                y=scale)


_BREADTH_EMITTERS.update({
    "identity": _emit_identity,
    "resize": _emit_resize,
    "rotate": _emit_rotate,
    "cross_channel_norm": _emit_cross_channel_norm,
})


# ---------------------------------------------------------------------
# breadth round 5 emitters: detection, 3-D conv/pool, image geometry,
# ranking/beam costs (reference gserver PriorBoxLayer, MultiBoxLossLayer,
# DetectionOutputLayer, ROIPoolLayer, CropLayer, PReluLayer,
# Conv3DLayer, Pool3DLayer, ConvexCombinationLayer, KmaxSeqScoreLayer,
# SubNestedSequenceLayer, CostLayer.cpp LambdaCost /
# MultiClassCrossEntropyWithSelfNorm, CrossEntropyOverBeam.cpp)
# ---------------------------------------------------------------------


def _emit_crop(t, node):
    x = t._in(node)
    a = node.attrs
    if a["shape"] is None:
        raise NotImplementedError("crop_layer needs an explicit shape")
    axis = int(a["axis"])
    shape = [int(s) for s in x.shape]
    offs = [0] * len(shape)
    for k, (o, s) in enumerate(zip(a["offset"], a["shape"])):
        if axis + k < len(shape):
            offs[axis + k] = int(o)
            shape[axis + k] = int(s)
    # batch axis: crop nothing (dynamic N) — kernel slices by python ints,
    # so pass the traced dim through as the full extent
    shape[0] = -1
    return _L().crop(x, shape=shape, offsets=offs)


def _emit_prelu(t, node):
    pa = node.attrs.get("param_attr")
    return _L().prelu(
        t._in(node), mode=node.attrs["mode"],
        param_attr=_user_attr(pa, node.name + ".w0"),
    )


def _emit_priorbox(t, node):
    feat = t._var(node.parents[0].name)
    img = t._var(node.parents[1].name)
    if img.shape is None or len(img.shape) != 4:
        c, h, w = node.parents[1].im_shape
        img = _L().reshape(x=img, shape=[-1, c, h, w])
    a = node.attrs
    boxes, variances = fluid.layers.prior_box(
        input=feat, image=img, min_sizes=a["min_size"],
        max_sizes=a["max_size"] or None,
        aspect_ratios=a["aspect_ratio"], variance=a["variance"],
        flip=True, clip=True,
    )
    L = _L()
    # [H, W, P, 4] anchor grid -> flat [M, 4], matching the loc/conf
    # head flattening order (NHWC -> [N, H*W*P, ...])
    boxes = L.reshape(x=boxes, shape=[-1, 4])
    variances = L.reshape(x=variances, shape=[-1, 4])
    t._bind(node.name + "@var", variances)
    return boxes


def _ssd_heads(t, node):
    """Gather loc/conf conv features into [N, P, 4] and [N, P, C]."""
    L = _L()
    a = node.attrs
    n_loc = a["n_loc"]
    locs = [t._var(p.name) for p in node.parents[:n_loc]]
    confs = [t._var(p.name) for p in node.parents[n_loc:2 * n_loc]]
    C = int(a["num_classes"])

    def flat(vs, width):
        parts = []
        for v in vs:
            nhwc = L.transpose(v, [0, 2, 3, 1])
            parts.append(L.reshape(x=nhwc, shape=[0, -1, width]))
        return parts[0] if len(parts) == 1 else L.concat(input=parts, axis=1)

    return flat(locs, 4), flat(confs, C)


def _emit_detection_output(t, node):
    L = _L()
    a = node.attrs
    loc, conf = _ssd_heads(t, node)
    priors = t._var(node.parents[-1].name)
    variances = t._var(node.parents[-1].name + "@var")
    scores = L.transpose(L.softmax(conf), [0, 2, 1])  # [N, C, P]
    return fluid.layers.detection_output(
        scores=scores, loc=loc, prior_box=priors, prior_box_var=variances,
        background_label=a["background_id"],
        nms_threshold=a["nms_threshold"], nms_top_k=a["nms_top_k"],
        keep_top_k=a["keep_top_k"],
        score_threshold=a["confidence_threshold"],
    )


def _emit_multibox_loss(t, node):
    L = _L()
    a = node.attrs
    loc, conf = _ssd_heads(t, node)
    priors = t._var(node.parents[-2].name)
    variances = t._var(node.parents[-2].name + "@var")
    label = t._var(node.parents[-1].name)
    # label rows: [class, xmin, ymin, xmax, ymax(, difficult)]
    gt_label = L.lod_reset(
        L.cast(L.slice(label, axes=[1], starts=[0], ends=[1]), "int64"),
        y=label,
    )
    gt_box = L.lod_reset(
        L.slice(label, axes=[1], starts=[1], ends=[5]), y=label
    )
    cost = fluid.layers.ssd_loss(
        location=loc, confidence=conf, gt_box=gt_box, gt_label=gt_label,
        prior_box=priors, prior_box_var=variances,
        overlap_threshold=a["overlap_threshold"],
        neg_pos_ratio=a["neg_pos_ratio"], neg_overlap=a["neg_overlap"],
        background_label=a["background_id"],
    )
    return L.mean(x=cost)


def _emit_roi_pool(t, node):
    a = node.attrs
    return _L().roi_pool(
        t._var(node.parents[0].name), t._var(node.parents[1].name),
        pooled_height=a["pooled_height"], pooled_width=a["pooled_width"],
        spatial_scale=a["spatial_scale"],
    )


def _emit_scale_sub_region(t, node):
    x = t._var(node.parents[0].name)
    idx = _L().cast(t._var(node.parents[1].name), "int32")
    return _L().scale_sub_region(x, idx, node.attrs["value"])


def _emit_vol_reshape(t, node):
    c, d, h, w = node.attrs["shape"]
    return _L().reshape(x=t._in(node), shape=[-1, c, d, h, w])


def _emit_img_conv3d(t, node):
    a = node.attrs
    pa = a.get("param_attr")
    return _L().conv3d(
        input=t._in(node), num_filters=a["num_filters"],
        filter_size=a["filter_size"], stride=a["stride"],
        padding=a["padding"], groups=a.get("groups", 1) or 1,
        act=a["act"],
        param_attr=_user_attr(pa, node.name + ".w0"),
        bias_attr=(
            False if not a.get("bias", True)
            else fluid.ParamAttr(name=node.name + ".wbias")
        ),
    )


def _emit_img_pool3d(t, node):
    a = node.attrs
    return _L().pool3d(
        input=t._in(node), pool_size=a["pool_size"],
        pool_type=a["pool_type"], pool_stride=a["stride"],
        pool_padding=a["padding"], ceil_mode=a.get("ceil_mode", True),
    )


def _emit_linear_comb(t, node):
    L = _L()
    w, v = t._ins(node)
    zdim = t._width(w, node.parents[0])
    full = t._width(v, node.parents[1])
    size = node.attrs.get("size") or full // zdim
    v3 = L.reshape(x=v, shape=[-1, zdim, size])
    w3 = L.reshape(x=w, shape=[-1, zdim, 1])
    return L.reshape(
        x=L.reduce_sum(L.elementwise_mul(x=v3, y=w3), dim=1),
        shape=[-1, size],
    )


def _emit_kmax_seq_score(t, node):
    return _L().kmax_sequence_score(
        t._in(node), beam_size=node.attrs["beam_size"]
    )


def _emit_sub_nested_seq(t, node):
    x = t._var(node.parents[0].name)
    sel = _L().cast(t._var(node.parents[1].name), "int32")
    return _L().sub_nested_seq(x, sel)


def _emit_lambda_cost(t, node):
    score, label = t._ins(node)
    return _L().lambda_rank_cost(
        score, label, ndcg_num=node.attrs["NDCG_num"]
    )


def _emit_ce_selfnorm(t, node):
    L = _L()
    x, label = t._ins(node)
    a = node.attrs
    z = L.reduce_sum(x, dim=1, keep_dim=True)
    logz = L.log(z)
    cost = L.elementwise_add(
        x=L.cross_entropy(input=x, label=label),
        y=L.elementwise_add(
            x=logz, y=L.scale(x=L.square(logz), scale=a["alpha"])
        ),
    )
    if a.get("coeff", 1.0) != 1.0:
        cost = L.scale(x=cost, scale=a["coeff"])
    return L.mean(x=cost)


def _emit_ce_over_beam(t, node):
    L = _L()
    helper = fluid.layer_helper.LayerHelper("cross_entropy_over_beam")
    scores = [t._var(p.name) for p in node.parents[0::2]]
    golds = [
        L.cast(t._var(p.name), "int32") for p in node.parents[1::2]
    ]
    out = helper.create_tmp_variable(dtype="float32")
    helper.append_op(
        type="cross_entropy_over_beam",
        inputs={"Scores": scores, "Gold": golds},
        outputs={"Out": [out]},
    )
    return L.mean(x=out)


_BREADTH_EMITTERS.update({
    "crop": _emit_crop,
    "prelu": _emit_prelu,
    "priorbox": _emit_priorbox,
    "detection_output": _emit_detection_output,
    "multibox_loss": _emit_multibox_loss,
    "roi_pool": _emit_roi_pool,
    "scale_sub_region": _emit_scale_sub_region,
    "vol_reshape": _emit_vol_reshape,
    "img_conv3d": _emit_img_conv3d,
    "img_pool3d": _emit_img_pool3d,
    "linear_comb": _emit_linear_comb,
    "kmax_seq_score": _emit_kmax_seq_score,
    "sub_nested_seq": _emit_sub_nested_seq,
    "lambda_cost": _emit_lambda_cost,
    "ce_selfnorm": _emit_ce_selfnorm,
    "ce_over_beam": _emit_ce_over_beam,
})


# ---------------------------------------------------------------------
# evaluator emitters (reference trainer_config_helpers/evaluators.py;
# DSL wrappers in trainer_config_helpers/evaluators.py here)
# ---------------------------------------------------------------------


def _emit_precision_recall_eval(t, node):
    L = _L()
    pred, label = t._ins(node)
    n_cls = t._width(pred, node.parents[0])
    _, idx = L.topk(pred, k=1)
    # batch metrics = [macro-P, macro-R, macro-F1, micro-P, micro-R,
    # micro-F1]; per-class counts ride the states tensor [C, (tp,fp,tn,fn)]
    batch_m, _, states = fluid.layers.precision_recall(
        input=L.cast(idx, "int64"), label=L.cast(label, "int64"),
        class_number=n_cls,
    )
    pos = node.attrs.get("positive_label")
    if pos is None:
        return L.slice(batch_m, axes=[0], starts=[2], ends=[3])
    row = L.slice(states, axes=[0], starts=[int(pos)],
                  ends=[int(pos) + 1])
    tp = L.slice(row, axes=[1], starts=[0], ends=[1])
    fp = L.slice(row, axes=[1], starts=[1], ends=[2])
    fn = L.slice(row, axes=[1], starts=[3], ends=[4])
    denom = L.sums(input=[L.scale(x=tp, scale=2.0), fp, fn])
    eps = L.fill_constant(shape=[1], dtype="float32", value=1e-12)
    return L.elementwise_div(
        x=L.scale(x=tp, scale=2.0),
        y=L.elementwise_max(x=L.reshape(x=denom, shape=[1]), y=eps),
    )


def _emit_pnpair_eval(t, node):
    helper = fluid.layer_helper.LayerHelper("pnpair_eval")
    vars_ = [t._var(p.name) for p in node.parents]
    inputs = {"Score": [vars_[0]], "Label": [vars_[1]],
              "QueryID": [vars_[2]]}
    if len(vars_) > 3:
        inputs["Weight"] = [vars_[3]]
    out = helper.create_tmp_variable(dtype="float32")
    helper.append_op(
        type="pnpair_eval", inputs=inputs, outputs={"Out": [out]},
    )
    return out


def _emit_ctc_error_eval(t, node):
    L = _L()
    pred, label = t._ins(node)
    n_cls = t._width(pred, node.parents[0])
    decoded = L.ctc_greedy_decoder(pred, blank=n_cls - 1)
    dist, _ = L.edit_distance(decoded, label, normalized=True)
    return L.mean(x=dist)


def _emit_chunk_eval(t, node):
    L = _L()
    pred, label = t._ins(node)
    a = node.attrs
    # prediction may be per-class scores: reduce to tag ids
    w = t._width(pred, node.parents[0])
    if w and w > 1:
        _, idx = L.topk(pred, k=1)
        pred = L.cast(idx, "int64")
    _, _, f1, _, _, _ = fluid.layers.chunk_eval(
        input=pred, label=label, chunk_scheme=a["chunk_scheme"],
        num_chunk_types=a["num_chunk_types"],
        excluded_chunk_types=a.get("excluded_chunk_types"),
    )
    return f1


def _emit_detection_map_eval(t, node):
    L = _L()
    det = t._var(node.parents[0].name)
    label = t._var(node.parents[1].name)
    a = node.attrs
    n_cls = a.get("num_classes")
    if not n_cls:
        n_cls = node.parents[0].attrs.get("num_classes")
    gt_label = L.lod_reset(
        L.cast(L.slice(label, axes=[1], starts=[0], ends=[1]), "int64"),
        y=label,
    )
    gt_box = L.lod_reset(
        L.slice(label, axes=[1], starts=[1], ends=[5]), y=label
    )
    inputs = {"Detection": [det], "GTBox": [gt_box],
              "GTLabel": [gt_label]}
    width = t._node_width(node.parents[1])
    if width and width >= 6:  # [class, x1, y1, x2, y2, difficult]
        inputs["GTDifficult"] = [
            L.slice(label, axes=[1], starts=[5], ends=[6])
        ]
    helper = fluid.layer_helper.LayerHelper("detection_map")
    out = helper.create_tmp_variable(dtype="float32")
    helper.append_op(
        type="detection_map",
        inputs=inputs,
        outputs={"MAP": [out]},
        attrs={
            "overlap_threshold": a.get("overlap_threshold", 0.5),
            "num_classes": int(n_cls),
            "background_id": int(a.get("background_id", 0)),
        },
    )
    return out


def _emit_maxid_printer(t, node):
    _, idx = _L().topk(t._in(node), k=1)
    return idx


_BREADTH_EMITTERS.update({
    "precision_recall_evaluator": _emit_precision_recall_eval,
    "pnpair_evaluator": _emit_pnpair_eval,
    "ctc_error_evaluator": _emit_ctc_error_eval,
    "chunk_evaluator": _emit_chunk_eval,
    "detection_map_evaluator": _emit_detection_map_eval,
    "maxid_printer": _emit_maxid_printer,
})
