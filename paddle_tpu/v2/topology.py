"""Topology: replay a v2 layer DAG into a fluid Program (reference
python/paddle/v2/topology.py builds a ModelConfig protobuf; here the
single core is the fluid Program and its XLA executor)."""

from __future__ import annotations

from typing import Dict, List

from .. import fluid
from . import data_type as dt
from .layer import Layer, parse_network

__all__ = ["Topology"]


class Topology(object):
    def __init__(self, layers, extra_layers=None):
        if not isinstance(layers, (list, tuple)):
            layers = [layers]
        extra = list(extra_layers or [])
        self.output_layers = list(layers)
        self.extra_layers = extra
        self.order = parse_network(*(list(layers) + extra))

        self.main_program = fluid.Program()
        self.startup_program = fluid.Program()
        self.var_of: Dict[str, object] = {}  # layer name -> fluid Variable
        self._data_layers: List[Layer] = []
        with fluid.program_guard(self.main_program, self.startup_program):
            for node in self.order:
                self.var_of[node.name] = self._emit(node)

    # ------------------------------------------------------------------
    def _in(self, node, i=0):
        return self.var_of[node.parents[i].name]

    def _ins(self, node):
        return [self.var_of[p.name] for p in node.parents]

    def _emit(self, node: Layer):
        L = fluid.layers
        a = node.attrs
        if node.kind == "data":
            t = a["type"]
            self._data_layers.append(node)
            lod = 1 if t.seq_type != 0 else 0
            if t.type == dt.DataType.Index:
                shape, dtype = [1], "int64"
            else:
                shape, dtype = [t.dim], "float32"
            return L.data(name=node.name, shape=shape, dtype=dtype,
                          lod_level=lod)
        if node.kind == "fc":
            # deterministic parameter names derived from the layer name
            # (reference convention "___fc_0__.w0") so Parameters re-bind
            # across replays of the same DAG
            attrs = [
                fluid.ParamAttr(name="%s.w%d" % (node.name, i))
                for i in range(len(node.parents))
            ]
            return L.fc(input=self._ins(node), size=a["size"], act=a["act"],
                        param_attr=attrs,
                        bias_attr=fluid.ParamAttr(name=node.name + ".wbias"))
        if node.kind == "embedding":
            t = node.parents[0].attrs["type"]
            return L.embedding(input=self._in(node),
                               size=[t.dim, a["size"]],
                               param_attr=fluid.ParamAttr(
                                   name=node.name + ".w0"))
        if node.kind == "concat":
            return L.concat(input=self._ins(node), axis=1)
        if node.kind == "img_conv":
            return L.conv2d(
                input=self._in(node), num_filters=a["num_filters"],
                filter_size=a["filter_size"], stride=a["stride"],
                padding=a["padding"], act=a["act"],
                groups=a.get("groups", 1) or 1,
                param_attr=fluid.ParamAttr(name=node.name + ".w0"),
                bias_attr=(
                    False if not a.get("bias", True)
                    else fluid.ParamAttr(name=node.name + ".wbias")
                ),
            )
        if node.kind == "im_reshape":
            c, h, w = a["shape"]
            return L.reshape(x=self._in(node), shape=[-1, c, h, w])
        if node.kind == "lrn":
            return L.lrn(
                input=self._in(node), n=a["size"], k=1.0,
                alpha=a.get("scale", 1e-4), beta=a.get("power", 0.75),
            )
        if node.kind == "addto":
            out = L.sums(input=self._ins(node))
            act = a.get("act")
            if act:
                out = getattr(L, act)(out)
            return out
        if node.kind == "img_pool":
            return L.pool2d(
                input=self._in(node), pool_size=a["pool_size"],
                pool_stride=a["stride"], pool_padding=a["padding"],
                pool_type=a["pool_type"],
            )
        if node.kind == "batch_norm":
            return L.batch_norm(input=self._in(node), act=a["act"])
        if node.kind == "lstmemory":
            # v2 semantics: `size` is the hidden width H and the input must
            # be 4H wide (fluid dynamic_lstm's `size` argument is 4H)
            size = a["size"]
            if size is None:
                size = int(self._in(node).shape[1]) // 4
            hidden, _ = L.dynamic_lstm(
                input=self._in(node), size=size * 4,
                is_reverse=a.get("reverse", False),
                param_attr=fluid.ParamAttr(name=node.name + ".w0"),
                bias_attr=fluid.ParamAttr(name=node.name + ".wbias"),
            )
            return hidden
        if node.kind == "gru":
            return L.dynamic_gru(
                input=self._in(node), size=a["size"],
                is_reverse=a.get("reverse", False),
                param_attr=fluid.ParamAttr(name=node.name + ".w0"),
            )
        if node.kind == "seq_pool":
            return L.sequence_pool(input=self._in(node),
                                   pool_type=a["pool_type"])
        if node.kind == "last_seq":
            return L.sequence_last_step(input=self._in(node))
        if node.kind == "first_seq":
            return L.sequence_first_step(input=self._in(node))
        if node.kind == "max_id":
            _, idx = L.topk(self._in(node), k=1)
            return idx
        if node.kind == "classification_cost":
            pred, label = self._ins(node)
            # reference classification_cost = softmax output + CE cost; the
            # DSL's `input` already went through act=Softmax
            cost = L.cross_entropy(input=pred, label=label)
            return L.mean(x=cost)
        if node.kind == "cross_entropy_cost":
            pred, label = self._ins(node)
            return L.mean(x=L.cross_entropy(input=pred, label=label))
        if node.kind == "mse_cost":
            pred, label = self._ins(node)
            return L.mean(x=L.square_error_cost(input=pred, label=label))
        if node.kind == "dropout":
            return L.dropout(x=self._in(node), dropout_prob=a["rate"])
        if node.kind == "classification_error_evaluator":
            pred, label = self._ins(node)
            acc = L.accuracy(input=pred, label=label,
                             k=a.get("top_k", 1) or 1)
            one = L.fill_constant(shape=[1], dtype="float32", value=1.0)
            return L.elementwise_sub(x=one, y=acc)  # error = 1 - accuracy
        if node.kind == "auc_evaluator":
            pred, label = self._ins(node)
            return L.auc(input=pred, label=label)
        if node.kind == "sum_evaluator":
            return L.reduce_sum(self._in(node))
        if node.kind == "column_sum_evaluator":
            return L.reduce_sum(self._in(node), dim=0)
        raise NotImplementedError("v2 layer kind %r" % node.kind)

    # ------------------------------------------------------------------
    def data_layers(self) -> Dict[str, Layer]:
        return {n.name: n for n in self._data_layers}

    def data_type(self):
        return [(n.name, n.attrs["type"]) for n in self._data_layers]

    def get_layer_proto(self, name):
        return None
