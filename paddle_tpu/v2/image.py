"""paddle.v2.image: image decode / resize / crop / flip / transform
utilities (reference python/paddle/v2/image.py, which wraps cv2).

PIL + numpy implementation (cv2 is not in this image): same API and
HWC-uint8 in / CHW-float out conventions. Color images are RGB order
(the reference's cv2 path is BGR — documented divergence; the mean
argument of simple_transform is applied per channel in the order given,
so models trained here are self-consistent).
"""

from __future__ import annotations

import io
import tarfile

import numpy as np

__all__ = [
    "batch_images_from_tar",
    "load_image_bytes",
    "load_image",
    "resize_short",
    "to_chw",
    "center_crop",
    "random_crop",
    "left_right_flip",
    "simple_transform",
    "load_and_transform",
]


def load_image_bytes(bytes, is_color=True):  # noqa: A002 - reference name
    """Decode an encoded image buffer to an HWC uint8 array."""
    from PIL import Image

    img = Image.open(io.BytesIO(bytes))
    img = img.convert("RGB" if is_color else "L")
    arr = np.asarray(img, np.uint8)
    return arr


def load_image(file, is_color=True):  # noqa: A002 - reference name
    with open(file, "rb") as f:
        return load_image_bytes(f.read(), is_color=is_color)


def resize_short(im, size):
    """Resize so the SHORTER edge equals `size`, keeping aspect ratio
    (reference resize_short)."""
    from PIL import Image

    h, w = im.shape[:2]
    if h > w:
        new_w, new_h = size, int(round(h * size / w))
    else:
        new_w, new_h = int(round(w * size / h)), size
    img = Image.fromarray(im)
    return np.asarray(img.resize((new_w, new_h), Image.BILINEAR), im.dtype)


def to_chw(im, order=(2, 0, 1)):
    """HWC -> CHW (reference to_chw); grayscale gains a channel axis."""
    if im.ndim == 2:
        im = im[:, :, None]
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h0 = max((h - size) // 2, 0)
    w0 = max((w - size) // 2, 0)
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h0 = np.random.randint(0, max(h - size, 0) + 1)
    w0 = np.random.randint(0, max(w - size, 0) + 1)
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """The standard train/test pipeline (reference simple_transform):
    resize_short -> (random crop + random flip | center crop) -> CHW
    float32 -> optional per-channel (or per-pixel) mean subtraction."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color=is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color=is_color)
    else:
        im = center_crop(im, crop_size, is_color=is_color)
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        if mean.ndim == 1:
            mean = mean[:, None, None]
        im = im - mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(
        load_image(filename, is_color=is_color), resize_size, crop_size,
        is_train, is_color=is_color, mean=mean,
    )


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """Pre-batch a tar of images into pickled {data, label} blocks
    (reference batch_images_from_tar): returns the meta file path."""
    import os
    import pickle

    out_path = "%s_%s_batch" % (data_file, dataset_name)
    if not os.path.isdir(out_path):
        os.makedirs(out_path)
    tf = tarfile.open(data_file)
    data, labels, file_id, names = [], [], 0, []
    for mem in tf.getmembers():
        if mem.name not in img2label:
            continue
        data.append(tf.extractfile(mem).read())
        labels.append(img2label[mem.name])
        if len(data) == num_per_batch:
            output = {"label": labels, "data": data}
            part = os.path.join(out_path, "batch_%d" % file_id)
            with open(part, "wb") as f:
                pickle.dump(output, f, protocol=2)
            names.append(part)
            file_id += 1
            data, labels = [], []
    if data:
        part = os.path.join(out_path, "batch_%d" % file_id)
        with open(part, "wb") as f:
            pickle.dump({"label": labels, "data": data}, f, protocol=2)
        names.append(part)
    meta = os.path.join(out_path, "batch_meta")
    with open(meta, "w") as f:
        f.write("\n".join(names))
    return meta
