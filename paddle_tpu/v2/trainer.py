"""paddle.v2.trainer.SGD: the v2 train/test loop over the fluid executor
(reference python/paddle/v2/trainer.py:37 SGD, :137 train — there it
drives the SWIG GradientMachine + ParameterUpdater; here one fused XLA
step per batch via the fluid Executor)."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .. import fluid
from . import event as v2_event
from . import data_type as dt
from .parameters import Parameters
from .topology import Topology

__all__ = ["SGD"]


def _convert_feed(batch, data_nodes, feeding):
    """Batch of instance tuples -> fluid feed dict, per data-layer type
    (the py_paddle DataProviderConverter's job in the reference)."""
    names = [n.name for n in data_nodes]
    if feeding is None:
        feeding = {name: i for i, name in enumerate(names)}
    feed = {}
    for node in data_nodes:
        idx = feeding[node.name]
        col = [inst[idx] for inst in batch]
        t = node.attrs["type"]
        if t.seq_type == 0:  # plain
            if t.type == dt.DataType.Index:
                feed[node.name] = np.asarray(col, np.int64).reshape(-1, 1)
            elif t.type in (dt.DataType.SparseNonValue, dt.DataType.SparseValue):
                # sparse instances materialise to dense rows (the TPU path
                # is dense; reference converts via SparseBinaryScanner)
                dense = np.zeros((len(col), t.dim), np.float32)
                for r, inst in enumerate(col):
                    if t.type == dt.DataType.SparseNonValue:
                        dense[r, list(inst)] = 1.0
                    else:
                        for i, v in inst:
                            dense[r, int(i)] = float(v)
                feed[node.name] = dense
            else:
                feed[node.name] = np.asarray(col, np.float32).reshape(
                    len(col), -1
                )
        else:  # single-level sequence -> packed + offsets
            lens = [len(x) for x in col]
            lod = np.cumsum([0] + lens).astype(np.int32)
            if t.type == dt.DataType.Index:
                flat = np.concatenate(
                    [np.asarray(x, np.int64).reshape(-1) for x in col]
                ).reshape(-1, 1)
            else:
                flat = np.concatenate(
                    [np.asarray(x, np.float32).reshape(len(x), -1) for x in col]
                )
            feed[node.name] = (flat, [lod])
    return feed


def _metric_value(m):
    """Scalars become floats; vector metrics (column_sum) stay arrays."""
    arr = np.ravel(np.asarray(m))
    return float(arr[0]) if arr.size == 1 else np.asarray(m)


class SGD(object):
    def __init__(self, cost, parameters, update_equation, extra_layers=None,
                 is_local=True, pserver_spec=None, use_etcd=True):
        if not isinstance(parameters, Parameters):
            raise TypeError("parameters should be paddle.v2.parameters.create(...)")
        self.__parameters__ = parameters
        # reuse the parameters' topology when it covers this cost, so the
        # trainer updates the same scope arrays in place
        topo = parameters.topology
        if not any(l is cost for l in topo.output_layers) or any(
            l.name not in topo.var_of for l in (extra_layers or [])
        ):
            topo = Topology([cost], extra_layers=extra_layers)
        # a topology can host at most one optimizer: a second SGD over the
        # same Parameters gets a fresh replay of the DAG instead of
        # appending a second backward pass to the shared program
        if getattr(topo, "_minimized", False):
            topo = Topology([cost], extra_layers=extra_layers)
        self._topology = topo
        self._cost_var = topo.var_of[cost.name]
        # metric layers from extra_layers: fetched every batch and handed
        # to event handlers via the evaluator payload (reference book
        # handlers read event.evaluator after each iteration)
        metric_layers = [
            l for l in getattr(topo, "extra_layers", [])
            if l.name in topo.var_of
        ]
        self._metric_fetches = [
            (l.name, topo.var_of[l.name]) for l in metric_layers
        ]
        # accumulation semantics per metric: sum-type evaluators report a
        # running TOTAL over the dataset (reference sum_evaluator /
        # column_sum_evaluator), ratio metrics an example-weighted mean
        self._metric_is_sum = [
            getattr(l, "kind", "") in ("sum_evaluator", "column_sum_evaluator")
            for l in metric_layers
        ]
        # snapshot the forward-only program BEFORE minimize appends the
        # backward+update ops: test() must never touch parameters
        self._test_program = topo.main_program.clone(for_test=True)
        self._optimizer = update_equation._fluid()
        self._model_average = None
        with fluid.program_guard(topo.main_program, topo.startup_program):
            self._optimizer.minimize(self._cost_var)
            # legacy update_hooks: params with a pruning hook get their
            # static mask built + re-applied after every update — BEFORE
            # ModelAverage so the EMA accumulates masked (sparse) values
            self._pruning = fluid.optimizer.StaticPruning().build(
                topo.main_program, topo.startup_program
            )
            ma_spec = getattr(update_equation, "model_average", None)
            if ma_spec is not None:
                # reference averaged parameters (trainer.py:130 catchUp/
                # apply/restore): EMA slots inside the train step; test()
                # and save_parameter_to_tar run on the averages
                self._model_average = fluid.optimizer.ModelAverage.from_spec(
                    ma_spec
                ).build(topo.main_program)
        topo._minimized = True
        # initialize ONLY vars not already in the parameters' scope (the
        # optimizer state); re-running the full startup program would
        # clobber values loaded via Parameters.init_from_tar
        self._exe = fluid.Executor(fluid.CPUPlace())
        startup = topo.startup_program.clone()
        blk = startup.global_block()
        blk.ops = [
            op
            for op in blk.ops
            if any(n not in parameters.scope for n in op.output_arg_names)
        ]
        with fluid.executor.scope_guard(parameters.scope):
            self._exe.run(startup)
        # params that were initialized BEFORE this trainer existed (the
        # Parameters.create startup) bypassed the in-startup mask apply:
        # sparsify them now so pruning holds from step 0
        for pname, mname in self._pruning.masks.items():
            sc = parameters.scope
            if pname in sc and mname in sc:
                sc.set(
                    pname,
                    np.asarray(sc.get(pname)) * np.asarray(sc.get(mname)),
                )

    # ------------------------------------------------------------------
    def train(self, reader, num_passes=1, event_handler=None, feeding=None):
        if event_handler is None:
            event_handler = lambda e: None
        data_nodes = self._topology._data_layers
        scope = self.__parameters__.scope
        from ..fluid.data_feeder import AsyncDeviceFeeder

        for pass_id in range(num_passes):
            event_handler(v2_event.BeginPass(pass_id))

            def _feeds():
                # decode + upload in a background thread: batch k+1
                # overlaps the device step on batch k (reference
                # DataProvider.h:249 DoubleBuffer)
                for batch in reader():
                    yield _convert_feed(batch, data_nodes, feeding)

            from ..parallel.mesh import get_default_mesh, spans_processes

            _mesh = self._exe.mesh or get_default_mesh()
            feeder = AsyncDeviceFeeder(
                _feeds(), capacity=2,
                upload=not (_mesh is not None and spans_processes(_mesh)),
            )
            try:
                self._train_pass(
                    feeder, pass_id, event_handler, scope)
            finally:
                feeder.close()
            event_handler(v2_event.EndPass(pass_id))

    def _train_pass(self, feeds, pass_id, event_handler, scope):
        for batch_id, feed in enumerate(feeds):
            event_handler(v2_event.BeginIteration(pass_id, batch_id))
            with fluid.executor.scope_guard(scope):
                fetched = self._exe.run(
                    self._topology.main_program,
                    feed=feed,
                    fetch_list=[self._cost_var]
                    + [v for _, v in self._metric_fetches],
                )
            cost, metrics = fetched[0], fetched[1:]
            event_handler(
                v2_event.EndIteration(
                    pass_id, batch_id, float(np.ravel(cost)[0]),
                    evaluator=self._metric_payload(metrics),
                )
            )

    # ------------------------------------------------------------------
    def _avg_apply_ctx(self):
        """Averaged-parameter context for eval/export: the EMA weights
        when averaging is configured AND at least one step has trained;
        the live weights otherwise (e.g. evaluating a freshly-loaded
        model before train())."""
        import contextlib

        ma = self._model_average
        if ma is None:
            return contextlib.nullcontext()
        scope = self.__parameters__.scope
        steps = scope.get(ma._steps_name) if ma._steps_name in scope else None
        if steps is None or float(np.ravel(np.asarray(steps))[0]) < 1.0:
            return contextlib.nullcontext()
        return ma.apply(scope=scope)

    def test(self, reader, feeding=None):
        data_nodes = self._topology._data_layers
        scope = self.__parameters__.scope
        # averaged parameters evaluate the EMA weights (reference: the
        # tester's apply/restore around averaged params)
        avg_ctx = self._avg_apply_ctx()
        test_prog = self._test_program  # forward-only snapshot, stable id
        # the test program is a pre-minimize clone: metric vars live in it
        # under the same names
        metric_vars = [
            test_prog.global_block().var(v.name)
            for _, v in self._metric_fetches
        ]
        with avg_ctx:
            costs, n = [], 0
            metric_sums = [0.0] * len(metric_vars)
            for batch in reader():
                feed = _convert_feed(batch, data_nodes, feeding)
                with fluid.executor.scope_guard(scope):
                    fetched = self._exe.run(
                        test_prog, feed=feed,
                        fetch_list=[test_prog.global_block().var(
                            self._cost_var.name)] + metric_vars,
                    )
                costs.append(float(np.ravel(fetched[0])[0]) * len(batch))
                for i, m in enumerate(fetched[1:]):
                    # sum evaluators accumulate a dataset TOTAL; ratio metrics
                    # (classification_error, auc) average example-weighted
                    v = np.asarray(_metric_value(m))
                    if self._metric_is_sum[i]:
                        metric_sums[i] = metric_sums[i] + v
                    else:
                        metric_sums[i] = metric_sums[i] + v * len(batch)
                n += len(batch)
            avg = sum(costs) / max(n, 1)
            evaluator = {}
            for i, (name, _) in enumerate(self._metric_fetches):
                val = np.asarray(metric_sums[i])
                if not self._metric_is_sum[i]:
                    val = val / max(n, 1)
                evaluator[name] = float(val) if val.ndim == 0 else val
            return v2_event.TestResult(evaluator=evaluator, cost=avg)

    def _metric_payload(self, metrics):
        return {
            name: _metric_value(m)
            for (name, _), m in zip(self._metric_fetches, metrics)
        }

    def save_parameter_to_tar(self, f):
        # export averaged weights when averaging is active (reference
        # save with averaged params applied); live weights otherwise
        with self._avg_apply_ctx():
            self.__parameters__.to_tar(f)


def infer(output_layer, parameters, input, feeding=None):
    """paddle.infer (reference python/paddle/v2/inference.py): forward
    the prediction sub-graph with the given parameters. Delegates to
    inference.Inference — one binding path."""
    from .inference import Inference

    return Inference(output_layer, parameters).infer(input,
                                                     feeding=feeding)
