"""paddle.v2.inference (reference python/paddle/v2/inference.py):
the Inference class binds a network output + trained Parameters once and
serves repeated infer() calls; the module-level infer() is the one-shot
form (re-exported as paddle.v2.infer)."""

from __future__ import annotations

from . import minibatch
from .topology import Topology
from .trainer import _convert_feed
from .. import fluid

__all__ = ["infer", "Inference"]


def infer(output_layer, parameters, input, feeding=None, field="value"):
    """paddle.infer (reference inference.py:125): one-shot form over the
    Inference class — single binding path for parameter loading."""
    return Inference(output_layer, parameters).infer(input,
                                                     feeding=feeding,
                                                     field=field)


class Inference(object):
    """Bind (output_layer, parameters) once; iterate batches with
    iter_infer_field / run one batch with infer (reference
    inference.py:24)."""

    def __init__(self, output_layer, parameters):
        outputs = output_layer if isinstance(output_layer, (list, tuple)) \
            else [output_layer]
        self._outputs = list(outputs)
        self._topo = Topology(self._outputs)
        self._exe = fluid.Executor(fluid.CPUPlace())
        self._scope = fluid.executor.Scope()
        with fluid.executor.scope_guard(self._scope):
            self._exe.run(self._topo.startup_program)
            for v in self._topo.main_program.list_vars():
                if v.persistable and parameters.has_key(v.name):
                    self._scope.set(v.name, parameters[v.name])

    def infer(self, input, feeding=None, field="value"):
        if field not in ("value",):
            raise NotImplementedError(
                "field=%r: this core returns layer VALUES; ids come from "
                "max_id/beam layers in the graph itself" % (field,)
            )
        feed = _convert_feed(input, self._topo._data_layers, feeding)
        with fluid.executor.scope_guard(self._scope):
            fetches = self._exe.run(
                self._topo.main_program, feed=feed,
                fetch_list=[self._topo.var_of[o.name]
                            for o in self._outputs],
            )
        return fetches[0] if len(fetches) == 1 else fetches

    def iter_infer(self, input, feeding=None, batch_size=128):
        for batch in minibatch.batch(lambda: iter(input), batch_size)():
            yield self.infer(batch, feeding=feeding)

    def iter_infer_field(self, input, field="value", feeding=None,
                         batch_size=128):
        """Reference inference.py iter_infer_field: per-batch results of
        one field."""
        for batch in minibatch.batch(lambda: iter(input), batch_size)():
            yield self.infer(batch, feeding=feeding, field=field)
