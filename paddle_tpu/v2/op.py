"""Math operations over v2 layers (reference python/paddle/v2/op.py).

Registers unary math functions (paddle.v2.op.exp(layer) etc., each a
mixed layer with the activation applied) and patches +, -, *, neg onto
the Layer node so `a + b`, `2.0 * a` build graphs — same surface as the
reference, lowered through the one fluid core.
"""

from __future__ import annotations

import numbers

from .. import trainer_config_helpers as conf
from . import activation as act
from .config_base import Layer

__all__ = []


def __register_unary_math_op__(op_name, activation):
    def op(input, name=None):
        return conf.mixed_layer(
            input=[conf.identity_projection(input=input)],
            name=name,
            act=activation,
        )

    op.__name__ = op_name
    op.__doc__ = type(activation).__doc__
    globals()[op_name] = op
    __all__.append(op_name)


__register_unary_math_op__("exp", act.Exp())
__register_unary_math_op__("log", act.Log())
__register_unary_math_op__("abs", act.Abs())
__register_unary_math_op__("sigmoid", act.Sigmoid())
__register_unary_math_op__("tanh", act.Tanh())
__register_unary_math_op__("square", act.Square())
__register_unary_math_op__("relu", act.Relu())
__register_unary_math_op__("sqrt", act.SquareRootN())
__register_unary_math_op__("reciprocal", act.Reciprocal())
__register_unary_math_op__("softmax", act.Softmax())


def _size_of(node):
    return node.attrs.get("size") if hasattr(node, "attrs") else None


def __add__(layeroutput, other):
    if isinstance(other, numbers.Number):
        return conf.slope_intercept_layer(
            input=layeroutput, intercept=float(other)
        )
    if not isinstance(other, Layer):
        raise TypeError(
            "Layer can only be added with another Layer or a number"
        )
    return conf.mixed_layer(input=[
        conf.identity_projection(input=layeroutput),
        conf.identity_projection(input=other),
    ])


Layer.__radd__ = __add__
Layer.__add__ = __add__


def __neg__(layeroutput):
    return conf.slope_intercept_layer(input=layeroutput, slope=-1.0)


Layer.__neg__ = __neg__


def __sub__(layeroutput, other):
    if isinstance(other, numbers.Number):
        return conf.slope_intercept_layer(
            input=layeroutput, intercept=-float(other)
        )
    if not isinstance(other, Layer):
        raise TypeError(
            "Layer can only be subtracted with another Layer or a number"
        )
    return __add__(layeroutput, __neg__(other))


Layer.__sub__ = __sub__


def __rsub__(layeroutput, other):
    return __add__(__neg__(layeroutput), other)


Layer.__rsub__ = __rsub__


def __mul__(layeroutput, other):
    if isinstance(other, numbers.Number):
        return conf.slope_intercept_layer(
            input=layeroutput, slope=float(other)
        )
    if not isinstance(other, Layer):
        raise TypeError(
            "Layer can only be multiplied with another Layer or a number"
        )
    if _size_of(layeroutput) == 1:
        return conf.scaling_layer(input=other, weight=layeroutput)
    if _size_of(other) == 1:
        return conf.scaling_layer(input=layeroutput, weight=other)
    raise TypeError(
        "At least one of the operands of '*' must be a number or a "
        "Layer with size=1"
    )


Layer.__mul__ = __mul__
Layer.__rmul__ = __mul__
