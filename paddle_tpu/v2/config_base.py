"""v2 config base (reference python/paddle/v2/config_base.py).

The reference aliases `Layer` to trainer_config_helpers' LayerOutput and
wraps every DSL function so created layers register in `__layer_map__`
for topology traversal. Here the v2 DSL node (v2/layer.py Layer) IS the
LayerOutput — one node class under both surfaces — and nodes
self-register at construction (Layer._registry), so the conversion
wrapper only needs to preserve name/doc metadata.
"""

from __future__ import annotations

from .layer import Layer

__layer_map__ = {}


def __convert_to_v2__(f, name, module):
    def wrapped(*args, **kwargs):
        out = f(*args, **kwargs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for l in outs:
            if isinstance(l, Layer):
                __layer_map__[l.name] = l
        return out

    wrapped.__doc__ = f.__doc__
    wrapped.__name__ = name
    wrapped.__module__ = module
    return wrapped


__all__ = ["Layer", "__layer_map__", "__convert_to_v2__"]
