"""UCI housing reader creators (reference dataset/uci_housing.py API:
yield (13 features, [price])). Synthetic linear-plus-noise data."""

import numpy as np

from . import common

__all__ = ["train", "test", "feature_range"]

_W = None
UCI_DIM = 13


def _w():
    global _W
    if _W is None:
        _W = common.rng_for("uci_housing", "w").randn(UCI_DIM)
    return _W


def _reader(split, n):
    def reader():
        rng = common.rng_for("uci_housing", split)
        for _ in range(n):
            x = rng.randn(UCI_DIM).astype("float32")
            y = float(x @ _w() + 0.1 * rng.randn())
            yield x, np.array([y], "float32")

    return reader


def train():
    return _reader("train", 404)


def test():
    return _reader("test", 102)


def feature_range(maximums, minimums):
    pass
