"""UCI housing reader creators (reference dataset/uci_housing.py:
download housing.data, normalise features, 80/20 split, yield
(13 features, [price])).

Wire format: `housing.data` — whitespace-separated rows of 14 floats
(13 features + MEDV target), exactly the UCI archive layout the
reference parses with np.fromfile(sep=' ') (uci_housing.py:62
load_data). A real file placed in the cache is decoded; fetch()
synthesises a REAL-FORMAT file from the deterministic corpus, so the
parse/normalise path runs either way. Normalisation matches the
reference: x_i = (x_i - avg_i) / (max_i - min_i).
"""

import os

import numpy as np

from . import common

__all__ = ["train", "test", "feature_range", "fetch", "convert"]

UCI_DIM = 13
N_ROWS = 506  # the real dataset's row count
TRAIN_RATIO = 0.8

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD",
    "TAX", "PTRATIO", "B", "LSTAT",
]

_CACHE = {}


def _path():
    return os.path.join(common.DATA_HOME, "uci_housing", "housing.data")


def _synthetic_rows():
    """Deterministic corpus: linear-plus-noise target over plausible
    positive feature scales."""
    rng = common.rng_for("uci_housing", "data")
    w = common.rng_for("uci_housing", "w").randn(UCI_DIM)
    x = np.abs(rng.randn(N_ROWS, UCI_DIM)) * (
        1.0 + 10.0 * rng.rand(UCI_DIM)
    )
    y = x @ (w * 0.1) + 0.5 * rng.randn(N_ROWS) + 22.0
    return np.concatenate([x, y[:, None]], axis=1)


def fetch():
    path = _path()
    if os.path.exists(path):
        return path
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        for row in _synthetic_rows():
            f.write(" ".join("%.4f" % v for v in row) + "\n")
    os.replace(tmp, path)
    return path


def _load():
    """Decode + normalise (reference load_data semantics). Only DECODED
    files are cached — a fallback result is recomputed so a
    housing.data that appears later in the process gets decoded."""
    path = _path()
    decode = os.path.exists(path)
    key = (path, decode)
    if key in _CACHE:
        return _CACHE[key]
    if decode:
        data = np.fromfile(path, sep=" ")
    else:
        data = _synthetic_rows().ravel()
    data = data.reshape(-1, UCI_DIM + 1)
    maximums = data.max(axis=0)
    minimums = data.min(axis=0)
    avgs = data.mean(axis=0)
    for i in range(UCI_DIM):
        data[:, i] = (data[:, i] - avgs[i]) / max(
            maximums[i] - minimums[i], 1e-12
        )
    _CACHE[key] = data.astype("float32")
    return _CACHE[key]


def _reader(lo, hi):
    def reader():
        data = _load()
        n = data.shape[0]
        for row in data[int(lo * n):int(hi * n)]:
            yield row[:-1], row[-1:]

    return reader


def train():
    return _reader(0.0, TRAIN_RATIO)


def test():
    return _reader(TRAIN_RATIO, 1.0)


def feature_range(maximums, minimums):
    """Reference saves a matplotlib bar chart of feature scales; headless
    here — kept as an API no-op."""


def convert(path):
    common.convert(path, train(), 128, "uci_housing_train")
    common.convert(path, test(), 128, "uci_housing_test")
