"""Shared dataset plumbing (reference dataset/common.py: download cache,
reader converters). Here: deterministic RNG streams for the synthetic
corpora + the cache-dir convention kept for drop-in real data."""

import os

import numpy as np

# PADDLE_TPU_DATA_HOME points the cache at GENUINE downloads (the
# reference's ~/.cache/paddle/dataset layout, common.py:37): on a
# connected machine, place the real archives there and every reader
# decodes them instead of the synthetic corpus (r4 verdict #7;
# tests/test_real_archives.py verifies against the reference md5s)
DATA_HOME = (
    os.environ.get("PADDLE_TPU_DATA_HOME")
    or os.path.expanduser("~/.cache/paddle_tpu/dataset")
)

__all__ = ["DATA_HOME", "rng_for", "md5file", "download", "convert",
           "read_converted", "fetch_all"]


def rng_for(name: str, split: str) -> np.random.RandomState:
    # crc32, not hash(): Python's per-process hash salt would give a
    # different synthetic corpus on every interpreter run
    import zlib

    seed = zlib.crc32(("%s/%s" % (name, split)).encode()) % (2**31)
    return np.random.RandomState(seed)


def to_pixels(img):
    """[-1,1] floats -> uint8 pixels (the real datasets' wire encoding);
    round-trips exactly with from_pixels."""
    return np.clip(np.round((img + 1.0) * 127.5), 0, 255).astype(np.uint8)


def from_pixels(pixels):
    """uint8 pixels -> [-1,1] float32 (reference readers' normalisation)."""
    return pixels.astype("float32") / 127.5 - 1.0


def md5file(fname):
    import hashlib

    m = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            m.update(chunk)
    return m.hexdigest()


def download(url, module_name, md5sum=None, save_name=None):
    raise RuntimeError(
        "no network egress in this environment; place files under %s "
        "manually" % DATA_HOME
    )


def convert(output_path, reader, line_count, name_prefix):
    """Serialise a reader's samples into record files through the NATIVE
    record writer (reference common.convert -> recordio; the Go master
    dispatches these chunks). STREAMING: samples never materialise in
    memory at once; each file holds up to `line_count` pickled samples,
    named `<prefix>-00000-of-NNNNN` like the reference (temp names are
    renamed once the final file count is known)."""
    import pickle

    from ... import native

    os.makedirs(output_path, exist_ok=True)
    tmp_paths = []
    writer, written = None, 0
    for sample in (reader() if callable(reader) else reader):
        if writer is None:
            tmp = os.path.join(
                output_path, ".%s-%05d.tmp" % (name_prefix, len(tmp_paths))
            )
            writer = native.RecordWriter(tmp)
            tmp_paths.append(tmp)
        writer.write(pickle.dumps(sample, protocol=2))
        written += 1
        if written == line_count:
            writer.close()
            writer, written = None, 0
    if writer is not None:
        writer.close()
    n_files = max(1, len(tmp_paths))
    paths = []
    for i, tmp in enumerate(tmp_paths):
        path = os.path.join(
            output_path, "%s-%05d-of-%05d" % (name_prefix, i, n_files)
        )
        os.replace(tmp, path)
        paths.append(path)
    return paths


def read_converted(paths):
    """Reader creator over files written by convert() (reference
    master-dispatched recordio consumption)."""
    import pickle

    from ... import native

    def reader():
        for rec in native.PrefetchReader(list(paths)):
            yield pickle.loads(rec)

    return reader


def ranked_vocab(word_freq, cutoff=0):
    """Frequency dictionary -> {word: id} ranked by (-freq, word), with
    '<unk>' assigned the LAST id (the reference's build_dict convention,
    shared by imdb/imikolov)."""
    kept = [x for x in word_freq.items() if x[1] > cutoff]
    ranked = sorted(kept, key=lambda x: (-x[1], x[0]))
    words = [w for w, _ in ranked]
    word_idx = dict(zip(words, range(len(words))))
    word_idx["<unk>"] = len(words)
    return word_idx


def fetch_all():
    """Populate every dataset module's cache (reference common.fetch_all:
    iterates the whole dataset package; modules without fetch() skip)."""
    import importlib
    import pkgutil

    pkg = importlib.import_module("paddle_tpu.v2.dataset")
    for info in pkgutil.iter_modules(pkg.__path__):
        mod = importlib.import_module(
            "paddle_tpu.v2.dataset." + info.name
        )
        if hasattr(mod, "fetch"):
            mod.fetch()
