"""Shared dataset plumbing (reference dataset/common.py: download cache,
reader converters). Here: deterministic RNG streams for the synthetic
corpora + the cache-dir convention kept for drop-in real data."""

import os

import numpy as np

DATA_HOME = os.path.expanduser("~/.cache/paddle_tpu/dataset")

__all__ = ["DATA_HOME", "rng_for", "md5file", "download"]


def rng_for(name: str, split: str) -> np.random.RandomState:
    # crc32, not hash(): Python's per-process hash salt would give a
    # different synthetic corpus on every interpreter run
    import zlib

    seed = zlib.crc32(("%s/%s" % (name, split)).encode()) % (2**31)
    return np.random.RandomState(seed)


def md5file(fname):
    import hashlib

    m = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            m.update(chunk)
    return m.hexdigest()


def download(url, module_name, md5sum=None, save_name=None):
    raise RuntimeError(
        "no network egress in this environment; place files under %s "
        "manually" % DATA_HOME
    )
