"""WMT16 (Multi30k-style) reader creators (reference dataset/wmt16.py:
`wmt16.tar.gz` holding members wmt16/{train,val,test} of `en\\tde`
parallel lines; dictionaries BUILT from the train corpus by frequency,
written to DATA_HOME/wmt16/<lang>_<size>.dict with the first three lines
<s>/<e>/<unk>, then loaded by line number — wmt16.py:59-137 semantics:
yields (src <s>..<e>, trg <s>.., trg_next ..<e>), unk id shared from the
source dict, src_lang selects the column).

fetch() synthesises a REAL-FORMAT tarball from the deterministic corpus
(German side = reversed English words with a 'de' suffix, so seq2seq
structure is learnable); real files decode identically.
"""

import io
import os
import tarfile
from collections import defaultdict

from . import common

__all__ = ["train", "test", "validation", "get_dict", "fetch"]

START_MARK, END_MARK, UNK_MARK = "<s>", "<e>", "<unk>"
# total dict entries INCLUDING the three marks (reference formula:
# min(dict_size, TOTAL_*_WORDS))
TOTAL_EN_WORDS = 63
TOTAL_DE_WORDS = 63
_VOCAB = 60
N_TRAIN, N_VAL, N_TEST = 256, 64, 64
_MEMBERS = {"train": "wmt16/train", "val": "wmt16/val",
            "test": "wmt16/test"}
_COUNTS = {"train": N_TRAIN, "val": N_VAL, "test": N_TEST}


def _path():
    return os.path.join(common.DATA_HOME, "wmt16", "wmt16.tar.gz")


def _synthetic_pairs(split, n):
    rng = common.rng_for("wmt16", split)
    for _ in range(n):
        l = int(rng.randint(2, 8))
        ids = rng.randint(0, _VOCAB, l)
        en = " ".join("w%d" % i for i in ids)
        de = " ".join("w%dde" % i for i in ids[::-1])
        yield "%s\t%s" % (en, de)


def fetch():
    path = _path()
    if os.path.exists(path):
        return path
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with tarfile.open(tmp, "w:gz") as tf:
        for split, member in _MEMBERS.items():
            blob = ("\n".join(_synthetic_pairs(split, _COUNTS[split]))
                    + "\n").encode()
            info = tarfile.TarInfo(member)
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    os.replace(tmp, path)
    return path


def _lines(split):
    path = _path()
    if os.path.exists(path):
        with tarfile.open(path) as tf:
            for line in tf.extractfile(
                    _MEMBERS[split]).read().decode().splitlines():
                yield line
    else:
        for line in _synthetic_pairs(split, _COUNTS[split]):
            yield line


def _build_dict(dict_size, save_path, lang):
    """Frequency dict over the train corpus column (reference
    __build_dict): first three lines are the marks."""
    word_dict = defaultdict(int)
    col = 0 if lang == "en" else 1
    for line in _lines("train"):
        parts = line.strip().split("\t")
        if len(parts) != 2:
            continue
        for w in parts[col].split():
            word_dict[w] += 1
    with open(save_path, "w") as fout:
        fout.write("%s\n%s\n%s\n" % (START_MARK, END_MARK, UNK_MARK))
        ranked = sorted(word_dict.items(), key=lambda x: x[1], reverse=True)
        for idx, (word, _) in enumerate(ranked):
            if idx + 3 == dict_size:
                break
            fout.write("%s\n" % word)


def _load_dict(dict_size, lang, reverse=False):
    dict_path = os.path.join(
        common.DATA_HOME, "wmt16", "%s_%d.dict" % (lang, dict_size))
    tar = _path()
    stale = (
        not os.path.exists(dict_path)
        or len(open(dict_path).readlines()) > dict_size
        # a corpus tarball that appeared (or changed) after the dict was
        # built invalidates it — a dict built from the synthetic
        # fallback must not decode a real corpus
        or (os.path.exists(tar)
            and os.path.getmtime(tar) > os.path.getmtime(dict_path))
    )
    if stale:
        os.makedirs(os.path.dirname(dict_path), exist_ok=True)
        _build_dict(dict_size, dict_path, lang)
    word_dict = {}
    with open(dict_path) as fdict:
        for idx, line in enumerate(fdict):
            if reverse:
                word_dict[idx] = line.strip()
            else:
                word_dict[line.strip()] = idx
    return word_dict


def _dict_size(src_dict_size, trg_dict_size, src_lang):
    src_dict_size = min(src_dict_size, (
        TOTAL_EN_WORDS if src_lang == "en" else TOTAL_DE_WORDS))
    trg_dict_size = min(trg_dict_size, (
        TOTAL_DE_WORDS if src_lang == "en" else TOTAL_EN_WORDS))
    return src_dict_size, trg_dict_size


def _reader_creator(split, src_dict_size, trg_dict_size, src_lang):
    def reader():
        src_dict = _load_dict(src_dict_size, src_lang)
        trg_dict = _load_dict(
            trg_dict_size, "de" if src_lang == "en" else "en")
        start_id = src_dict[START_MARK]
        end_id = src_dict[END_MARK]
        unk_id = src_dict[UNK_MARK]
        src_col = 0 if src_lang == "en" else 1
        for line in _lines(split):
            parts = line.strip().split("\t")
            if len(parts) != 2:
                continue
            src_ids = [start_id] + [
                src_dict.get(w, unk_id) for w in parts[src_col].split()
            ] + [end_id]
            trg_ids = [
                trg_dict.get(w, unk_id)
                for w in parts[1 - src_col].split()
            ]
            trg_next = trg_ids + [end_id]
            trg_ids = [start_id] + trg_ids
            yield src_ids, trg_ids, trg_next

    return reader


def _checked(src_dict_size, trg_dict_size, src_lang):
    if src_lang not in ("en", "de"):
        raise ValueError("src_lang must be 'en' or 'de'")
    return _dict_size(src_dict_size, trg_dict_size, src_lang)


def train(src_dict_size, trg_dict_size, src_lang="en"):
    s, t = _checked(src_dict_size, trg_dict_size, src_lang)
    return _reader_creator("train", s, t, src_lang)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    s, t = _checked(src_dict_size, trg_dict_size, src_lang)
    return _reader_creator("test", s, t, src_lang)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    s, t = _checked(src_dict_size, trg_dict_size, src_lang)
    return _reader_creator("val", s, t, src_lang)


def get_dict(lang, dict_size, reverse=False):
    dict_size = min(dict_size, (
        TOTAL_EN_WORDS if lang == "en" else TOTAL_DE_WORDS))
    return _load_dict(dict_size, lang, reverse)


def convert(path, src_dict_size, trg_dict_size, src_lang):
    """Convert the dataset to record files (reference wmt16.convert),
    through the native record writer."""
    common.convert(
        path,
        train(src_dict_size=src_dict_size, trg_dict_size=trg_dict_size,
              src_lang=src_lang),
        1000,
        "wmt16_train",
    )
    common.convert(
        path,
        test(src_dict_size=src_dict_size, trg_dict_size=trg_dict_size,
              src_lang=src_lang),
        1000,
        "wmt16_test",
    )
