"""WMT16 reader creators (reference dataset/wmt16.py API). Same synthetic
reverse-copy corpus as wmt14, with the get_dict surface."""

from . import common, wmt14

__all__ = ["train", "test", "validation", "get_dict"]


def get_dict(lang, dict_size, reverse=False):
    d = {("%s_w%d" % (lang, i)): i for i in range(dict_size)}
    return {v: k for k, v in d.items()} if reverse else d


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return wmt14.train(min(src_dict_size, trg_dict_size))


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return wmt14.test(min(src_dict_size, trg_dict_size))


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return wmt14.test(min(src_dict_size, trg_dict_size))
