"""MQ2007 learning-to-rank reader creators (reference dataset/mq2007.py
API: train/test with format= 'pairwise' | 'pointwise' | 'listwise')."""

import numpy as np

from . import common

__all__ = ["train", "test"]

_FEAT = 46


def _query(rng):
    n_docs = int(rng.randint(2, 6))
    feats = rng.rand(n_docs, _FEAT).astype("float32")
    rels = rng.randint(0, 3, n_docs)
    return feats, rels


def _reader(split, n, format):
    def reader():
        rng = common.rng_for("mq2007", split)
        for _ in range(n):
            feats, rels = _query(rng)
            if format == "pointwise":
                for f, r in zip(feats, rels):
                    yield f, int(r)
            elif format == "pairwise":
                for i in range(len(rels)):
                    for j in range(len(rels)):
                        if rels[i] > rels[j]:
                            yield feats[i], feats[j]
            else:  # listwise
                yield feats, rels.astype("int64")

    return reader


def train(format="pairwise"):
    return _reader("train", 64, format)


def test(format="pairwise"):
    return _reader("test", 16, format)
