"""MQ2007 (LETOR 4.0) learning-to-rank reader creators (reference
dataset/mq2007.py: Fold1/train.txt + test.txt parsed into per-query
groups; format = 'pointwise' | 'pairwise' | 'listwise').

Wire format: the LETOR svmlight-style line the reference's
load_from_text parses —

  rel qid:NN 1:v 2:v ... 46:v #docid = GX000-.. inc = 1 prob = 0.5

46 dense features per query-document pair, queries contiguous by qid.
Real files placed under DATA_HOME/MQ2007/MQ2007/Fold1/ are decoded;
fetch() synthesises REAL-FORMAT files from the deterministic corpus.
(The genuine distribution ships as a .rar; no rar extractor exists in
this image, so fetch() writes the extracted layout directly — the LINE
format, the part that carries semantics, is exact.)
"""

import os

import numpy as np

from . import common

__all__ = ["train", "test", "fetch", "NUM_FEATURES"]

NUM_FEATURES = 46
N_TRAIN_QUERIES, N_TEST_QUERIES = 64, 16


def _dir():
    return os.path.join(common.DATA_HOME, "MQ2007", "MQ2007", "Fold1")


def _synthetic_queries(split, n):
    rng = common.rng_for("mq2007", split)
    for qid in range(n):
        n_docs = int(rng.randint(2, 6))
        feats = rng.rand(n_docs, NUM_FEATURES).astype("float32")
        rels = rng.randint(0, 3, n_docs)
        yield qid + 1, feats, rels


def fetch():
    d = _dir()
    os.makedirs(d, exist_ok=True)
    for split, n in (("train", N_TRAIN_QUERIES), ("test", N_TEST_QUERIES)):
        path = os.path.join(d, "%s.txt" % split)
        if os.path.exists(path):
            continue
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            for qid, feats, rels in _synthetic_queries(split, n):
                for j in range(feats.shape[0]):
                    cols = " ".join(
                        "%d:%.6f" % (k + 1, feats[j, k])
                        for k in range(NUM_FEATURES)
                    )
                    f.write(
                        "%d qid:%d %s #docid = GX%03d-00-%07d inc = 1 "
                        "prob = 0.5\n" % (rels[j], qid, cols, qid, j)
                    )
        os.replace(tmp, path)
    return d


def _parse_line(line):
    head, _, _ = line.partition("#")
    parts = head.split()
    rel = int(parts[0])
    qid = int(parts[1].split(":")[1])
    feats = np.full(NUM_FEATURES, -1.0, "float32")  # LETOR missing = -1
    for tok in parts[2:]:
        k, _, v = tok.partition(":")
        feats[int(k) - 1] = float(v)
    return qid, rel, feats


def _queries(split, n):
    """Per-query (feats [n_docs, 46], rels [n_docs]) groups, decoded from
    the cached file when present."""
    path = os.path.join(_dir(), "%s.txt" % split)
    if os.path.exists(path):
        cur_qid, feats, rels = None, [], []
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                qid, rel, fv = _parse_line(line)
                if cur_qid is not None and qid != cur_qid:
                    yield np.stack(feats), np.asarray(rels)
                    feats, rels = [], []
                cur_qid = qid
                feats.append(fv)
                rels.append(rel)
        if feats:
            yield np.stack(feats), np.asarray(rels)
    else:
        for _, feats, rels in _synthetic_queries(split, n):
            yield feats, rels


def _reader(split, n, format):
    def reader():
        for feats, rels in _queries(split, n):
            if format == "pointwise":
                for f, r in zip(feats, rels):
                    yield f, int(r)
            elif format == "pairwise":
                for i in range(len(rels)):
                    for j in range(len(rels)):
                        if rels[i] > rels[j]:
                            yield feats[i], feats[j]
            else:  # listwise
                yield feats, rels.astype("int64")

    return reader


def train(format="pairwise"):
    return _reader("train", N_TRAIN_QUERIES, format)


def test(format="pairwise"):
    return _reader("test", N_TEST_QUERIES, format)
