"""Flowers-102 reader creators (reference dataset/flowers.py:
102flowers.tgz of jpg/image_NNNNN.jpg members plus imagelabels.mat
(1-based labels) and setid.mat (trnid/tstid/valid index arrays, with the
reference's deliberate train<->test flag swap: TRAIN_FLAG='tstid');
samples map through default_mapper = load_image_bytes + simple_transform
256->224 with the BGR mean — a flattened float32 3x224x224 vector and
the label.

fetch() synthesises REAL-FORMAT files (actual JPEG members via PIL,
actual .mat v5 files via scipy.io.savemat) from the deterministic
corpus; real downloads decode through the same path.
"""

import functools
import io
import os
import tarfile

import numpy as np

from . import common
from .. import image as paddle_image

__all__ = ["train", "test", "valid", "fetch"]

TRAIN_FLAG = "tstid"  # the reference swaps train/test on purpose
TEST_FLAG = "trnid"
VALID_FLAG = "valid"
N_IMAGES = 64
_CLASSES = 102
_SRC_HW = 96  # stored jpg size; simple_transform resizes to 256 -> 224


def _cache(name):
    return os.path.join(common.DATA_HOME, "flowers", name)


def _synthetic_images():
    """Deterministic (label, HWC uint8 image) pairs: each class gets a
    distinct dominant colour so the data is separable after jpg loss."""
    rng = common.rng_for("flowers", "data")
    out = []
    for i in range(N_IMAGES):
        label = int(rng.randint(1, _CLASSES + 1))  # 1-based like the .mat
        base = np.array([
            (label * 53) % 256, (label * 97) % 256, (label * 193) % 256,
        ], np.float32)
        img = base[None, None, :] + 30.0 * rng.rand(_SRC_HW, _SRC_HW, 3)
        out.append((label, np.clip(img, 0, 255).astype(np.uint8)))
    return out


def fetch():
    d = os.path.dirname(_cache("x"))
    tgz = _cache("102flowers.tgz")
    labels_mat = _cache("imagelabels.mat")
    setid_mat = _cache("setid.mat")
    if all(os.path.exists(f) for f in (tgz, labels_mat, setid_mat)):
        return d
    from PIL import Image
    from scipy.io import savemat

    os.makedirs(d, exist_ok=True)
    data = _synthetic_images()
    if not os.path.exists(tgz):
        with tarfile.open(tgz + ".tmp", "w:gz") as tf:
            for i, (_, img) in enumerate(data):
                buf = io.BytesIO()
                Image.fromarray(img).save(buf, format="JPEG", quality=92)
                blob = buf.getvalue()
                info = tarfile.TarInfo("jpg/image_%05d.jpg" % (i + 1))
                info.size = len(blob)
                tf.addfile(info, io.BytesIO(blob))
        os.replace(tgz + ".tmp", tgz)
    if not os.path.exists(labels_mat):
        savemat(labels_mat + ".tmp.mat",
                {"labels": np.array([[l for l, _ in data]], np.float64)})
        os.replace(labels_mat + ".tmp.mat", labels_mat)
    if not os.path.exists(setid_mat):
        ids = np.arange(1, N_IMAGES + 1)
        savemat(setid_mat + ".tmp.mat", {
            # 1-based image ids per split (reference layout)
            "tstid": ids[: N_IMAGES // 2][None],
            "trnid": ids[N_IMAGES // 2: 3 * N_IMAGES // 4][None],
            "valid": ids[3 * N_IMAGES // 4:][None],
        })
        os.replace(setid_mat + ".tmp.mat", setid_mat)
    return d


def default_mapper(is_train, sample):
    """Image bytes -> flattened f32 via the reference transform chain."""
    img, label = sample
    img = paddle_image.load_image_bytes(img)
    img = paddle_image.simple_transform(
        img, 256, 224, is_train, mean=[103.94, 116.78, 123.68])
    return img.flatten().astype("float32"), label


train_mapper = functools.partial(default_mapper, True)
test_mapper = functools.partial(default_mapper, False)


def _reader_creator(dataset_name, mapper):
    from scipy.io import loadmat

    def reader():
        fetch()
        labels = loadmat(_cache("imagelabels.mat"))["labels"].ravel()
        ids = loadmat(_cache("setid.mat"))[dataset_name].ravel()
        with tarfile.open(_cache("102flowers.tgz")) as tf:
            members = {m.name: m for m in tf.getmembers()}
            for img_id in ids:
                name = "jpg/image_%05d.jpg" % int(img_id)
                blob = tf.extractfile(members[name]).read()
                # reference yields int(label) - 1: 0-based classes
                # (flowers.py:119) despite the 1-based .mat labels
                yield mapper((blob, int(labels[int(img_id) - 1]) - 1))

    return reader


def train(mapper=train_mapper, buffered_size=1024, use_xmap=True):
    return _reader_creator(TRAIN_FLAG, mapper)


def test(mapper=test_mapper, buffered_size=1024, use_xmap=True):
    return _reader_creator(TEST_FLAG, mapper)


def valid(mapper=test_mapper, buffered_size=1024, use_xmap=True):
    return _reader_creator(VALID_FLAG, mapper)
