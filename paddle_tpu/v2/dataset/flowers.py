"""Flowers-102 reader creators (reference dataset/flowers.py API).
Synthetic class-separable images in the reference record shape
(3x224x224 flattened float vector, int label)."""

from . import common

__all__ = ["train", "test", "valid"]

_DIM = 3 * 224 * 224
_CLASSES = 102


def _reader(split, n):
    def reader():
        rng = common.rng_for("flowers", split)
        for _ in range(n):
            label = int(rng.randint(0, _CLASSES))
            img = rng.rand(_DIM).astype("float32")
            yield img, label

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader("train", 128)


def test(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader("test", 32)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader("valid", 32)
