"""Movie-review sentiment reader creators (reference dataset/sentiment.py
API: get_word_dict, train, test). Synthetic separable corpus."""

from . import common

__all__ = ["train", "test", "get_word_dict"]

NUM_TRAINING_INSTANCES = 256
_VOCAB = 300


def get_word_dict():
    return [("w%d" % i, i) for i in range(_VOCAB)]


def _reader(split, n):
    def reader():
        rng = common.rng_for("sentiment", split)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            l = int(rng.randint(4, 30))
            lo = 2 if label == 0 else _VOCAB // 2
            yield list(map(int, rng.randint(lo, lo + _VOCAB // 2 - 2, l))), label

    return reader


def train():
    return _reader("train", NUM_TRAINING_INSTANCES)


def test():
    return _reader("test", 64)
