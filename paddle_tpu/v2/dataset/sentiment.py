"""Movie-review sentiment reader creators (reference dataset/sentiment.py:
the NLTK movie_reviews corpus — one tokenised review per file under
corpora/movie_reviews/{neg,pos}/cvNNN_NNNNN.txt — word dict sorted by
corpus frequency, neg=0 / pos=1, neg/pos files interleaved then split
1600/400).

Wire format: the NLTK corpus DIRECTORY layout, decoded with a plain
directory walk (no nltk dependency — the reference only used nltk as a
downloader/tokenizer; the on-disk layout is ordinary text files).
fetch() synthesises a REAL-LAYOUT corpus from the deterministic,
polarity-separable pools. API deviation kept from round 1: train()/
test() return reader CREATORS like every other module here (the
reference returns bare iterators — an inconsistency of its own surface).
"""

import collections
import os
from itertools import chain

from . import common

__all__ = ["train", "test", "get_word_dict", "fetch", "convert"]

NUM_TRAINING_INSTANCES = 256
N_PER_CLASS = 160  # 320 files total

_POS_POOL = ["great", "wonderful", "superb", "moving", "delight",
             "masterpiece", "love", "charming"]
_NEG_POOL = ["awful", "boring", "dreadful", "waste", "terrible",
             "clumsy", "hate", "tedious"]
_NEUTRAL = ["the", "movie", "film", "plot", "actor", "scene", "story",
            "director", "screen", "minute"]


def _dir():
    return os.path.join(common.DATA_HOME, "corpora", "movie_reviews")


def _synthetic_docs(polarity):
    rng = common.rng_for("sentiment", polarity)
    pool = _POS_POOL if polarity == "pos" else _NEG_POOL
    for i in range(N_PER_CLASS):
        length = int(rng.randint(6, 30))
        words = [
            pool[rng.randint(len(pool))]
            if rng.rand() < 0.4
            else _NEUTRAL[rng.randint(len(_NEUTRAL))]
            for _ in range(length)
        ]
        yield i, " ".join(words)


def fetch():
    base = _dir()
    for polarity in ("neg", "pos"):
        d = os.path.join(base, polarity)
        os.makedirs(d, exist_ok=True)
        for i, text in _synthetic_docs(polarity):
            path = os.path.join(d, "cv%03d_%05d.txt" % (i, 10000 + i))
            if not os.path.exists(path):
                with open(path + ".tmp", "w") as f:
                    f.write(text + "\n")
                os.replace(path + ".tmp", path)
    return base


def _fileids(polarity):
    d = os.path.join(_dir(), polarity)
    if os.path.isdir(d):
        return ["%s/%s" % (polarity, n) for n in sorted(os.listdir(d))
                if n.endswith(".txt")]
    return ["%s/synth_%d" % (polarity, i) for i in range(N_PER_CLASS)]


_SYNTH = {}


def _words(fileid):
    polarity, name = fileid.split("/", 1)
    path = os.path.join(_dir(), polarity, name)
    if os.path.exists(path):
        with open(path) as f:
            return f.read().lower().split()
    if polarity not in _SYNTH:
        _SYNTH[polarity] = {
            i: text.split() for i, text in _synthetic_docs(polarity)
        }
    idx = int(name.rsplit("_", 1)[-1]) if name.startswith("synth_") else 0
    return _SYNTH[polarity][idx]


def get_word_dict():
    """[(word, id)] sorted by corpus frequency (reference semantics)."""
    freq = collections.defaultdict(int)
    for polarity in ("neg", "pos"):
        for fid in _fileids(polarity):
            for w in _words(fid):
                freq[w] += 1
    ranked = sorted(freq.items(), key=lambda x: (-x[1], x[0]))
    return [(w, i) for i, (w, _) in enumerate(ranked)]


_CACHE = {}


def _load_all():
    # keyed on whether real files exist so a fetch() later in the
    # process invalidates the fallback result
    key = (_dir(), os.path.isdir(os.path.join(_dir(), "pos")))
    if key in _CACHE:
        return _CACHE[key]
    ids = dict(get_word_dict())
    data = []
    # neg/pos interleaved, as the reference's sort_files does
    for fid in chain.from_iterable(zip(_fileids("neg"), _fileids("pos"))):
        label = 0 if fid.startswith("neg") else 1
        data.append(([ids[w] for w in _words(fid)], label))
    _CACHE[key] = data
    return data


def train():
    def reader():
        for sample in _load_all()[:NUM_TRAINING_INSTANCES]:
            yield sample

    return reader


def test():
    def reader():
        for sample in _load_all()[NUM_TRAINING_INSTANCES:]:
            yield sample

    return reader


def convert(path):
    common.convert(path, train(), 128, "sentiment_train")
    common.convert(path, test(), 128, "sentiment_test")
