"""PTB (imikolov) reader creators (reference dataset/imikolov.py:
simple-examples.tgz -> ptb.train.txt / ptb.valid.txt, build_dict by
frequency with <unk> last, n-gram readers over <s> sentence <e>).

Wire format: `simple-examples.tgz` — a tar containing
`./simple-examples/data/ptb.train.txt` and `ptb.valid.txt`, one
tokenised sentence per line (exactly the Mikolov PTB layout the
reference extracts, imikolov.py:55,77). Real files are decoded; fetch()
synthesises REAL-FORMAT files from the deterministic corpus.

build_dict(min_word_freq): count words of train+valid (plus <s>/<e>),
keep freq > threshold, sort by (-freq, word), ids 0..; '<unk>' gets the
last id — reference semantics exactly.
"""

import collections
import io
import os
import tarfile

from . import common

__all__ = ["train", "test", "build_dict", "fetch", "convert", "DataType"]

_TRAIN_MEMBER = "./simple-examples/data/ptb.train.txt"
_VALID_MEMBER = "./simple-examples/data/ptb.valid.txt"
N_TRAIN, N_VALID = 1024, 256


class DataType(object):
    NGRAM = 1
    SEQ = 2


def _path():
    return os.path.join(common.DATA_HOME, "imikolov", "simple-examples.tgz")


def _vocab_words():
    # zipf-ish vocabulary: low ids appear often (clear the reference's
    # default min_word_freq=50 bar), tail ids map to <unk>
    return ["w%03d" % i for i in range(160)]


def _synthetic_sentences(split, n):
    rng = common.rng_for("imikolov", split)
    words = _vocab_words()
    for _ in range(n):
        length = int(rng.randint(5, 18))
        ids = (rng.zipf(1.35, size=length) - 1) % len(words)
        # learnable structure: every other word follows its predecessor
        ids[1::2] = (ids[:-1:2] + 1) % len(words)
        yield " ".join(words[i] for i in ids)


def fetch():
    path = _path()
    if os.path.exists(path):
        return path
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with tarfile.open(tmp, "w:gz") as tf:
        for member, split, n in (
            (_TRAIN_MEMBER, "train", N_TRAIN),
            (_VALID_MEMBER, "test", N_VALID),
        ):
            blob = "\n".join(_synthetic_sentences(split, n)).encode() + b"\n"
            info = tarfile.TarInfo(member)
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    os.replace(tmp, path)
    return path


def _lines(split):
    """Decode the tar member when cached, else the in-memory corpus."""
    member = _TRAIN_MEMBER if split == "train" else _VALID_MEMBER
    n = N_TRAIN if split == "train" else N_VALID
    path = _path()
    if os.path.exists(path):
        with tarfile.open(path) as tf:
            f = tf.extractfile(member)
            for raw in f.read().decode().splitlines():
                yield raw
    else:
        for line in _synthetic_sentences(split, n):
            yield line


def word_count(lines, word_freq=None):
    if word_freq is None:
        word_freq = collections.defaultdict(int)
    for l in lines:
        for w in l.strip().split():
            word_freq[w] += 1
        word_freq["<s>"] += 1
        word_freq["<e>"] += 1
    return word_freq


def build_dict(min_word_freq=50):
    word_freq = word_count(_lines("test"), word_count(_lines("train")))
    word_freq.pop("<unk>", None)
    return common.ranked_vocab(word_freq, min_word_freq)


def _reader_creator(split, word_idx, n, data_type):
    def reader():
        UNK = word_idx["<unk>"]
        for line in _lines(split):
            toks = ["<s>"] + line.strip().split() + ["<e>"]
            if data_type == DataType.NGRAM:
                if n <= 0:
                    raise ValueError("invalid gram length %d" % n)
                if len(toks) >= n:
                    ids = [word_idx.get(w, UNK) for w in toks]
                    for i in range(n, len(ids) + 1):
                        yield tuple(ids[i - n:i])
            elif data_type == DataType.SEQ:
                ids = [word_idx.get(w, UNK) for w in toks]
                yield ids[:-1], ids[1:]
            else:
                raise ValueError("unknown data type %r" % data_type)

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return _reader_creator("train", word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    return _reader_creator("test", word_idx, n, data_type)


def convert(path):
    word_idx = build_dict()
    common.convert(path, train(word_idx, 5), 512, "imikolov_train")
    common.convert(path, test(word_idx, 5), 512, "imikolov_test")
