"""PTB n-gram reader creators (reference dataset/imikolov.py API:
build_dict(); train/test(word_idx, n) yield n-tuples of word ids)."""

from . import common

__all__ = ["train", "test", "build_dict"]

_VOCAB = 200


def build_dict(min_word_freq=50):
    return {("w%d" % i): i for i in range(_VOCAB)}


def _reader(split, n_items, word_idx, n):
    v = len(word_idx)

    def reader():
        rng = common.rng_for("imikolov", split)
        for _ in range(n_items):
            ctx = rng.randint(0, v, n - 1)
            nxt = int(ctx.sum() % v)
            yield tuple(map(int, ctx)) + (nxt,)

    return reader


def train(word_idx, n):
    return _reader("train", 512, word_idx, n)


def test(word_idx, n):
    return _reader("test", 128, word_idx, n)
