"""VOC2012 segmentation reader creators (reference dataset/voc2012.py:
VOCtrainval tar with ImageSets/Segmentation/{train,val,trainval}.txt
name lists, JPEGImages/<name>.jpg photos and SegmentationClass/<name>.png
paletted class masks; readers yield (HWC uint8 image array, HW class
mask array) via PIL — including the reference's own split quirk:
train() reads the 'trainval' list and test() the 'train' list).

fetch() synthesises a REAL-FORMAT tarball (actual JPEG + paletted PNG
members via PIL) from the deterministic corpus; a real VOCtrainval tar
decodes through the same reader.
"""

import io
import os
import tarfile

import numpy as np

from . import common

__all__ = ["train", "test", "val", "fetch"]

SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"
_H = _W = 64
_CLASSES = 21
N_TRAIN, N_VAL = 48, 16


def _path():
    return os.path.join(common.DATA_HOME, "voc2012",
                        "VOCtrainval_11-May-2012.tar")


def _synthetic_pairs():
    """(name, HWC uint8 image, HW uint8 mask): blocky class regions so
    masks look like segmentations, image colour follows the mask."""
    rng = common.rng_for("voc2012", "data")
    out = []
    for i in range(N_TRAIN + N_VAL):
        mask = np.zeros((_H, _W), np.uint8)
        for _ in range(int(rng.randint(2, 5))):
            c = int(rng.randint(1, _CLASSES))
            y, x = rng.randint(0, _H - 8), rng.randint(0, _W - 8)
            h, w = rng.randint(8, _H - y + 1), rng.randint(8, _W - x + 1)
            mask[y:y + h, x:x + w] = c
        m32 = mask.astype(np.int32)
        img = np.stack([(m32 * 11) % 256, (m32 * 29) % 256,
                        (m32 * 47) % 256], axis=-1).astype(np.float32)
        img += 20.0 * rng.rand(_H, _W, 3)
        out.append(("2012_%06d" % i,
                    np.clip(img, 0, 255).astype(np.uint8), mask))
    return out


def fetch():
    from PIL import Image

    path = _path()
    if os.path.exists(path):
        return path
    os.makedirs(os.path.dirname(path), exist_ok=True)
    pairs = _synthetic_pairs()
    names = [n for n, _, _ in pairs]
    sets = {
        "train": names[:N_TRAIN],
        "val": names[N_TRAIN:],
        "trainval": names,
    }
    # a deterministic 256-colour palette (the real VOC palette is also a
    # fixed class-indexed table; PIL reads the indices back either way)
    palette = []
    for c in range(256):
        palette += [(c * 37) % 256, (c * 73) % 256, (c * 151) % 256]
    with tarfile.open(path + ".tmp", "w") as tf:
        def add(name, blob):
            info = tarfile.TarInfo(name)
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))

        for split, members in sets.items():
            add(SET_FILE.format(split),
                ("\n".join(members) + "\n").encode())
        for name, img, mask in pairs:
            buf = io.BytesIO()
            Image.fromarray(img).save(buf, format="JPEG", quality=92)
            add(DATA_FILE.format(name), buf.getvalue())
            pim = Image.fromarray(mask, mode="P")
            pim.putpalette(palette)
            buf = io.BytesIO()
            pim.save(buf, format="PNG")
            add(LABEL_FILE.format(name), buf.getvalue())
    os.replace(path + ".tmp", path)
    return path


def reader_creator(filename, sub_name):
    from PIL import Image

    def reader():
        with tarfile.open(filename) as tarobject:
            name2mem = {m.name: m for m in tarobject.getmembers()}
            sets = tarobject.extractfile(
                name2mem[SET_FILE.format(sub_name)])
            for line in sets.read().decode().splitlines():
                line = line.strip()
                if not line:
                    continue
                data = tarobject.extractfile(
                    name2mem[DATA_FILE.format(line)]).read()
                label = tarobject.extractfile(
                    name2mem[LABEL_FILE.format(line)]).read()
                yield (np.array(Image.open(io.BytesIO(data))),
                       np.array(Image.open(io.BytesIO(label))))

    return reader


def train():
    """Reference quirk kept: train() reads the 'trainval' list."""
    return reader_creator(fetch(), "trainval")


def test():
    """Reference quirk kept: test() reads the 'train' list."""
    return reader_creator(fetch(), "train")


def val():
    return reader_creator(fetch(), "val")
