"""VOC2012 segmentation reader creators (reference dataset/voc2012.py
API). Synthetic (image, segmentation-mask) pairs at a small resolution."""

import numpy as np

from . import common

__all__ = ["train", "test", "val"]

_H = _W = 64
_CLASSES = 21


def _reader(split, n):
    def reader():
        rng = common.rng_for("voc2012", split)
        for _ in range(n):
            img = rng.rand(3, _H, _W).astype("float32")
            mask = rng.randint(0, _CLASSES, (_H, _W)).astype("int32")
            yield img, mask

    return reader


def train():
    return _reader("train", 64)


def test():
    return _reader("test", 16)


def val():
    return _reader("val", 16)
