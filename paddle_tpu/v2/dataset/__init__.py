"""Dataset reader creators (reference python/paddle/v2/dataset/*).

The reference downloads real corpora into ~/.cache/paddle/dataset; this
environment has no network egress, so each module serves a deterministic
synthetic corpus with the exact record shapes, vocab APIs and reader-
creator signatures of the original. Swap in real data by dropping files
into the cache dir and extending `common.load_cached` (the synthetic
generators are the fallback, not the format)."""

from . import (  # noqa: F401
    cifar,
    criteo,
    common,
    conll05,
    flowers,
    imdb,
    imikolov,
    mnist,
    movielens,
    mq2007,
    sentiment,
    uci_housing,
    voc2012,
    wmt14,
    wmt16,
)

__all__ = [
    "mnist", "cifar", "imdb", "imikolov", "movielens", "uci_housing",
    "wmt14", "wmt16", "conll05", "sentiment", "flowers", "voc2012",
    "mq2007", "criteo", "common",
]
