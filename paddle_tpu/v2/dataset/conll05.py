"""CoNLL-2005 SRL reader creators (reference dataset/conll05.py:
conll05st-tests.tar.gz holding `conll05st-release/test.wsj/words/
test.wsj.words.gz` (one token per line, blank line between sentences)
and `.../props/test.wsj.props.gz` (per line: predicate-lemma column +
one bracket-label column per predicate — `(A0*`, `*`, `*)`, `(V*)` ...);
plus wordDict.txt / verbDict.txt / targetDict.txt files loaded by line
number. The bracket columns convert to B-/I-/O tag sequences and each
predicate yields one 9-field sample: word ids, 5 predicate-context
columns (bos/eos padded), predicate id, context mark, label ids —
conll05.py:132-178 semantics with UNK_IDX=0.

fetch() synthesises REAL-FORMAT files (tarball with gzipped members,
dict text files, f32 embedding blob) from the deterministic corpus;
real downloads decode through the same parser.
"""

import gzip
import io
import itertools
import os
import tarfile

from . import common

__all__ = ["get_dict", "get_embedding", "test", "fetch", "convert"]

UNK_IDX = 0
_WORDS_MEMBER = "conll05st-release/test.wsj/words/test.wsj.words.gz"
_PROPS_MEMBER = "conll05st-release/test.wsj/props/test.wsj.props.gz"
N_SENTENCES = 128
_WORD_POOL = ["w%02d" % i for i in range(80)]
_VERBS = ["say", "make", "take", "give", "find", "tell", "ask", "keep",
          "show", "hold", "bring", "begin", "move", "play", "run"]
_ROLES = ["A0", "A1", "A2", "AM-TMP"]
EMB_DIM = 32


def _cache(name):
    return os.path.join(common.DATA_HOME, "conll05st", name)


def _synthetic_sentences():
    """(words, lemma, verb position, B-/I-/O tags); the writer encodes
    the tags into bracket notation and the parser must round-trip."""
    rng = common.rng_for("conll05", "test")
    out = []
    for _ in range(N_SENTENCES):
        L = int(rng.randint(4, 12))
        words = [_WORD_POOL[rng.randint(len(_WORD_POOL))] for _ in range(L)]
        v = int(rng.randint(1, L - 1))
        lemma = _VERBS[rng.randint(len(_VERBS))]
        words[v] = lemma
        tags = ["O"] * L
        tags[v] = "B-V"
        # an A0 span somewhere before the verb
        a0_end = int(rng.randint(0, v))
        a0_start = int(rng.randint(0, a0_end + 1))
        for i in range(a0_start, a0_end + 1):
            tags[i] = "B-A0" if i == a0_start else "I-A0"
        # a second role span after the verb, when room remains
        if v + 2 < L:
            role = _ROLES[1:][int(rng.randint(3))]
            a1_start = v + 1 + int(rng.randint(0, L - v - 2))
            a1_end = a1_start + int(rng.randint(0, L - a1_start))
            for i in range(a1_start, a1_end + 1):
                tags[i] = ("B-" + role) if i == a1_start else ("I-" + role)
        out.append((words, lemma, v, tags))
    return out


def _encode_brackets(tags):
    """B-/I-/O -> the props bracket column (inverse of the reference's
    decoding state machine)."""
    col = []
    for i, t in enumerate(tags):
        nxt = tags[i + 1] if i + 1 < len(tags) else "O"
        same_continues = nxt.startswith("I-") and (
            t[2:] == nxt[2:] if t != "O" else False
        )
        if t == "O":
            col.append("*")
        elif t.startswith("B-"):
            tag = t[2:]
            col.append("(%s*" % tag if same_continues else "(%s*)" % tag)
        else:  # I- : continue or close the open span
            col.append("*" if same_continues else "*)")
    return col


def _dict_words():
    return ["<unk>"] + sorted(set(_WORD_POOL) | set(_VERBS)) + \
        ["bos", "eos"]


def _label_entries():
    labels = ["O"]
    for r in _ROLES + ["V"]:
        labels += ["B-" + r, "I-" + r]
    return labels


def fetch():
    d = os.path.dirname(_cache("x"))
    os.makedirs(d, exist_ok=True)
    for name, entries in (
        ("wordDict.txt", _dict_words()),
        ("verbDict.txt", sorted(_VERBS)),
        ("targetDict.txt", _label_entries()),
    ):
        path = _cache(name)
        if not os.path.exists(path):
            with open(path + ".tmp", "w") as f:
                f.write("\n".join(entries) + "\n")
            os.replace(path + ".tmp", path)
    # embedding blob: [n_words, EMB_DIM] f32 (the reference ships a
    # pretrained binary; here deterministic random)
    emb_path = _cache("emb")
    if not os.path.exists(emb_path):
        import numpy as np

        rng = common.rng_for("conll05", "emb")
        arr = rng.randn(len(_dict_words()), EMB_DIM).astype("<f4")
        with open(emb_path + ".tmp", "wb") as f:
            f.write(arr.tobytes())
        os.replace(emb_path + ".tmp", emb_path)
    tar_path = _cache("conll05st-tests.tar.gz")
    if not os.path.exists(tar_path):
        words_lines, props_lines = [], []
        for words, lemma, v, tags in _synthetic_sentences():
            col = _encode_brackets(tags)
            for i, w in enumerate(words):
                words_lines.append(w)
                props_lines.append(
                    "%s %s" % (lemma if i == v else "-", col[i]))
            words_lines.append("")
            props_lines.append("")
        with tarfile.open(tar_path + ".tmp", "w:gz") as tf:
            for member, lines in ((_WORDS_MEMBER, words_lines),
                                  (_PROPS_MEMBER, props_lines)):
                blob = io.BytesIO()
                with gzip.GzipFile(fileobj=blob, mode="wb") as gz:
                    gz.write(("\n".join(lines) + "\n").encode())
                data = blob.getvalue()
                info = tarfile.TarInfo(member)
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
        os.replace(tar_path + ".tmp", tar_path)
    return d


def load_dict(filename):
    d = {}
    with open(filename) as f:
        for i, line in enumerate(f):
            d[line.strip()] = i
    return d


def get_dict():
    """(word_dict, verb_dict, label_dict) from the dict files (reference
    get_dict); synthesised via fetch() when absent."""
    fetch()  # idempotent per artifact; heals a partially-written cache
    return (load_dict(_cache("wordDict.txt")),
            load_dict(_cache("verbDict.txt")),
            load_dict(_cache("targetDict.txt")))


def get_embedding():
    """Path to the [n_words, EMB_DIM] f32 embedding blob (reference
    returns the downloaded file path)."""
    if not os.path.exists(_cache("emb")):
        fetch()
    return _cache("emb")


def corpus_reader(data_path=None, words_name=_WORDS_MEMBER,
                  props_name=_PROPS_MEMBER):
    """Yield (sentence words, predicate lemma, B-/I-/O labels) per
    predicate column — the reference corpus_reader bracket decoding."""
    data_path = data_path or _cache("conll05st-tests.tar.gz")
    if not os.path.exists(data_path):
        fetch()

    def _decode_column(col):
        """One bracket column -> B-/I-/O tags. Grammar: '(TAG*' opens a
        span, '*' continues it (or is O outside one), '*)' closes it,
        '(TAG*)' is a single-token span."""
        tags, span = [], None
        for tok in col:
            if tok.startswith("("):
                tag = tok[1:tok.index("*")]
                tags.append("B-" + tag)
                span = None if tok.endswith(")") else tag
            elif tok == "*)":
                tags.append("I-" + (span or "O"))
                span = None
            elif tok == "*":
                tags.append("I-" + span if span else "O")
            else:
                raise RuntimeError("unexpected props token: %s" % tok)
        return tags

    def _sentences():
        """Group the parallel line streams into per-sentence
        (words, prop rows) chunks at blank lines."""
        with tarfile.open(data_path) as tf:
            wf = tf.extractfile(words_name)
            pf = tf.extractfile(props_name)
            with gzip.GzipFile(fileobj=wf) as words_file, \
                    gzip.GzipFile(fileobj=pf) as props_file:
                words, rows = [], []
                for wline, pline in itertools.zip_longest(words_file,
                                                          props_file):
                    w = wline.decode().strip()
                    row = pline.decode().strip().split()
                    if not row:  # sentence boundary
                        if rows:
                            yield words, rows
                        words, rows = [], []
                    else:
                        words.append(w)
                        rows.append(row)
                if rows:
                    yield words, rows

    def reader():
        for words, rows in _sentences():
            lemma_col = [r[0] for r in rows]
            predicates = [x for x in lemma_col if x != "-"]
            n_preds = len(rows[0]) - 1
            for k in range(n_preds):
                col = [r[1 + k] for r in rows]
                yield words, predicates[k], _decode_column(col)

    return reader


def reader_creator(corpus_reader, word_dict=None, predicate_dict=None,
                   label_dict=None):
    # context-window offsets and their out-of-range padding tokens: the
    # reference marks the 5-token window around the predicate and pads
    # positions that fall off the sentence with 'bos'/'eos'
    # (conll05.py:135-162)
    offsets = ((-2, "bos"), (-1, "bos"), (0, None), (1, "eos"), (2, "eos"))

    def reader():
        for sentence, predicate, labels in corpus_reader():
            n = len(sentence)
            v = labels.index("B-V")
            mark = [0] * n
            ctx_cols = []
            for off, pad in offsets:
                ok = 0 <= v + off < n
                if ok:
                    mark[v + off] = 1
                word = sentence[v + off] if ok else pad
                ctx_cols.append([word_dict.get(word, UNK_IDX)] * n)

            yield tuple(
                [[word_dict.get(w, UNK_IDX) for w in sentence]]
                + ctx_cols
                + [[predicate_dict.get(predicate)] * n, mark,
                   [label_dict.get(t) for t in labels]]
            )

    return reader


def test():
    word_dict, verb_dict, label_dict = get_dict()
    return reader_creator(
        corpus_reader(),
        word_dict=word_dict,
        predicate_dict=verb_dict,
        label_dict=label_dict,
    )


def convert(path):
    common.convert(path, test(), 128, "conll05_test")
