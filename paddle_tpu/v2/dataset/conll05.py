"""CoNLL-2005 SRL reader creators (reference dataset/conll05.py API:
get_dict() -> (word_dict, verb_dict, label_dict); test() yields the
9-field record used by the label_semantic_roles book test)."""

from . import common

__all__ = ["get_dict", "get_embedding", "test"]

_N_WORDS, _N_VERBS, _N_LABELS = 120, 20, 9


def get_dict():
    word_dict = {("w%d" % i): i for i in range(_N_WORDS)}
    verb_dict = {("v%d" % i): i for i in range(_N_VERBS)}
    label_dict = {("l%d" % i): i for i in range(_N_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    return None


def test():
    def reader():
        rng = common.rng_for("conll05", "test")
        for _ in range(128):
            l = int(rng.randint(3, 12))
            words = list(map(int, rng.randint(2, _N_WORDS, l)))
            pred_pos = int(rng.randint(0, l))
            verb = [int(rng.randint(0, _N_VERBS))] * l
            mark = [1 if i == pred_pos else 0 for i in range(l)]
            labels = [
                int(w % (_N_LABELS - 1)) if m == 0 else _N_LABELS - 1
                for w, m in zip(words, mark)
            ]

            def roll(k):
                return [words[(i + k) % l] for i in range(l)]

            yield (words, roll(-2), roll(-1), words, roll(1), roll(2), verb,
                   mark, labels)

    return reader
