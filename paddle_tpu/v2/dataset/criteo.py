"""Criteo display-advertising CTR reader creators — the data side of the
wide&deep/DeepFM workload (models/ctr.py). The reference era served this
model class through the sparse pserver path (row-sharded tables,
RemoteParameterUpdater.h:265); here the tables row-shard over the mesh
(parallel/embedding.py) and this module supplies the classic wire format.

Wire format (Criteo Display Advertising Challenge TSV, the canonical CTR
benchmark): each line is

  label \\t I1..I13 (integer counts, may be empty) \\t C1..C26 (8-hex-char
  categorical hashes, may be empty)

gzip-wrapped under ``DATA_HOME/criteo``. Real files placed there are
DECODED; ``fetch()`` synthesises REAL-FORMAT files from the deterministic
corpus (zero-egress harness), so the decode path is exercised either way.
Without cached files the readers fall back to the in-memory corpus.

Readers yield ``(dense, ids, label)``:
  dense  — float32[13], log1p-scaled integer features (missing -> 0)
  ids    — int64[26], each categorical token bucket-hashed into its
           field's disjoint id range: id = field*buckets + crc32(tok)%buckets
  label  — int, 0/1 click
"""

import gzip
import os
import zlib

import numpy as np

from . import common

__all__ = ["train", "test", "fetch", "convert", "vocab_size",
           "NUM_DENSE", "NUM_SPARSE"]

NUM_DENSE = 13
NUM_SPARSE = 26
N_TRAIN, N_TEST = 512, 128
_FILES = {"train": "train.txt.gz", "test": "test.txt.gz"}


def vocab_size(buckets_per_field=1000):
    """Total id space across the 26 disjoint per-field ranges — the
    [vocab] for models.ctr tables."""
    return NUM_SPARSE * int(buckets_per_field)


def _cache_dir():
    return os.path.join(common.DATA_HOME, "criteo")


def _synthetic_lines(split, n):
    """Deterministic corpus in the REAL TSV schema. Click probability
    depends on C1/C2 parity so CTR models have signal to learn."""
    rng = common.rng_for("criteo", split)
    for _ in range(n):
        ints = [
            "" if rng.rand() < 0.1 else str(int(rng.poisson(3.0)))
            for _ in range(NUM_DENSE)
        ]
        cats = [
            "" if rng.rand() < 0.05 else "%08x" % rng.randint(0, 1 << 20)
            for _ in range(NUM_SPARSE)
        ]
        sig = (zlib.crc32(cats[0].encode()) ^ zlib.crc32(cats[1].encode())) & 1
        label = int(sig ^ (rng.rand() < 0.15))
        yield "\t".join([str(label)] + ints + cats)


def _write_gz(split, n, path):
    if os.path.exists(path):
        return  # never clobber genuine downloads
    tmp = path + ".tmp"
    with gzip.open(tmp, "wt") as f:
        for line in _synthetic_lines(split, n):
            f.write(line + "\n")
    os.replace(tmp, path)


def fetch():
    os.makedirs(_cache_dir(), exist_ok=True)
    _write_gz("train", N_TRAIN, os.path.join(_cache_dir(), _FILES["train"]))
    _write_gz("test", N_TEST, os.path.join(_cache_dir(), _FILES["test"]))


def _parse(line, buckets):
    parts = line.rstrip("\n").split("\t")
    if len(parts) == NUM_DENSE + NUM_SPARSE:
        # the canonical Kaggle test split carries no label column;
        # yield -1 so held-out data still decodes
        parts = ["-1"] + parts
    if len(parts) != 1 + NUM_DENSE + NUM_SPARSE:
        raise ValueError(
            "criteo line has %d fields, want %d (labeled) or %d"
            % (len(parts), 1 + NUM_DENSE + NUM_SPARSE,
               NUM_DENSE + NUM_SPARSE)
        )
    label = int(parts[0])
    dense = np.zeros(NUM_DENSE, np.float32)
    for i, tok in enumerate(parts[1:1 + NUM_DENSE]):
        if tok:
            dense[i] = np.log1p(max(int(tok), 0))
    ids = np.empty(NUM_SPARSE, np.int64)
    for i, tok in enumerate(parts[1 + NUM_DENSE:]):
        ids[i] = i * buckets + (zlib.crc32(tok.encode()) % buckets)
    return dense, ids, label


def _reader_creator(split, n, buckets):
    def reader():
        path = os.path.join(_cache_dir(), _FILES[split])
        if os.path.exists(path):
            with gzip.open(path, "rt") as f:
                for line in f:
                    yield _parse(line, buckets)
        else:
            for line in _synthetic_lines(split, n):
                yield _parse(line, buckets)

    return reader


def train(buckets_per_field=1000):
    return _reader_creator("train", N_TRAIN, int(buckets_per_field))


def test(buckets_per_field=1000):
    return _reader_creator("test", N_TEST, int(buckets_per_field))


def convert(path, buckets_per_field=1000):
    common.convert(path, train(buckets_per_field), 256, "criteo_train")
    common.convert(path, test(buckets_per_field), 256, "criteo_test")
