"""CIFAR reader creators (reference dataset/cifar.py API: train10/test10
yield (3072 floats, int label); train100/test100 likewise)."""

from . import common

__all__ = ["train10", "test10", "train100", "test100"]


def _reader(split, n, classes):
    def reader():
        rng = common.rng_for("cifar%d" % classes, split)
        for _ in range(n):
            label = int(rng.randint(0, classes))
            img = rng.randn(3072) * 0.2
            img[(label % 3) * 1024:(label % 3) * 1024 + 256] += (
                (label + 1) / classes
            )
            yield img.astype("float32"), label

    return reader


def train10():
    return _reader("train", 512, 10)


def test10():
    return _reader("test", 128, 10)


def train100():
    return _reader("train", 512, 100)


def test100():
    return _reader("test", 128, 100)
