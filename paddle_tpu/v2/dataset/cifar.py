"""CIFAR reader creators (reference dataset/cifar.py API: train10/test10
yield (3072 floats, int label); train100/test100 likewise).

Real data path: when ``cifar-10-python.tar.gz`` exists under
``common.DATA_HOME/cifar`` (the reference's download cache layout) it is
DECODED — the genuine https://www.cs.toronto.edu/~kriz/cifar wire format:
a tar.gz of pickled batches, each a dict with ``data`` uint8 [N, 3072]
and ``labels``. ``fetch()`` synthesises a real-format archive from the
deterministic corpus (zero network egress), so the decode/shuffle path
runs either way; without a cache the readers fall back to the in-memory
synthetic corpus.
"""

import io
import os
import pickle
import tarfile

import numpy as np

from . import common

__all__ = ["train10", "test10", "train100", "test100", "fetch", "convert"]

# genuine-download checksums (reference dataset/cifar.py:41-43)
CIFAR10_MD5 = "c58f30108f718f92721af3b95e74349a"
CIFAR100_MD5 = "eb9058c3a382ffc7106e4002c42a8d85"

_TAR10 = "cifar-10-python.tar.gz"


def _cache_path():
    return os.path.join(common.DATA_HOME, "cifar", _TAR10)


def _synthetic(split, n, classes):
    rng = common.rng_for("cifar%d" % classes, split)
    for _ in range(n):
        label = int(rng.randint(0, classes))
        img = rng.randn(3072) * 0.2
        img[(label % 3) * 1024:(label % 3) * 1024 + 256] += (
            (label + 1) / classes
        )
        yield img.astype("float32"), label


def fetch():
    """Populate the download cache with a REAL-FORMAT cifar-10 archive
    (reference cifar.fetch; files synthesised — no network egress)."""
    path = _cache_path()
    if os.path.exists(path):
        return path
    os.makedirs(os.path.dirname(path), exist_ok=True)

    def batch_bytes(split, n):
        data, labels = [], []
        for img, label in _synthetic(split, n, 10):
            # floats -> uint8 pixels like the original batches
            data.append(common.to_pixels(img))
            labels.append(label)
        return pickle.dumps(
            {b"data": np.stack(data), b"labels": labels}, protocol=2
        )

    with tarfile.open(path, "w:gz") as tar:
        for name, split, n in (
            ("cifar-10-batches-py/data_batch_1", "train", 512),
            ("cifar-10-batches-py/test_batch", "test", 128),
        ):
            payload = batch_bytes(split, n)
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))
    return path


def _decode_tar(sub_name):
    """Decode the real CIFAR wire format (reference cifar.reader_creator:
    tar.gz of pickled batch dicts)."""
    with tarfile.open(_cache_path(), "r:gz") as tar:
        names = [
            m.name for m in tar.getmembers() if sub_name in m.name
        ]
        for name in sorted(names):
            batch = pickle.load(tar.extractfile(name), encoding="bytes")
            data = batch[b"data"]
            labels = batch.get(b"labels") or batch.get(b"fine_labels")
            for i in range(len(labels)):
                yield (common.from_pixels(data[i]), int(labels[i]))


def _reader(split, n, classes):
    sub = "data_batch" if split == "train" else "test_batch"

    def reader():
        if classes == 10 and os.path.exists(_cache_path()):
            yield from _decode_tar(sub)
        else:
            yield from _synthetic(split, n, classes)

    return reader


def train10():
    return _reader("train", 512, 10)


def test10():
    return _reader("test", 128, 10)


def train100():
    return _reader("train", 512, 100)


def test100():
    return _reader("test", 128, 100)


def convert(path):
    """Convert to record files via the native writer (reference
    cifar.convert)."""
    common.convert(path, train10(), 128, "cifar_train10")
    common.convert(path, test10(), 128, "cifar_test10")
