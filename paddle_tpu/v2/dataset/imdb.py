"""IMDB sentiment reader creators (reference dataset/imdb.py API:
word_dict(); train/test(word_idx) yield (word-id list, 0/1 label))."""

from . import common

__all__ = ["train", "test", "word_dict"]

_VOCAB = 400


def word_dict():
    return {("w%d" % i): i for i in range(_VOCAB)}


def _reader(split, n, word_idx):
    v = len(word_idx)

    def reader():
        rng = common.rng_for("imdb", split)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            l = int(rng.randint(5, 40))
            lo = 2 if label == 0 else v // 2
            words = rng.randint(lo, lo + v // 2 - 2, size=l)
            yield list(map(int, words)), label

    return reader


def train(word_idx):
    return _reader("train", 256, word_idx)


def test(word_idx):
    return _reader("test", 64, word_idx)
