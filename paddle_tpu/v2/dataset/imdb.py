"""IMDB sentiment reader creators (reference dataset/imdb.py:
aclImdb_v1.tar.gz -> aclImdb/{train,test}/{pos,neg}/*.txt, tokenize by
lowercase + punctuation strip, build_dict by frequency with <unk> last,
readers yield (word-id list, label) with POS=0 / NEG=1 — the reference's
label convention, imdb.py:83).

Wire format: the real Stanford tarball layout — one review per .txt
member under the four split/polarity directories. Real files are
decoded; fetch() synthesises a REAL-FORMAT tarball from the
deterministic corpus (polarity-correlated word pools so sentiment is
learnable), exercising the tar/tokenize path either way.
"""

import collections
import io
import os
import re
import string
import tarfile

from . import common

__all__ = ["build_dict", "word_dict", "train", "test", "fetch", "convert"]

# genuine-download checksum (reference dataset/imdb.py:32)
MD5 = "7c2ac02c03563afcf9b574c7e56c153a"

N_TRAIN, N_TEST = 256, 64  # reviews per split (half pos, half neg)

_POS_POOL = ["great", "wonderful", "superb", "moving", "delight",
             "masterpiece", "love", "charming", "beautiful", "perfect"]
_NEG_POOL = ["awful", "boring", "dreadful", "waste", "terrible",
             "clumsy", "hate", "tedious", "flat", "mess"]
_NEUTRAL = ["the", "movie", "film", "plot", "actor", "scene", "story",
            "director", "screen", "minute", "character", "music",
            "camera", "dialog", "ending", "beginning"]


def _path():
    return os.path.join(common.DATA_HOME, "imdb", "aclImdb_v1.tar.gz")


def _synthetic_reviews(split):
    n = N_TRAIN if split == "train" else N_TEST
    rng = common.rng_for("imdb", split)
    for i in range(n):
        label = i % 2  # 0 = pos, 1 = neg (reference convention)
        pool = _POS_POOL if label == 0 else _NEG_POOL
        length = int(rng.randint(8, 40))
        words = [
            pool[rng.randint(len(pool))]
            if rng.rand() < 0.4
            else _NEUTRAL[rng.randint(len(_NEUTRAL))]
            for _ in range(length)
        ]
        # real-review dressing the tokenizer must strip
        text = " ".join(words).capitalize() + "."
        yield label, i, text


def fetch():
    path = _path()
    if os.path.exists(path):
        return path
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with tarfile.open(tmp, "w:gz") as tf:
        for split in ("train", "test"):
            for label, i, text in _synthetic_reviews(split):
                polarity = "pos" if label == 0 else "neg"
                blob = text.encode()
                info = tarfile.TarInfo(
                    "aclImdb/%s/%s/%d_%d.txt"
                    % (split, polarity, i, 7 if label == 0 else 2)
                )
                info.size = len(blob)
                tf.addfile(info, io.BytesIO(blob))
    os.replace(tmp, path)
    return path


_PUNCT = str.maketrans("", "", string.punctuation)


def _tok(text):
    return text.rstrip("\n\r").translate(_PUNCT).lower().split()


def tokenize(pattern):
    """Yield tokenised docs whose tar member name matches `pattern`
    (reference imdb.py:64 tokenize — sequential tar access). The
    no-tarball fallback synthesises the member NAMES and applies the
    same pattern, so broad patterns (e.g. the whole train split) see
    both polarities exactly as the decoded path would."""
    path = _path()
    if os.path.exists(path):
        with tarfile.open(path) as tarf:
            tf = tarf.next()
            while tf is not None:
                if pattern.match(tf.name):
                    yield _tok(tarf.extractfile(tf).read().decode())
                tf = tarf.next()
    else:
        for split in ("train", "test"):
            for label, i, text in _synthetic_reviews(split):
                polarity = "pos" if label == 0 else "neg"
                name = "aclImdb/%s/%s/%d_%d.txt" % (
                    split, polarity, i, 7 if label == 0 else 2)
                if pattern.match(name):
                    yield _tok(text)


def build_dict(pattern, cutoff):
    """Frequency dictionary over docs matching `pattern`; <unk> last."""
    word_freq = collections.defaultdict(int)
    for doc in tokenize(pattern):
        for word in doc:
            word_freq[word] += 1
    return common.ranked_vocab(word_freq, cutoff)


def word_dict():
    """Reference convenience: dictionary over the whole training set."""
    return build_dict(re.compile(r"aclImdb/train/.*\.txt$"), 0)


def _reader_creator(pos_pattern, neg_pattern, word_idx):
    UNK = word_idx["<unk>"]

    def load(pattern, out, label):
        for doc in tokenize(pattern):
            out.append(([word_idx.get(w, UNK) for w in doc], label))

    ins = []
    load(pos_pattern, ins, 0)
    load(neg_pattern, ins, 1)

    def reader():
        for doc, label in ins:
            yield doc, label

    return reader


def train(word_idx):
    return _reader_creator(
        re.compile(r"aclImdb/train/pos/.*\.txt$"),
        re.compile(r"aclImdb/train/neg/.*\.txt$"), word_idx)


def test(word_idx):
    return _reader_creator(
        re.compile(r"aclImdb/test/pos/.*\.txt$"),
        re.compile(r"aclImdb/test/neg/.*\.txt$"), word_idx)


def convert(path):
    w = word_dict()
    common.convert(path, train(w), 128, "imdb_train")
    common.convert(path, test(w), 128, "imdb_test")
