"""MNIST reader creators (reference dataset/mnist.py API: train/test yield
(784-dim float in [-1,1], int label)). Synthetic separable digits."""

from . import common

__all__ = ["train", "test"]

N_TRAIN, N_TEST = 512, 128


def _reader(split, n):
    def reader():
        rng = common.rng_for("mnist", split)
        for _ in range(n):
            label = int(rng.randint(0, 10))
            img = rng.randn(784) * 0.3 - 0.5
            img[label * 70:(label + 1) * 70] += 1.2  # class-separable band
            yield img.clip(-1, 1).astype("float32"), label

    return reader


def train():
    return _reader("train", N_TRAIN)


def test():
    return _reader("test", N_TEST)
