"""MNIST reader creators (reference dataset/mnist.py API: train/test yield
(784-dim float in [-1,1], int label)).

Real data path: when the IDX-format gz files exist under
``common.DATA_HOME/mnist`` (the reference's download cache layout), they
are DECODED — magic 2051 image files / 2049 label files, gzip-wrapped,
exactly http://yann.lecun.com/exdb/mnist/ wire format. ``fetch()``
populates that cache; with zero network egress it synthesises
REAL-FORMAT files from the deterministic corpus, so the decode path is
exercised either way. Without cached files the readers fall back to the
in-memory synthetic corpus directly.
"""

import gzip
import os
import struct

import numpy as np

from . import common

__all__ = ["train", "test", "fetch", "convert"]

N_TRAIN, N_TEST = 512, 128

# genuine-download checksums (reference dataset/mnist.py:28-34) — used
# by tests/test_real_archives.py to tell real archives from synthetics
TRAIN_IMAGE_MD5 = "f68b3c2dcbeaaa9fbdd348bbdeb94873"
TRAIN_LABEL_MD5 = "d53e105ee54ea40749a09fcbcd1e9432"
TEST_IMAGE_MD5 = "9fb629c4189551a2d022fa330f9573f3"
TEST_LABEL_MD5 = "ec29112dd5afa0611ce80d1b7f02629c"

_FILES = {
    "train": ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz"),
    "test": ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"),
}


def _cache_dir():
    return os.path.join(common.DATA_HOME, "mnist")


def _synthetic(split, n):
    rng = common.rng_for("mnist", split)
    for _ in range(n):
        label = int(rng.randint(0, 10))
        img = rng.randn(784) * 0.3 - 0.5
        img[label * 70:(label + 1) * 70] += 1.2  # class-separable band
        yield img.clip(-1, 1).astype("float32"), label


def _write_idx(split, n, img_path, lbl_path):
    """Serialise the corpus in the REAL MNIST wire format. Never
    overwrites: a user may have placed genuine downloads in the cache
    (common.download points them here)."""
    imgs, labels = [], []
    for img, label in _synthetic(split, n):
        imgs.append(common.to_pixels(img))
        labels.append(label)
    if not os.path.exists(img_path):
        with gzip.open(img_path, "wb") as f:
            f.write(struct.pack(">IIII", 2051, len(imgs), 28, 28))
            f.write(np.stack(imgs).tobytes())
    if not os.path.exists(lbl_path):
        with gzip.open(lbl_path, "wb") as f:
            f.write(struct.pack(">II", 2049, len(labels)))
            f.write(np.asarray(labels, np.uint8).tobytes())


def fetch():
    """Populate the download cache (reference mnist.fetch). No network
    egress here, so real-FORMAT IDX files are synthesised for whichever
    files are missing (user-placed genuine files are left untouched)."""
    d = _cache_dir()
    os.makedirs(d, exist_ok=True)
    for split, (img_name, lbl_name) in _FILES.items():
        _write_idx(split, N_TRAIN if split == "train" else N_TEST,
                   os.path.join(d, img_name), os.path.join(d, lbl_name))
    return d


def _decode_idx(img_path, lbl_path):
    """Parse the IDX wire format (reference mnist.py reader_creator)."""
    with gzip.open(img_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise IOError("%s: bad IDX image magic %d" % (img_path, magic))
        imgs = np.frombuffer(f.read(n * rows * cols), np.uint8)
        imgs = imgs.reshape(n, rows * cols)
    with gzip.open(lbl_path, "rb") as f:
        magic, n_l = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise IOError("%s: bad IDX label magic %d" % (lbl_path, magic))
        labels = np.frombuffer(f.read(n_l), np.uint8)
    if n != n_l:
        raise IOError("image/label count mismatch: %d vs %d" % (n, n_l))
    for i in range(n):
        # the reference normalises to [-1, 1] floats
        yield (common.from_pixels(imgs[i]), int(labels[i]))


def _reader(split, n):
    img_name, lbl_name = _FILES[split]

    def reader():
        img_path = os.path.join(_cache_dir(), img_name)
        lbl_path = os.path.join(_cache_dir(), lbl_name)
        if os.path.exists(img_path) and os.path.exists(lbl_path):
            yield from _decode_idx(img_path, lbl_path)
        else:
            yield from _synthetic(split, n)

    return reader


def train():
    return _reader("train", N_TRAIN)


def test():
    return _reader("test", N_TEST)


def convert(path):
    """Convert the dataset to record files (reference mnist.convert),
    through the native record writer."""
    common.convert(path, train(), 64, "mnist_train")
    common.convert(path, test(), 64, "mnist_test")
