"""WMT14 reader creators (reference dataset/wmt14.py API: train/test(
dict_size) yield (src ids, trg ids, trg_next ids)). Synthetic reverse-copy
corpus: the 'translation' is the reversed source."""

from . import common

__all__ = ["train", "test", "N"]

N = 30  # default synthetic dict size cap
START, END = 0, 1


def _reader(split, n_items, dict_size):
    def reader():
        rng = common.rng_for("wmt14", split)
        for _ in range(n_items):
            l = int(rng.randint(2, 8))
            src = list(map(int, rng.randint(2, dict_size, l)))
            rev = src[::-1]
            yield src, [START] + rev, rev + [END]

    return reader


def train(dict_size):
    return _reader("train", 256, dict_size)


def test(dict_size):
    return _reader("test", 64, dict_size)
