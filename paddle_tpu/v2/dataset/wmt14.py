"""WMT14 reader creators (reference dataset/wmt14.py: a tgz holding
`*src.dict` / `*trg.dict` (one token per line, id = line number, first
three <s>/<e>/<unk>) plus parallel corpora members ending `train/train`
and `test/test` with one `source\\ttarget` pair per line; readers yield
(src ids <s>..<e>, trg ids <s>.., trg_next ids ..<e>), UNK_IDX=2,
sentences over 80 tokens skipped — wmt14.py:52-110 semantics exactly).

fetch() synthesises a REAL-FORMAT tarball from the deterministic
reverse-copy corpus (the 'translation' is the reversed source, so
seq2seq models have learnable structure); real files placed in the
cache decode identically.
"""

import io
import os
import tarfile

from . import common

__all__ = ["train", "test", "get_dict", "fetch", "N"]

N = 30  # default synthetic dict size cap (kept from round 1)
START, END, UNK_IDX = "<s>", "<e>", 2
_VOCAB = 60  # w0..; dict line order: <s>, <e>, <unk>, w0, w1, ...
N_TRAIN, N_TEST = 256, 64


def _path():
    return os.path.join(common.DATA_HOME, "wmt14", "wmt14.tgz")


def _dict_lines():
    return ["<s>", "<e>", "<unk>"] + ["w%d" % i for i in range(_VOCAB)]


def _synthetic_pairs(split, n):
    rng = common.rng_for("wmt14", split)
    for _ in range(n):
        l = int(rng.randint(2, 8))
        ids = rng.randint(3, 3 + _VOCAB, l)
        src = " ".join("w%d" % (i - 3) for i in ids)
        trg = " ".join("w%d" % (i - 3) for i in ids[::-1])
        yield "%s\t%s" % (src, trg)


def fetch():
    path = _path()
    if os.path.exists(path):
        return path
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with tarfile.open(tmp, "w:gz") as tf:
        members = {
            "wmt14/src.dict": "\n".join(_dict_lines()) + "\n",
            "wmt14/trg.dict": "\n".join(_dict_lines()) + "\n",
            "wmt14/train/train": "\n".join(
                _synthetic_pairs("train", N_TRAIN)) + "\n",
            "wmt14/test/test": "\n".join(
                _synthetic_pairs("test", N_TEST)) + "\n",
        }
        for name, text in members.items():
            blob = text.encode()
            info = tarfile.TarInfo(name)
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    os.replace(tmp, path)
    return path


def _read_dicts(dict_size):
    path = _path()
    if os.path.exists(path):
        out = []
        with tarfile.open(path) as tf:
            for suffix in ("src.dict", "trg.dict"):
                names = [m.name for m in tf if m.name.endswith(suffix)]
                lines = (
                    tf.extractfile(names[0]).read().decode().splitlines()
                )
                out.append(
                    {w: i for i, w in enumerate(lines[:dict_size])}
                )
        return out[0], out[1]
    d = {w: i for i, w in enumerate(_dict_lines()[:dict_size])}
    return d, dict(d)  # the synthetic corpus shares src/trg vocab


def get_dict(dict_size, reverse=True):
    """(src_dict, trg_dict); reverse=True (the REFERENCE default,
    wmt14.py:159) maps id -> word for decoding beam output."""
    src, trg = _read_dicts(dict_size)
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg


def _pair_lines(split, n):
    path = _path()
    suffix = "train/train" if split == "train" else "test/test"
    if os.path.exists(path):
        with tarfile.open(path) as tf:
            names = [m.name for m in tf if m.name.endswith(suffix)]
            for name in names:
                for line in tf.extractfile(name).read().decode().splitlines():
                    yield line
    else:
        for line in _synthetic_pairs(split, n):
            yield line


def _reader_creator(split, n_items, dict_size):
    def reader():
        src_dict, trg_dict = _read_dicts(dict_size)
        for line in _pair_lines(split, n_items):
            parts = line.strip().split("\t")
            if len(parts) != 2:
                continue
            src_ids = [
                src_dict.get(w, UNK_IDX)
                for w in [START] + parts[0].split() + [END]
            ]
            trg_ids = [trg_dict.get(w, UNK_IDX) for w in parts[1].split()]
            if len(src_ids) > 80 or len(trg_ids) > 80:
                continue
            trg_next = trg_ids + [trg_dict[END]]
            trg_ids = [trg_dict[START]] + trg_ids
            yield src_ids, trg_ids, trg_next

    return reader


def train(dict_size):
    return _reader_creator("train", N_TRAIN, dict_size)


def test(dict_size):
    return _reader_creator("test", N_TEST, dict_size)


def convert(path):
    """Convert the dataset to record files (reference wmt14.convert),
    through the native record writer."""
    dict_size = 30000
    common.convert(path, train(dict_size), 1000, "wmt14_train")
    common.convert(path, test(dict_size), 1000, "wmt14_test")
