"""Movielens reader creators (reference dataset/movielens.py API:
max_user_id/max_movie_id/max_job_id, age_table, movie_categories,
get_movie_title_dict; train/test yield the 8-field rating record)."""

from . import common

__all__ = [
    "train", "test", "max_user_id", "max_movie_id", "max_job_id",
    "age_table", "movie_categories", "get_movie_title_dict",
]

age_table = [1, 18, 25, 35, 45, 50, 56]

_N_USERS, _N_MOVIES, _N_JOBS = 60, 80, 12
_N_CATS, _N_TITLE_WORDS = 10, 100


def max_user_id():
    return _N_USERS - 1


def max_movie_id():
    return _N_MOVIES - 1


def max_job_id():
    return _N_JOBS - 1


def movie_categories():
    return {("cat%d" % i): i for i in range(_N_CATS)}


def get_movie_title_dict():
    return {("t%d" % i): i for i in range(_N_TITLE_WORDS)}


def _reader(split, n):
    def reader():
        rng = common.rng_for("movielens", split)
        for _ in range(n):
            uid = int(rng.randint(1, _N_USERS))
            gender = int(rng.randint(0, 2))
            age = int(rng.randint(0, len(age_table)))
            job = int(rng.randint(0, _N_JOBS))
            mov = int(rng.randint(1, _N_MOVIES))
            cats = list(map(int, rng.randint(0, _N_CATS, rng.randint(1, 4))))
            title = list(map(int, rng.randint(0, _N_TITLE_WORDS, rng.randint(2, 6))))
            score = float(3.0 + 2.0 * ((uid % 2) == (mov % 2)))
            yield uid, gender, age, job, mov, cats, title, [score]

    return reader


def train():
    return _reader("train", 512)


def test():
    return _reader("test", 128)
