"""Movielens ml-1m reader creators (reference dataset/movielens.py:
ml-1m.zip holding ml-1m/{movies,users,ratings}.dat with '::'-separated
fields — movies '(YYYY)' title suffix stripped by regex, category and
title-word dicts built from the corpus, ratings rescaled r*2-5, train/
test split by a seeded random ratio — movielens.py:100-160 semantics).

Each record: [uid, gender(0/1), age_index, job_id, movie_id,
[category ids], [title word ids], [rating]].

fetch() synthesises a REAL-FORMAT zip from the deterministic corpus;
real ml-1m.zip files decode through the same parser.
"""

import os
import random
import re
import zipfile

from . import common

__all__ = [
    "train", "test", "max_user_id", "max_movie_id", "max_job_id",
    "age_table", "movie_categories", "get_movie_title_dict", "fetch",
    "user_info", "movie_info", "convert",
]

age_table = [1, 18, 25, 35, 45, 50, 56]

_N_USERS, _N_MOVIES, _N_JOBS = 60, 80, 12
_CATS = ["Action", "Comedy", "Drama", "Horror", "Romance", "Sci-Fi",
         "Thriller", "War", "Musical", "Mystery"]
_TITLE_POOL = ["the", "of", "night", "day", "return", "story", "last",
               "first", "dark", "light", "lost", "city", "king", "man",
               "woman", "dream", "shadow", "river", "mountain", "sky"]
N_RATINGS = 640
_TEST_RATIO = 0.1  # reference __reader__ default

_META = {}
_SYNTH_CACHE = []


def _path():
    return os.path.join(common.DATA_HOME, "movielens", "ml-1m.zip")


def _synthetic_dats():
    if _SYNTH_CACHE:
        return _SYNTH_CACHE[0]
    rng = common.rng_for("movielens", "corpus")
    movies = []
    for mid in range(1, _N_MOVIES + 1):
        n_words = int(rng.randint(1, 4))
        words = [_TITLE_POOL[rng.randint(len(_TITLE_POOL))]
                 for _ in range(n_words)]
        title = " ".join(w.capitalize() for w in words)
        year = 1970 + int(rng.randint(0, 35))
        cats = sorted({_CATS[rng.randint(len(_CATS))]
                       for _ in range(rng.randint(1, 4))})
        movies.append("%d::%s (%d)::%s" % (mid, title, year, "|".join(cats)))
    users = []
    for uid in range(1, _N_USERS + 1):
        gender = "M" if rng.rand() < 0.5 else "F"
        age = age_table[rng.randint(len(age_table))]
        job = int(rng.randint(0, _N_JOBS))
        users.append("%d::%s::%d::%d::%05d" % (uid, gender, age, job, 10000))
    ratings = []
    for _ in range(N_RATINGS):
        uid = int(rng.randint(1, _N_USERS + 1))
        mid = int(rng.randint(1, _N_MOVIES + 1))
        r = 1 + ((uid % 2) == (mid % 2)) * 3 + int(rng.randint(0, 2))
        ratings.append("%d::%d::%d::%d" % (uid, mid, r, 978300000))
    _SYNTH_CACHE.append((movies, users, ratings))
    return _SYNTH_CACHE[0]


def fetch():
    path = _path()
    if os.path.exists(path):
        return path
    os.makedirs(os.path.dirname(path), exist_ok=True)
    movies, users, ratings = _synthetic_dats()
    tmp = path + ".tmp"
    with zipfile.ZipFile(tmp, "w") as z:
        z.writestr("ml-1m/movies.dat", "\n".join(movies) + "\n")
        z.writestr("ml-1m/users.dat", "\n".join(users) + "\n")
        z.writestr("ml-1m/ratings.dat", "\n".join(ratings) + "\n")
    os.replace(tmp, path)
    return path


def _dat_lines(member):
    path = _path()
    if os.path.exists(path):
        with zipfile.ZipFile(path) as z:
            with z.open("ml-1m/%s" % member) as f:
                for line in f.read().decode("latin1").splitlines():
                    yield line
    else:
        movies, users, ratings = _synthetic_dats()
        for line in {"movies.dat": movies, "users.dat": users,
                     "ratings.dat": ratings}[member]:
            yield line


def _meta():
    """Parse movies.dat/users.dat exactly like the reference
    __initialize_meta_info__ (title year stripped, dicts from corpus)."""
    key = (_path(), os.path.exists(_path()))
    if key in _META:
        return _META[key]
    pattern = re.compile(r"^(.*)\((\d+)\)$")
    movie_info, title_words, cat_set = {}, set(), set()
    for line in _dat_lines("movies.dat"):
        movie_id, title, categories = line.strip().split("::")
        cats = categories.split("|")
        cat_set.update(cats)
        title = pattern.match(title).group(1)
        movie_info[int(movie_id)] = (cats, title)
        for w in title.split():
            title_words.add(w.lower())
    title_dict = {w: i for i, w in enumerate(sorted(title_words))}
    cat_dict = {c: i for i, c in enumerate(sorted(cat_set))}
    user_info = {}
    for line in _dat_lines("users.dat"):
        uid, gender, age, job, _zip = line.strip().split("::")
        user_info[int(uid)] = (
            0 if gender == "M" else 1,
            age_table.index(int(age)),
            int(job),
        )
    _META[key] = (movie_info, title_dict, cat_dict, user_info)
    return _META[key]


def _reader_creator(is_test, rand_seed=0, test_ratio=_TEST_RATIO):
    def reader():
        movie_info, title_dict, cat_dict, user_info = _meta()
        rand = random.Random(x=rand_seed)
        for line in _dat_lines("ratings.dat"):
            if (rand.random() < test_ratio) != is_test:
                continue
            uid, mov_id, rating, _ts = line.strip().split("::")
            uid, mov_id = int(uid), int(mov_id)
            rating = float(rating) * 2 - 5.0
            gender, age, job = user_info[uid]
            cats, title = movie_info[mov_id]
            yield (uid, gender, age, job, mov_id,
                   [cat_dict[c] for c in cats],
                   [title_dict[w.lower()] for w in title.split()],
                   [rating])

    return reader


def train():
    return _reader_creator(is_test=False)


def test():
    return _reader_creator(is_test=True)


def max_user_id():
    return max(_meta()[3])


def max_movie_id():
    return max(_meta()[0])


def max_job_id():
    return max(j for _, _, j in _meta()[3].values())


def movie_categories():
    return dict(_meta()[2])


def get_movie_title_dict():
    return dict(_meta()[1])


def user_info():
    """{uid: (gender01, age_index, job)} (reference user_info returns
    UserInfo objects; the tuple carries the same .value() fields)."""
    return dict(_meta()[3])


def movie_info():
    """{movie_id: (categories, title)} (reference movie_info)."""
    return dict(_meta()[0])


def convert(path):
    common.convert(path, train(), 256, "movielens_train")
    common.convert(path, test(), 256, "movielens_test")
