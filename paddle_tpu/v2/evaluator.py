"""paddle.v2.evaluator: metric layers attached via SGD(extra_layers=...)
(reference python/paddle/v2/evaluator.py auto-wrapping
trainer_config_helpers/evaluators.py; e.g. classification_error_evaluator
at evaluators.py:220).

Each evaluator is a lazy DSL Layer that Topology lowers to a fluid metric
op; trainer.SGD fetches it per batch and delivers the value in the
event.evaluator payload keyed by the evaluator's name — matching the
reference book-style `event.evaluator` access pattern.
"""

from __future__ import annotations

from .layer import Layer, _as_list

__all__ = ["classification_error", "auc", "sum", "column_sum"]


def classification_error(input, label, name=None, top_k=1, **kwargs):
    """Fraction of mis-classified instances in the batch (reference
    classification_error_evaluator)."""
    return Layer("classification_error_evaluator", name,
                 _as_list(input) + _as_list(label), {"top_k": top_k})


def auc(input, label, name=None, **kwargs):
    """Area under the ROC curve over the batch (reference auc_evaluator)."""
    return Layer("auc_evaluator", name, _as_list(input) + _as_list(label), {})


def sum(input, name=None, **kwargs):  # noqa: A001 - reference name
    """Sum of the input over the batch (reference sum_evaluator)."""
    return Layer("sum_evaluator", name, _as_list(input), {})


def column_sum(input, name=None, **kwargs):
    """Per-column sum of the input (reference column_sum_evaluator)."""
    return Layer("column_sum_evaluator", name, _as_list(input), {})


def precision_recall(input, label, positive_label=None, name=None,
                     **kwargs):
    """Macro F1 (or the positive class's F1) over the batch (reference
    precision_recall_evaluator)."""
    return Layer("precision_recall_evaluator", name,
                 _as_list(input) + _as_list(label),
                 {"positive_label": positive_label})


def ctc_error(input, label, name=None, **kwargs):
    """Normalised edit distance of the CTC greedy decode (reference
    ctc_error_evaluator)."""
    return Layer("ctc_error_evaluator", name,
                 _as_list(input) + _as_list(label), {})


def chunk(input, label, chunk_scheme, num_chunk_types, name=None,
          excluded_chunk_types=None, **kwargs):
    """Chunking F1 (reference chunk_evaluator)."""
    return Layer("chunk_evaluator", name,
                 _as_list(input) + _as_list(label), {
                     "chunk_scheme": chunk_scheme,
                     "num_chunk_types": num_chunk_types,
                     "excluded_chunk_types": excluded_chunk_types,
                 })


def detection_map(input, label, overlap_threshold=0.5, num_classes=None,
                  name=None, **kwargs):
    """Per-batch VOC mAP (reference detection_map_evaluator)."""
    return Layer("detection_map_evaluator", name,
                 _as_list(input) + _as_list(label), {
                     "overlap_threshold": overlap_threshold,
                     "background_id": 0, "num_classes": num_classes,
                 })


__all__ += ["precision_recall", "ctc_error", "chunk", "detection_map"]
