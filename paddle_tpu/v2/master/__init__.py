"""paddle.v2.master.client shim (reference python/paddle/v2/master/
client.py:29 wrapping the Go master via a cgo shim). Backed by the
in-process Coordinator (paddle_tpu.distributed) — same task-lease
semantics, no etcd/Go."""

from __future__ import annotations

import pickle
from typing import List, Optional

from ...distributed import Coordinator

__all__ = ["client"]


class client(object):
    """API-shaped like the reference: set_dataset(paths), next_record().

    `etcd_endpoints` of the form "host:port" connects to a Coordinator
    service (distributed/coordinator.py RemoteCoordinator) so multiple
    workers share one task queue; anything else gets a private
    in-process Coordinator (single-worker / tests)."""

    def __init__(self, etcd_endpoints=None, timeout_sec=60, buf_size=32):
        addr = etcd_endpoints if isinstance(etcd_endpoints, str) else None
        if addr and ":" in addr.rsplit("/", 1)[-1]:
            from ...distributed.coordinator import RemoteCoordinator

            self._coordinator = RemoteCoordinator(
                addr.rsplit("/", 1)[-1], timeout_s=timeout_sec
            )
        else:
            self._coordinator = Coordinator(timeout_s=timeout_sec)
        self._iter = None
        self._pass = 0

    def set_dataset(self, paths: List[str]):
        self._coordinator.set_dataset(list(paths))

    def _records(self):
        # the offset-aware lease loop (skip records a previous holder
        # already delivered, report the offset + fencing token on
        # failure) lives in ONE place: MasterClient. task_failed used to
        # re-lease the WHOLE chunk, replaying every record delivered
        # before the error.
        from ..reader import creator
        from ...distributed.coordinator import MasterClient

        return iter(MasterClient(
            self._coordinator,
            lambda payload: creator.recordio([payload])(),
            epoch_limit=self._pass,
        ))

    def next_record(self) -> Optional[bytes]:
        """One raw record, None at pass end (reference returns (r, err));
        the next call after a pass end starts the NEXT pass (epoch
        rollover in the coordinator's queue)."""
        if self._iter is None:
            self._iter = self._records()
        try:
            return next(self._iter)
        except StopIteration:
            self._iter = None
            self._pass += 1
            return None

    def paddle_start_get_records(self, pass_id):
        self._pass = int(pass_id)
        self._iter = self._records()
