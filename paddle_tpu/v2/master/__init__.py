"""paddle.v2.master.client shim (reference python/paddle/v2/master/
client.py:29 wrapping the Go master via a cgo shim). Backed by the
in-process Coordinator (paddle_tpu.distributed) — same task-lease
semantics, no etcd/Go."""

from __future__ import annotations

import pickle
from typing import List, Optional

from ...distributed import Coordinator

__all__ = ["client"]


class client(object):
    """API-shaped like the reference: set_dataset(paths), next_record()."""

    def __init__(self, etcd_endpoints=None, timeout_sec=60, buf_size=32):
        self._coordinator = Coordinator(timeout_s=timeout_sec)
        self._iter = None

    def set_dataset(self, paths: List[str]):
        self._coordinator.set_dataset(list(paths))

    def _records(self):
        from ..reader import creator

        while True:
            task = self._coordinator.get_task()
            if task is None:
                return
            try:
                for rec in creator.recordio([task.payload])():
                    yield rec
            except Exception:
                self._coordinator.task_failed(task.task_id)
                continue
            self._coordinator.task_finished(task.task_id)

    def next_record(self) -> Optional[bytes]:
        """One raw record, None at pass end (reference returns (r, err))."""
        if self._iter is None:
            self._iter = self._records()
        try:
            return next(self._iter)
        except StopIteration:
            self._iter = None
            return None

    def paddle_start_get_records(self, pass_id):
        self._iter = self._records()
