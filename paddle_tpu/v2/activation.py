"""Activation objects (reference paddle.v2.activation / trainer_config_
helpers.activations). Each instance names the fluid activation to apply."""

__all__ = [
    "Tanh", "Sigmoid", "Softmax", "Identity", "Linear", "Relu", "BRelu",
    "SoftRelu", "STanh", "Abs", "Square", "Exp", "Log", "SquareRootN",
    "Reciprocal",
]


class BaseActivation(object):
    name = None

    def __repr__(self):
        return "activation.%s" % type(self).__name__


def _make(cls_name, act_name):
    cls = type(cls_name, (BaseActivation,), {"name": act_name})
    return cls


Tanh = _make("Tanh", "tanh")
Sigmoid = _make("Sigmoid", "sigmoid")
Softmax = _make("Softmax", "softmax")
Identity = _make("Identity", None)
Linear = Identity
Relu = _make("Relu", "relu")
BRelu = _make("BRelu", "brelu")
SoftRelu = _make("SoftRelu", "soft_relu")
STanh = _make("STanh", "stanh")
Abs = _make("Abs", "abs")
Square = _make("Square", "square")
Exp = _make("Exp", "exp")
Log = _make("Log", "log")
Reciprocal = _make("Reciprocal", "reciprocal")
SquareRootN = _make("SquareRootN", "sqrt")
