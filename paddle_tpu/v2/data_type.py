"""Input type descriptors (reference python/paddle/trainer/
PyDataProvider2.py InputType re-exported as paddle.v2.data_type)."""

__all__ = [
    "InputType",
    "DataType",
    "dense_vector",
    "dense_vector_sequence",
    "integer_value",
    "integer_value_sequence",
    "sparse_binary_vector",
    "sparse_float_vector",
]


class DataType(object):
    Dense = 0
    SparseNonValue = 1
    SparseValue = 2
    Index = 3


class SeqType(object):
    NO_SEQUENCE = 0
    SEQUENCE = 1
    SUB_SEQUENCE = 2


class InputType(object):
    def __init__(self, dim, seq_type, tp):
        self.dim = dim
        self.seq_type = seq_type
        self.type = tp


def dense_vector(dim, seq_type=SeqType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.Dense)


def dense_vector_sequence(dim):
    return dense_vector(dim, SeqType.SEQUENCE)


def integer_value(value_range, seq_type=SeqType.NO_SEQUENCE):
    return InputType(value_range, seq_type, DataType.Index)


def integer_value_sequence(value_range):
    return integer_value(value_range, SeqType.SEQUENCE)


def sparse_binary_vector(dim, seq_type=SeqType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.SparseNonValue)


def sparse_float_vector(dim, seq_type=SeqType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.SparseValue)
