"""v2 optimizer wrappers mapping onto fluid optimizers (reference
python/paddle/v2/optimizer.py wraps the C++ ParameterUpdater family;
SURVEY.md N4/N7 — on TPU every update strategy collapses to the sharded
in-graph optimizer step)."""

from __future__ import annotations

from .. import fluid

__all__ = ["Momentum", "Adam", "Adamax", "AdaGrad", "DecayedAdaGrad",
           "AdaDelta", "RMSProp", "SGD"]


class Optimizer(object):
    # settings-objects shared across the update equations: v2 configs
    # pass model_average=ModelAverage(...) / regularization through the
    # optimizer ctor (reference v2/optimizer.py kwargs)
    model_average = None

    def _capture(self, kwargs):
        self.model_average = kwargs.get("model_average")

    def _fluid(self):
        raise NotImplementedError


class SGD(Optimizer):
    def __init__(self, learning_rate=1e-3, **kwargs):
        self._capture(kwargs)
        self.learning_rate = learning_rate

    def _fluid(self):
        return fluid.optimizer.SGD(learning_rate=self.learning_rate)


class Momentum(Optimizer):
    def __init__(self, momentum=0.9, learning_rate=1e-3, sparse=False, **kwargs):
        self._capture(kwargs)
        self.momentum = momentum
        self.learning_rate = learning_rate

    def _fluid(self):
        return fluid.optimizer.Momentum(
            learning_rate=self.learning_rate, momentum=self.momentum
        )


class Adam(Optimizer):
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 learning_rate=1e-3, **kwargs):
        self._capture(kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.learning_rate = learning_rate

    def _fluid(self):
        return fluid.optimizer.Adam(
            learning_rate=self.learning_rate, beta1=self.beta1,
            beta2=self.beta2, epsilon=self.epsilon,
        )


class Adamax(Optimizer):
    def __init__(self, beta1=0.9, beta2=0.999, learning_rate=1e-3, **kwargs):
        self._capture(kwargs)
        self.beta1, self.beta2 = beta1, beta2
        self.learning_rate = learning_rate

    def _fluid(self):
        return fluid.optimizer.Adamax(
            learning_rate=self.learning_rate, beta1=self.beta1, beta2=self.beta2
        )


class AdaGrad(Optimizer):
    def __init__(self, learning_rate=1e-3, epsilon=1e-6, **kwargs):
        self._capture(kwargs)
        self.learning_rate, self.epsilon = learning_rate, epsilon

    def _fluid(self):
        return fluid.optimizer.Adagrad(
            learning_rate=self.learning_rate, epsilon=self.epsilon
        )


class DecayedAdaGrad(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, learning_rate=1e-3, **kwargs):
        self._capture(kwargs)
        self.rho, self.epsilon = rho, epsilon
        self.learning_rate = learning_rate

    def _fluid(self):
        return fluid.optimizer.DecayedAdagrad(
            learning_rate=self.learning_rate, decay=self.rho,
            epsilon=self.epsilon,
        )


class AdaDelta(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, learning_rate=1e-3, **kwargs):
        self._capture(kwargs)
        self.rho, self.epsilon = rho, epsilon
        self.learning_rate = learning_rate

    def _fluid(self):
        return fluid.optimizer.Adadelta(
            learning_rate=self.learning_rate, rho=self.rho,
            epsilon=self.epsilon,
        )


class RMSProp(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, learning_rate=1e-3, **kwargs):
        self._capture(kwargs)
        self.rho, self.epsilon = rho, epsilon
        self.learning_rate = learning_rate

    def _fluid(self):
        return fluid.optimizer.RMSProp(
            learning_rate=self.learning_rate, rho=self.rho,
            epsilon=self.epsilon,
        )


# settings-objects shared with the legacy DSL (reference v2/optimizer.py
# aliases the trainer_config_helpers implementations the same way)
from ..trainer_config_helpers import (  # noqa: E402,F401
    L2Regularization,
    ModelAverage,
)

__all__ += ["ModelAverage", "L2Regularization"]
