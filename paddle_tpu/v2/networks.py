"""paddle.v2.networks (reference python/paddle/v2/networks.py): the
composite network helpers, shared with the config DSL
(trainer_config_helpers/networks.py)."""

from ..trainer_config_helpers.networks import *  # noqa: F401,F403
from ..trainer_config_helpers import networks as _n

__all__ = list(_n.__all__)
