"""paddle.v2.data_feeder (reference python/paddle/v2/data_feeder.py,
wrapping py_paddle's DataProviderConverter): instance tuples -> the
executor's feed dict, slot order given by `feeding`."""

from __future__ import annotations

from .trainer import _convert_feed

__all__ = ["DataFeeder"]


class DataFeeder(object):
    def __init__(self, data_types, feeding=None):
        """data_types: [(name, data_type), ...] in provider slot order
        (the reference's constructor signature)."""
        from .layer import Layer

        self._nodes = []
        for name, t in data_types:
            node = Layer.__new__(Layer)
            node.kind = "data"
            node.name = name
            node.parents = []
            node.attrs = {"type": t}
            self._nodes.append(node)
        self._feeding = feeding

    def convert(self, dat, argument=None):
        return _convert_feed(dat, self._nodes, self._feeding)

    __call__ = convert
