"""paddle.v2.attr (reference python/paddle/v2/attr.py): parameter /
extra-layer attribute classes, shared with the config DSL."""

from ..trainer_config_helpers import (  # noqa: F401
    ExtraAttr,
    ExtraLayerAttribute,
    HookAttr,
    HookAttribute,
    ParamAttr,
    ParameterAttribute,
)

Param = ParamAttr
Extra = ExtraAttr
Hook = HookAttr

__all__ = ["Param", "Extra", "Hook", "ParamAttr", "ExtraAttr",
           "ParameterAttribute", "ExtraLayerAttribute", "HookAttr",
           "HookAttribute"]
