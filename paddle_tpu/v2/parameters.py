"""Parameters: a name -> array pool shared across topologies (reference
python/paddle/v2/parameters.py backed by SWIG GradientMachine args; here
backed by a fluid Scope, with tar serialization kept API-compatible)."""

from __future__ import annotations

import io
import struct
import tarfile

import numpy as np

from .. import fluid
from .topology import Topology

__all__ = ["Parameters", "create"]


# --- reference-compatible wire helpers -------------------------------------
# The reference tar layout (python/paddle/v2/parameters.py:306,328-384) is,
# per parameter: a member `<name>` holding struct.pack('IIQ', 0, 4, size)
# followed by raw little-endian float32 bytes, plus a member
# `<name>.protobuf` holding a serialized paddle.ParameterConfig
# (proto/ParameterConfig.proto: name=1 string, size=2 uint64,
# dims=9 repeated uint64). We hand-encode/decode exactly those three
# fields so tars interoperate without a protobuf dependency.


def _varint(n: int) -> bytes:
    out = b""
    n = int(n)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _encode_parameter_config(name: str, shape) -> bytes:
    size = int(np.prod(shape)) if len(shape) else 1
    raw = name.encode("utf-8")
    msg = b"\x0a" + _varint(len(raw)) + raw  # field 1: name (len-delimited)
    msg += b"\x10" + _varint(size)  # field 2: size (varint)
    for d in shape:
        msg += b"\x48" + _varint(int(d))  # field 9: dims (varint, repeated)
    return msg


def _decode_parameter_config(data: bytes):
    """Minimal proto2 reader: returns (name, size, dims), skipping unknown
    fields (a reference-produced config carries many optional scalars)."""
    name, size, dims = None, None, []
    i, n = 0, len(data)

    def read_varint(i):
        shift, val = 0, 0
        while True:
            b = data[i]
            val |= (b & 0x7F) << shift
            i += 1
            if not b & 0x80:
                return val, i
            shift += 7

    while i < n:
        tag, i = read_varint(i)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, i = read_varint(i)
            if field == 2:
                size = val
            elif field == 9:
                dims.append(val)
        elif wire == 2:
            ln, i = read_varint(i)
            payload = data[i : i + ln]
            i += ln
            if field == 1:
                name = payload.decode("utf-8")
        elif wire == 1:
            i += 8
        elif wire == 5:
            i += 4
        else:  # groups (3/4) never appear in ParameterConfig
            break
    return name, size, dims


def write_tar_param(tar, name, arr):
    """One parameter into an open tar in the v2 wire layout (the single
    writer — Parameters.to_tar and utils/torch2paddle both call this)."""
    flat = np.ascontiguousarray(arr, dtype="<f4")
    data = struct.pack("IIQ", 0, 4, flat.size) + flat.tobytes()
    info = tarfile.TarInfo(name=name)
    info.size = len(data)
    tar.addfile(info, io.BytesIO(data))
    conf = _encode_parameter_config(name, np.asarray(arr).shape)
    info = tarfile.TarInfo(name="%s.protobuf" % name)
    info.size = len(conf)
    tar.addfile(info, io.BytesIO(conf))


class Parameters(object):
    def __init__(self, topology: Topology):
        self.topology = topology
        self.scope = fluid.executor.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.executor.scope_guard(self.scope):
            exe.run(topology.startup_program)
        # track ALL persistables, not just Parameters: batch_norm running
        # mean/variance must survive to_tar/init_from_tar and infer()
        self._param_names = sorted(
            v.name
            for v in topology.main_program.list_vars()
            if v.persistable and v.name in self.scope
        )

    # --- dict-ish surface (reference parameters.py) --------------------
    def keys(self):
        return list(self._param_names)

    def names(self):
        return self.keys()

    def has_key(self, key):
        return key in self._param_names

    def __contains__(self, key):
        return key in self._param_names

    def __iter__(self):
        return iter(self._param_names)

    def __getitem__(self, key):
        return np.asarray(self.scope.get(key))

    def get(self, parameter_name):
        return self[parameter_name]

    def __setitem__(self, key, value):
        value = np.asarray(value, np.float32)
        self.scope.set(key, value)

    def set(self, parameter_name, value):
        self[parameter_name] = value

    def get_shape(self, key):
        return tuple(np.asarray(self.scope.get(key)).shape)

    # --- tar round trip -------------------------------------------------
    def serialize(self, name, f):
        """Reference wire layout (parameters.py:306): 16-byte
        struct.pack('IIQ', version=0, value_size=4, num_elements) header
        followed by raw little-endian float32 bytes."""
        arr = np.ascontiguousarray(self[name], dtype="<f4")
        f.write(struct.pack("IIQ", 0, 4, arr.size))
        f.write(arr.tobytes())

    def deserialize(self, name, f):
        f.read(16)  # header
        arr = np.frombuffer(f.read(), dtype="<f4")
        self.set(name, arr.reshape(self.get_shape(name)))

    def to_tar(self, f):
        """Write the reference v2 model-file layout: per parameter a raw
        `<name>` member (see serialize) and a `<name>.protobuf`
        ParameterConfig member — interoperable with reference-produced
        tars for the name/size/dims fields this framework uses."""
        with tarfile.open(fileobj=f, mode="w") as tar:
            for name in self._param_names:
                write_tar_param(tar, name, self[name])

    @staticmethod
    def from_tar(f):
        """Build a Parameters-like object from a model tar (no topology
        needed — shapes come from each ParameterConfig's dims, falling
        back to flat when absent)."""
        params = Parameters.__new__(Parameters)
        params.topology = None
        params.scope = fluid.executor.Scope()
        params._param_names = []
        shapes = {}
        blobs = {}
        with tarfile.open(fileobj=f, mode="r") as tar:
            for m in tar.getmembers():
                data = tar.extractfile(m).read()
                if m.name.endswith(".protobuf"):
                    name, size, dims = _decode_parameter_config(data)
                    if name is not None and dims:
                        shapes[name] = tuple(int(d) for d in dims)
                elif data[:6] == b"\x93NUMPY":  # pre-r2 .npy tars
                    blobs[m.name] = np.load(io.BytesIO(data))
                else:
                    blobs[m.name] = np.frombuffer(data[16:], dtype="<f4")
        for name in sorted(blobs):
            arr = blobs[name]
            if name in shapes and arr.ndim == 1:
                arr = arr.reshape(shapes[name])
            params._param_names.append(name)
            params.scope.set(name, np.asarray(arr, np.float32))
        return params

    def init_from_tar(self, f, exclude_params=()):
        tar_params = Parameters.from_tar(f)
        for name in tar_params.names():
            if name in self._param_names and name not in exclude_params:
                arr = tar_params.get(name)
                self.set(name, np.asarray(arr).reshape(self.get_shape(name)))


def create(*layers):
    """paddle.parameters.create(cost): build the topology and initialize
    its parameters."""
    return Parameters(Topology(list(layers)))
