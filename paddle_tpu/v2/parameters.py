"""Parameters: a name -> array pool shared across topologies (reference
python/paddle/v2/parameters.py backed by SWIG GradientMachine args; here
backed by a fluid Scope, with tar serialization kept API-compatible)."""

from __future__ import annotations

import io
import tarfile

import numpy as np

from .. import fluid
from .topology import Topology

__all__ = ["Parameters", "create"]


class Parameters(object):
    def __init__(self, topology: Topology):
        self.topology = topology
        self.scope = fluid.executor.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.executor.scope_guard(self.scope):
            exe.run(topology.startup_program)
        # track ALL persistables, not just Parameters: batch_norm running
        # mean/variance must survive to_tar/init_from_tar and infer()
        self._param_names = sorted(
            v.name
            for v in topology.main_program.list_vars()
            if v.persistable and v.name in self.scope
        )

    # --- dict-ish surface (reference parameters.py) --------------------
    def keys(self):
        return list(self._param_names)

    def names(self):
        return self.keys()

    def has_key(self, key):
        return key in self._param_names

    def __contains__(self, key):
        return key in self._param_names

    def __iter__(self):
        return iter(self._param_names)

    def __getitem__(self, key):
        return np.asarray(self.scope.get(key))

    def get(self, parameter_name):
        return self[parameter_name]

    def __setitem__(self, key, value):
        value = np.asarray(value, np.float32)
        self.scope.set(key, value)

    def set(self, parameter_name, value):
        self[parameter_name] = value

    def get_shape(self, key):
        return tuple(np.asarray(self.scope.get(key)).shape)

    # --- tar round trip -------------------------------------------------
    def to_tar(self, f):
        with tarfile.open(fileobj=f, mode="w") as tar:
            for name in self._param_names:
                arr = self[name]
                buf = io.BytesIO()
                np.save(buf, arr)
                data = buf.getvalue()
                info = tarfile.TarInfo(name=name)
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))

    @staticmethod
    def from_tar(f):
        """Returns {name: array}; use init_from_tar to load into an
        existing Parameters."""
        out = {}
        with tarfile.open(fileobj=f, mode="r") as tar:
            for m in tar.getmembers():
                buf = io.BytesIO(tar.extractfile(m).read())
                out[m.name] = np.load(buf)
        return out

    def init_from_tar(self, f):
        for name, arr in Parameters.from_tar(f).items():
            if name in self._param_names:
                self.set(name, arr)


def create(*layers):
    """paddle.parameters.create(cost): build the topology and initialize
    its parameters."""
    return Parameters(Topology(list(layers)))
