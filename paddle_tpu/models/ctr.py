"""CTR model family over the sparse/embedding path: Wide&Deep and DeepFM.

SURVEY.md §7.2 step 7 names a DeepFM/Wide&Deep config as the acceptance
workload for the sparse path (the reference serves this class of model
through row-sharded sparse pserver parameters, SparseRemoteParameterUpdater,
RemoteParameterUpdater.h:265 + SelectedRows). Here the graph is ordinary
fluid layers; the big per-field tables are plain `layers.embedding`
parameters, and scaling them across chips is one
`shard_parameter(table, P('model', None))` annotation — the executor
row-shards the table and XLA inserts the gather collectives, replacing
the pserver prefetch protocol (tests/test_ctr_models.py proves mesh ==
single-device).

Both builders take integer feature-id inputs shaped [B, num_fields]
(one id per field, the classic Criteo-style layout) plus an optional
dense feature vector.
"""

from __future__ import annotations

import paddle_tpu.fluid as fluid

__all__ = ["wide_deep", "deepfm"]


def _linear_term(ids, num_fields, vocab, table_name):
    """Per-id scalar weights summed over fields ([B, F] ids -> [B, 1]):
    the 'wide' linear model / FM first-order term — an embed_dim=1
    table."""
    w = fluid.layers.embedding(
        input=ids,
        size=[vocab, 1],
        param_attr=fluid.ParamAttr(name=table_name),
    )  # [B, F, 1]
    return fluid.layers.reduce_sum(
        fluid.layers.reshape(w, shape=[-1, num_fields]),
        dim=1, keep_dim=True,
    )


def _field_embeddings(ids, num_fields, vocab, dim, prefix):
    """Per-field embedding lookup: ids [B, F] int64 -> [B, F*dim] concat.
    One shared [vocab, dim] table per field group keeps the parameter
    count honest (fields index disjoint id ranges, as in Criteo
    preprocessing)."""
    emb = fluid.layers.embedding(
        input=ids,
        size=[vocab, dim],
        param_attr=fluid.ParamAttr(name="%s_table" % prefix),
    )
    # embedding of [B, F] ids -> [B, F, dim]; flatten the field axis
    return fluid.layers.reshape(emb, shape=[-1, num_fields * dim]), emb


def wide_deep(sparse_ids, label, num_fields, vocab, embed_dim=16,
              deep_dims=(128, 64), dense_input=None):
    """Wide&Deep (Cheng et al. 2016, the canonical pserver-era CTR
    model). Wide: a linear model over the raw ids (an embed_dim=1
    table = per-id weight). Deep: field embeddings -> MLP. Output:
    sigmoid(wide + deep); loss: mean logistic loss.

    Returns (loss, prob)."""
    # ---- wide: linear model over the raw ids
    wide = _linear_term(sparse_ids, num_fields, vocab, "wide_table")

    # ---- deep: embeddings -> MLP
    deep, _ = _field_embeddings(sparse_ids, num_fields, vocab, embed_dim,
                                "deep")
    if dense_input is not None:
        deep = fluid.layers.concat([deep, dense_input], axis=1)
    for i, width in enumerate(deep_dims):
        deep = fluid.layers.fc(input=deep, size=width, act="relu",
                               param_attr=fluid.ParamAttr(
                                   name="deep_fc%d_w" % i))
    deep_out = fluid.layers.fc(input=deep, size=1, act=None,
                               param_attr=fluid.ParamAttr(name="deep_out_w"))

    logit = fluid.layers.elementwise_add(x=wide, y=deep_out)
    loss = fluid.layers.mean(
        x=fluid.layers.sigmoid_cross_entropy_with_logits(
            x=logit, label=label))
    prob = fluid.layers.sigmoid(logit)
    return loss, prob


def deepfm(sparse_ids, label, num_fields, vocab, embed_dim=16,
           deep_dims=(128, 64), dense_input=None):
    """DeepFM (Guo et al. 2017): shared field embeddings feed BOTH the
    FM second-order interaction term and the deep MLP; plus a first-order
    per-id weight. FM pairwise sum uses the sum-square identity
    0.5 * sum_d[(Σ_f e_fd)² - Σ_f e_fd²] — one elementwise fusion on
    TPU instead of F² pairwise products.

    Returns (loss, prob)."""
    # first-order term
    first = _linear_term(sparse_ids, num_fields, vocab, "fm_w_table")

    flat, emb = _field_embeddings(sparse_ids, num_fields, vocab, embed_dim,
                                  "fm")
    # second-order: emb [B, F, D]
    sum_f = fluid.layers.reduce_sum(emb, dim=1)            # [B, D]
    sum_sq = fluid.layers.square(sum_f)                    # (Σe)²
    sq_sum = fluid.layers.reduce_sum(
        fluid.layers.square(emb), dim=1)                   # Σe²
    second = fluid.layers.scale(
        x=fluid.layers.reduce_sum(
            fluid.layers.elementwise_sub(x=sum_sq, y=sq_sum),
            dim=1, keep_dim=True),
        scale=0.5,
    )  # [B, 1]

    deep = flat
    if dense_input is not None:
        deep = fluid.layers.concat([deep, dense_input], axis=1)
    for i, width in enumerate(deep_dims):
        deep = fluid.layers.fc(input=deep, size=width, act="relu",
                               param_attr=fluid.ParamAttr(
                                   name="dfm_fc%d_w" % i))
    deep_out = fluid.layers.fc(input=deep, size=1, act=None,
                               param_attr=fluid.ParamAttr(name="dfm_out_w"))

    logit = fluid.layers.elementwise_add(
        x=fluid.layers.elementwise_add(x=first, y=second), y=deep_out)
    loss = fluid.layers.mean(
        x=fluid.layers.sigmoid_cross_entropy_with_logits(
            x=logit, label=label))
    prob = fluid.layers.sigmoid(logit)
    return loss, prob
