"""CRNN OCR model (reference-era OCR capability: conv feature extractor
-> columns as a sequence -> bidirectional recurrent encoder -> CTC.
The reference served this with WarpCTCLayer + im2sequence
(gserver/layers/WarpCTCLayer.cpp, operators/im2sequence_op.cc); here the
same graph compiles to one XLA program — im2sequence emits the LoD
side-band, the GRUs run as masked scans, CTC is the native log-space
kernel."""

from __future__ import annotations

from ..fluid import layers

__all__ = ["crnn_ctc", "ctc_infer", "greedy_decode"]


def _conv_pool(input, filters, channels):
    y = layers.conv2d(
        input=input, num_filters=filters, filter_size=3, padding=1,
        num_channels=channels, act="relu",
    )
    return layers.pool2d(input=y, pool_size=2, pool_stride=2)


def _encode(images, num_classes, hidden=48):
    """images [N, 1, H, W] -> per-column class logits (packed sequence
    rows with LoD) sized num_classes+1 (CTC blank is the last id)."""
    y = _conv_pool(images, 16, int(images.shape[1]))
    y = _conv_pool(y, 32, 16)
    h = int(y.shape[2])
    # every output column = one time step: kernel spans the full height
    seq = layers.im2sequence(y, filter_size=[h, 1], stride=[1, 1])
    fc = layers.fc(input=seq, size=hidden, act="relu")
    fwd = layers.dynamic_gru(input=layers.fc(input=fc, size=hidden * 3),
                             size=hidden)
    bwd = layers.dynamic_gru(input=layers.fc(input=fc, size=hidden * 3),
                             size=hidden, is_reverse=True)
    both = layers.concat([fwd, bwd], axis=1)
    return layers.fc(input=both, size=num_classes + 1)


def crnn_ctc(images, label, num_classes, hidden=48):
    """Training head: mean CTC loss over the batch. `label` is the
    packed int sequence [sum_len, 1] with its LoD."""
    logits = _encode(images, num_classes, hidden)
    cost = layers.warpctc(input=logits, label=label, blank=num_classes)
    return layers.mean(x=cost), logits


def greedy_decode(logits, num_classes):
    """Greedy CTC decode of `logits` (merge repeats, drop blanks).
    Build this in the SAME program as crnn_ctc and clone(for_test=True)
    before minimize() so serving shares the trained weights."""
    return layers.ctc_greedy_decoder(
        layers.softmax(logits), blank=num_classes
    )


def ctc_infer(images, num_classes, hidden=48):
    """Standalone serving graph (fresh parameters — load them via
    io.load_inference_model / parameter files)."""
    logits = _encode(images, num_classes, hidden)
    return greedy_decode(logits, num_classes)
