"""ResNet for ImageNet and CIFAR-10.

Parity: benchmark/paddle/image/resnet.py (the north-star ResNet-50
workload, BASELINE.md) and the book image_classification resnet_cifar10.
Bottleneck-v1 topology, NCHW, batch-norm after every conv.
"""

from __future__ import annotations

from ..fluid import layers

__all__ = ["resnet_imagenet", "resnet_cifar10"]


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu"):
    conv = layers.conv2d(
        input=input,
        num_filters=ch_out,
        filter_size=filter_size,
        stride=stride,
        padding=padding,
        act=None,
        bias_attr=False,
    )
    return layers.batch_norm(input=conv, act=act)


def shortcut(input, ch_in, ch_out, stride):
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None)
    return input


def basicblock(input, ch_in, ch_out, stride):
    short = shortcut(input, ch_in, ch_out, stride)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None)
    return layers.elementwise_add(x=short, y=conv2, act="relu")


def bottleneck(input, ch_in, ch_out, stride):
    short = shortcut(input, ch_in, ch_out * 4, stride)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None)
    return layers.elementwise_add(x=short, y=conv3, act="relu")


def layer_warp(block_func, input, ch_in, ch_out, count, stride):
    res_out = block_func(input, ch_in, ch_out, stride)
    ch_in = ch_out * 4 if block_func is bottleneck else ch_out
    for i in range(1, count):
        res_out = block_func(res_out, ch_in, ch_out, 1)
    return res_out


_IMAGENET_CFG = {
    18: (basicblock, [2, 2, 2, 2]),
    34: (basicblock, [3, 4, 6, 3]),
    50: (bottleneck, [3, 4, 6, 3]),
    101: (bottleneck, [3, 4, 23, 3]),
    152: (bottleneck, [3, 8, 36, 3]),
}


def resnet_imagenet(input, class_dim=1000, depth=50):
    """ResNet-{18,34,50,101,152} (benchmark/paddle/image/resnet.py layout)."""
    if depth not in _IMAGENET_CFG:
        raise ValueError("unsupported resnet depth %d" % depth)
    block_func, counts = _IMAGENET_CFG[depth]
    conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2, padding=3)
    pool1 = layers.pool2d(
        input=conv1, pool_size=3, pool_stride=2, pool_padding=1, pool_type="max"
    )
    ch_in = 64
    res = pool1
    for i, (count, ch_out) in enumerate(zip(counts, [64, 128, 256, 512])):
        stride = 1 if i == 0 else 2
        res = layer_warp(block_func, res, ch_in, ch_out, count, stride)
        ch_in = ch_out * 4 if block_func is bottleneck else ch_out
    pool2 = layers.pool2d(input=res, pool_size=7, pool_type="avg", global_pooling=True)
    return layers.fc(input=pool2, size=class_dim, act="softmax")


def resnet_cifar10(input, class_dim=10, depth=32):
    """CIFAR ResNet (book image_classification resnet_cifar10)."""
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input, ch_out=16, filter_size=3, stride=1, padding=1)
    res1 = layer_warp(basicblock, conv1, 16, 16, n, 1)
    res2 = layer_warp(basicblock, res1, 16, 32, n, 2)
    res3 = layer_warp(basicblock, res2, 32, 64, n, 2)
    pool = layers.pool2d(input=res3, pool_size=8, pool_type="avg", pool_stride=1)
    return layers.fc(input=pool, size=class_dim, act="softmax")
