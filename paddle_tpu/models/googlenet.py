"""GoogLeNet v1 (reference benchmark/paddle/image/googlenet.py — BASELINE
1149 ms/batch at bs=128 on K40m; Inception-v1 topology with LRN, no BN).

The two auxiliary softmax heads of the original paper are omitted, matching
the reference benchmark config (it trains the main head only).
"""

from __future__ import annotations

from ..fluid import layers

__all__ = ["googlenet"]


def _conv(input, num_filters, filter_size, stride=1, padding=0):
    return layers.conv2d(
        input=input,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=padding,
        act="relu",
    )


def inception(input, nf1, nf3r, nf3, nf5r, nf5, proj):
    t1 = _conv(input, nf1, 1)
    t3 = _conv(_conv(input, nf3r, 1), nf3, 3, padding=1)
    t5 = _conv(_conv(input, nf5r, 1), nf5, 5, padding=2)
    tp = layers.pool2d(
        input=input, pool_size=3, pool_stride=1, pool_padding=1, pool_type="max"
    )
    tp = _conv(tp, proj, 1)
    return layers.concat([t1, t3, t5, tp], axis=1)


def googlenet(input, class_dim=1000):
    net = _conv(input, 64, 7, stride=2, padding=3)
    net = layers.pool2d(
        input=net, pool_size=3, pool_stride=2, pool_padding=1, pool_type="max"
    )
    net = layers.lrn(input=net, n=5)
    net = _conv(net, 64, 1)
    net = _conv(net, 192, 3, padding=1)
    net = layers.lrn(input=net, n=5)
    net = layers.pool2d(
        input=net, pool_size=3, pool_stride=2, pool_padding=1, pool_type="max"
    )

    net = inception(net, 64, 96, 128, 16, 32, 32)
    net = inception(net, 128, 128, 192, 32, 96, 64)
    net = layers.pool2d(
        input=net, pool_size=3, pool_stride=2, pool_padding=1, pool_type="max"
    )
    net = inception(net, 192, 96, 208, 16, 48, 64)
    net = inception(net, 160, 112, 224, 24, 64, 64)
    net = inception(net, 128, 128, 256, 24, 64, 64)
    net = inception(net, 112, 144, 288, 32, 64, 64)
    net = inception(net, 256, 160, 320, 32, 128, 128)
    net = layers.pool2d(
        input=net, pool_size=3, pool_stride=2, pool_padding=1, pool_type="max"
    )
    net = inception(net, 256, 160, 320, 32, 128, 128)
    net = inception(net, 384, 192, 384, 48, 128, 128)
    net = layers.pool2d(input=net, pool_size=7, pool_type="avg", global_pooling=True)
    net = layers.dropout(x=net, dropout_prob=0.4)
    return layers.fc(input=net, size=class_dim, act="softmax")
