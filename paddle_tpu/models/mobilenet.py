"""MobileNet v1 (depthwise-separable convolutions — the reference's
depthwise kernels, paddle/function/DepthwiseConvOp*.cpp and
benchmark-era mobilenet configs, map to XLA grouped convolutions with
feature_group_count = channels)."""

from __future__ import annotations

from ..fluid import layers

__all__ = ["mobilenet_v1"]


def _conv_bn(input, num_filters, filter_size, stride, padding, channels,
             groups=1):
    conv = layers.conv2d(
        input=input,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=padding,
        groups=groups,
        num_channels=channels,
        act=None,
        bias_attr=False,
    )
    return layers.batch_norm(input=conv, act="relu")


def _depthwise_separable(input, channels, filters, stride, scale=1.0):
    ch = int(channels * scale)
    nf = int(filters * scale)
    # depthwise: groups == in channels (XLA feature_group_count)
    dw = _conv_bn(input, ch, 3, stride, 1, channels=ch, groups=ch)
    # pointwise 1x1 mixes channels on the MXU
    return _conv_bn(dw, nf, 1, 1, 0, channels=ch)


def mobilenet_v1(input, class_dim=1000, scale=1.0):
    """Standard 224x224 MobileNet v1 at width multiplier `scale`."""
    s = lambda n: int(n * scale)
    y = _conv_bn(input, s(32), 3, 2, 1, channels=int(input.shape[1]))
    cfg = [
        # (in, out, stride)
        (32, 64, 1),
        (64, 128, 2),
        (128, 128, 1),
        (128, 256, 2),
        (256, 256, 1),
        (256, 512, 2),
        (512, 512, 1),
        (512, 512, 1),
        (512, 512, 1),
        (512, 512, 1),
        (512, 512, 1),
        (512, 1024, 2),
        (1024, 1024, 1),
    ]
    for cin, cout, stride in cfg:
        y = _depthwise_separable(y, cin, cout, stride, scale)
    y = layers.pool2d(input=y, pool_type="avg", global_pooling=True)
    return layers.fc(input=y, size=class_dim, act="softmax")
