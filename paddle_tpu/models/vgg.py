"""VGG-16 (reference benchmark/cluster/vgg16/vgg16_fluid.py and the book
image_classification vgg16_bn_drop)."""

from __future__ import annotations

from ..fluid import layers, nets

__all__ = ["vgg16_bn_drop", "vgg16", "vgg19"]


def vgg16_bn_drop(input, class_dim=10):
    def conv_block(inp, num_filter, groups, dropouts):
        return nets.img_conv_group(
            input=inp,
            pool_size=2,
            pool_stride=2,
            conv_num_filter=[num_filter] * groups,
            conv_filter_size=3,
            conv_act="relu",
            conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts,
            pool_type="max",
        )

    conv1 = conv_block(input, 64, 2, [0.3, 0])
    conv2 = conv_block(conv1, 128, 2, [0.4, 0])
    conv3 = conv_block(conv2, 256, 3, [0.4, 0.4, 0])
    conv4 = conv_block(conv3, 512, 3, [0.4, 0.4, 0])
    conv5 = conv_block(conv4, 512, 3, [0.4, 0.4, 0])

    drop = layers.dropout(x=conv5, dropout_prob=0.5)
    fc1 = layers.fc(input=drop, size=512, act=None)
    bn = layers.batch_norm(input=fc1, act="relu")
    drop2 = layers.dropout(x=bn, dropout_prob=0.5)
    fc2 = layers.fc(input=drop2, size=512, act=None)
    return layers.fc(input=fc2, size=class_dim, act="softmax")


def vgg19(input, class_dim=1000):
    """Plain VGG-19 without BN: the variant the reference's CPU
    benchmark tables use (benchmark/IntelOptimizedPaddle.md:29,71);
    same block layout as vgg16 with 4-conv deep blocks."""

    def conv_block(inp, num_filter, groups):
        return nets.img_conv_group(
            input=inp,
            pool_size=2,
            pool_stride=2,
            conv_num_filter=[num_filter] * groups,
            conv_filter_size=3,
            conv_act="relu",
            pool_type="max",
        )

    conv1 = conv_block(input, 64, 2)
    conv2 = conv_block(conv1, 128, 2)
    conv3 = conv_block(conv2, 256, 4)
    conv4 = conv_block(conv3, 512, 4)
    conv5 = conv_block(conv4, 512, 4)
    fc1 = layers.fc(input=conv5, size=4096, act="relu")
    drop1 = layers.dropout(x=fc1, dropout_prob=0.5)
    fc2 = layers.fc(input=drop1, size=4096, act="relu")
    drop2 = layers.dropout(x=fc2, dropout_prob=0.5)
    return layers.fc(input=drop2, size=class_dim, act="softmax")


def vgg16(input, class_dim=1000):
    """Plain VGG-16 without BN (benchmark/paddle/image/vgg.py layout)."""

    def conv_block(inp, num_filter, groups):
        return nets.img_conv_group(
            input=inp,
            pool_size=2,
            pool_stride=2,
            conv_num_filter=[num_filter] * groups,
            conv_filter_size=3,
            conv_act="relu",
            pool_type="max",
        )

    conv1 = conv_block(input, 64, 2)
    conv2 = conv_block(conv1, 128, 2)
    conv3 = conv_block(conv2, 256, 3)
    conv4 = conv_block(conv3, 512, 3)
    conv5 = conv_block(conv4, 512, 3)
    fc1 = layers.fc(input=conv5, size=4096, act="relu")
    drop1 = layers.dropout(x=fc1, dropout_prob=0.5)
    fc2 = layers.fc(input=drop1, size=4096, act="relu")
    drop2 = layers.dropout(x=fc2, dropout_prob=0.5)
    return layers.fc(input=drop2, size=class_dim, act="softmax")
