"""Reference model zoo, built on the fluid layer API.

Parity targets: benchmark/paddle/image/{resnet,vgg,alexnet,googlenet}.py,
the book tests' models (python/paddle/v2/fluid/tests/book/), and
benchmark/cluster/vgg16/vgg16_fluid.py.
"""

from . import alexnet, googlenet, lenet, resnet, vgg

__all__ = ["lenet", "resnet", "vgg", "alexnet", "googlenet"]
