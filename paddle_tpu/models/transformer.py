"""Decoder-only transformer LM built on the parallel stack — the
long-context flagship (beyond-2018 capability; SURVEY §2.2 marks SP/ring
attention absent in the reference, first-class here).

Pure-JAX param-pytree model designed for a ('data', 'seq', 'model') mesh:
  * token embedding row-sharded over 'model' (parallel.sharded_lookup)
  * attention via parallel.sequence_parallel_attention (ring or Ulysses)
    over the 'seq' axis — O(T/n) activation memory per chip
  * MLP/attention weights column/row-sharded over 'model' by PartitionSpec
  * losses/gradients exact vs the single-device oracle (tested)

Use `init_params` + `loss_fn`/`train_step` under jax.jit with the
shardings from `param_specs`.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.attention import sequence_parallel_attention

__all__ = ["TransformerConfig", "init_params", "param_specs", "forward",
           "loss_fn", "make_train_step"]


class TransformerConfig:
    def __init__(self, vocab=256, dim=128, heads=4, layers=2, mlp_mult=4,
                 max_len=1024, dtype=jnp.float32):
        self.vocab = vocab
        self.dim = dim
        self.heads = heads
        self.layers = layers
        self.mlp_mult = mlp_mult
        self.max_len = max_len
        self.dtype = dtype


def init_params(cfg: TransformerConfig, key) -> Dict[str, Any]:
    ks = jax.random.split(key, cfg.layers + 2)
    d, h = cfg.dim, cfg.heads
    scale = 1.0 / math.sqrt(d)

    def dense(k, shape):
        return scale * jax.random.normal(k, shape, cfg.dtype)

    params = {
        "embed": dense(ks[0], (cfg.vocab, d)),
        "pos": dense(ks[1], (cfg.max_len, d)),
        "blocks": [],
        "ln_f": {"g": jnp.ones((d,), cfg.dtype), "b": jnp.zeros((d,), cfg.dtype)},
    }
    for i in range(cfg.layers):
        kq, kk, kv, ko, k1, k2 = jax.random.split(ks[2 + i], 6)
        params["blocks"].append({
            "ln1": {"g": jnp.ones((d,), cfg.dtype), "b": jnp.zeros((d,), cfg.dtype)},
            "wq": dense(kq, (d, d)),
            "wk": dense(kk, (d, d)),
            "wv": dense(kv, (d, d)),
            "wo": dense(ko, (d, d)),
            "ln2": {"g": jnp.ones((d,), cfg.dtype), "b": jnp.zeros((d,), cfg.dtype)},
            "w1": dense(k1, (d, cfg.mlp_mult * d)),
            "w2": dense(k2, (cfg.mlp_mult * d, d)),
        })
    return params


def param_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpecs for tensor parallelism over 'model' + row-sharded
    vocab. Megatron-style: qkv/w1 column-parallel, wo/w2 row-parallel."""
    rep = P()
    block = {
        "ln1": {"g": rep, "b": rep},
        "wq": P(None, "model"),
        "wk": P(None, "model"),
        "wv": P(None, "model"),
        "wo": P("model", None),
        "ln2": {"g": rep, "b": rep},
        "w1": P(None, "model"),
        "w2": P("model", None),
    }
    return {
        "embed": P("model", None),
        "pos": rep,
        "blocks": [block for _ in range(cfg.layers)],
        "ln_f": {"g": rep, "b": rep},
    }


def _ln(x, p):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-5) * p["g"] + p["b"]


def forward(params, tokens, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None, attn_impl: str = "ring"):
    """tokens [B, T] int -> logits [B, T, vocab]."""
    B, T = tokens.shape
    if mesh is not None and "model" in mesh.axis_names:
        from ..parallel.embedding import sharded_lookup

        x = sharded_lookup(params["embed"], tokens, mesh, "model")
    else:
        x = params["embed"][tokens]
    x = x + params["pos"][:T][None]

    for blk in params["blocks"]:
        h = _ln(x, blk["ln1"])
        q = (h @ blk["wq"]).reshape(B, T, cfg.heads, cfg.dim // cfg.heads)
        k = (h @ blk["wk"]).reshape(B, T, cfg.heads, cfg.dim // cfg.heads)
        v = (h @ blk["wv"]).reshape(B, T, cfg.heads, cfg.dim // cfg.heads)
        o = sequence_parallel_attention(
            q, k, v, mesh=mesh, axis="seq", impl=attn_impl, causal=True
        )
        x = x + o.reshape(B, T, cfg.dim) @ blk["wo"]
        h = _ln(x, blk["ln2"])
        x = x + jax.nn.gelu(h @ blk["w1"]) @ blk["w2"]

    x = _ln(x, params["ln_f"])
    return x @ params["embed"].T  # weight-tied output head


def loss_fn(params, tokens, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None, attn_impl: str = "ring"):
    """Next-token cross entropy over tokens [B, T+1] (input/target split)."""
    inp, tgt = tokens[:, :-1], tokens[:, 1:]
    logits = forward(params, inp, cfg, mesh=mesh, attn_impl=attn_impl)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return nll.mean()


def make_train_step(cfg: TransformerConfig, lr=1e-2,
                    mesh: Optional[Mesh] = None, attn_impl: str = "ring"):
    """SGD train step; jit it with in_shardings from param_specs when a
    mesh is used."""

    def step(params, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, tokens, cfg, mesh=mesh, attn_impl=attn_impl
        )
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    return step
