"""Decoder-only transformer LM built on the parallel stack — the
long-context flagship (beyond-2018 capability; SURVEY §2.2 marks SP/ring
attention absent in the reference, first-class here).

With `moe_experts > 0` every `moe_every`-th block's MLP becomes a
Switch-Transformer top-1 MoE FFN (parallel/moe.py) whose experts shard
over the 'expert' mesh axis — the Switch-LM flagship of the
expert-parallel path.

Pure-JAX param-pytree model designed for a ('data', 'seq', 'model') mesh:
  * token embedding row-sharded over 'model' (parallel.sharded_lookup)
  * attention via parallel.sequence_parallel_attention (ring or Ulysses)
    over the 'seq' axis — O(T/n) activation memory per chip
  * MLP/attention weights column/row-sharded over 'model' by PartitionSpec
  * losses/gradients exact vs the single-device oracle (tested)

Use `init_params` + `loss_fn`/`train_step` under jax.jit with the
shardings from `param_specs`.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.attention import sequence_parallel_attention

__all__ = ["TransformerConfig", "init_params", "param_specs", "forward",
           "loss_fn", "make_train_step"]


class TransformerConfig:
    def __init__(self, vocab=256, dim=128, heads=4, layers=2, mlp_mult=4,
                 max_len=1024, dtype=jnp.float32, moe_experts=0,
                 moe_every=2, moe_capacity_factor=1.25):
        self.vocab = vocab
        self.dim = dim
        self.heads = heads
        self.layers = layers
        self.mlp_mult = mlp_mult
        self.max_len = max_len
        self.dtype = dtype
        # Switch-Transformer MoE: with moe_experts > 0, every
        # `moe_every`-th block's MLP becomes a top-1 MoE FFN
        # (parallel/moe.py) — experts shard over the 'expert' mesh axis
        self.moe_experts = moe_experts
        self.moe_every = moe_every
        self.moe_capacity_factor = moe_capacity_factor

    def is_moe_block(self, i: int) -> bool:
        return self.moe_experts > 0 and (i % self.moe_every
                                         == self.moe_every - 1)


def init_params(cfg: TransformerConfig, key) -> Dict[str, Any]:
    ks = jax.random.split(key, cfg.layers + 2)
    d, h = cfg.dim, cfg.heads
    scale = 1.0 / math.sqrt(d)

    def dense(k, shape):
        return scale * jax.random.normal(k, shape, cfg.dtype)

    params = {
        "embed": dense(ks[0], (cfg.vocab, d)),
        "pos": dense(ks[1], (cfg.max_len, d)),
        "blocks": [],
        "ln_f": {"g": jnp.ones((d,), cfg.dtype), "b": jnp.zeros((d,), cfg.dtype)},
    }
    for i in range(cfg.layers):
        kq, kk, kv, ko, k1, k2 = jax.random.split(ks[2 + i], 6)
        # gate key derived separately so dense-model init stays
        # bit-identical to pre-MoE checkpoints for the same seed
        kg = jax.random.fold_in(ks[2 + i], 7)
        blk = {
            "ln1": {"g": jnp.ones((d,), cfg.dtype), "b": jnp.zeros((d,), cfg.dtype)},
            "wq": dense(kq, (d, d)),
            "wk": dense(kk, (d, d)),
            "wv": dense(kv, (d, d)),
            "wo": dense(ko, (d, d)),
            "ln2": {"g": jnp.ones((d,), cfg.dtype), "b": jnp.zeros((d,), cfg.dtype)},
        }
        if cfg.is_moe_block(i):
            E, m = cfg.moe_experts, cfg.mlp_mult * d
            blk["moe"] = {
                "gate_w": dense(kg, (d, E)),
                "w1": dense(k1, (E, d, m)),
                "b1": jnp.zeros((E, m), cfg.dtype),
                "w2": dense(k2, (E, m, d)),
                "b2": jnp.zeros((E, d), cfg.dtype),
            }
        else:
            blk["w1"] = dense(k1, (d, cfg.mlp_mult * d))
            blk["w2"] = dense(k2, (cfg.mlp_mult * d, d))
        params["blocks"].append(blk)
    return params


def param_specs(cfg: TransformerConfig, mesh=None) -> Dict[str, Any]:
    """PartitionSpecs for tensor parallelism over 'model' + row-sharded
    vocab + expert-sharded MoE FFNs. Megatron-style: qkv/w1
    column-parallel, wo/w2 row-parallel. Pass `mesh` to drop axes the
    mesh does not have (e.g. MoE params replicate on a mesh without an
    'expert' axis, matching forward()'s reference_moe fallback)."""
    rep = P()

    def fit(spec):
        if mesh is None:
            return spec
        return P(*(a if a in mesh.axis_names else None for a in spec))

    def block(i):
        b = {
            "ln1": {"g": rep, "b": rep},
            "wq": fit(P(None, "model")),
            "wk": fit(P(None, "model")),
            "wv": fit(P(None, "model")),
            "wo": fit(P("model", None)),
            "ln2": {"g": rep, "b": rep},
        }
        if cfg.is_moe_block(i):
            # experts shard over their leading E dim on 'expert'
            b["moe"] = {
                "gate_w": rep,
                "w1": fit(P("expert", None, None)),
                "b1": fit(P("expert", None)),
                "w2": fit(P("expert", None, None)),
                "b2": fit(P("expert", None)),
            }
        else:
            b["w1"] = fit(P(None, "model"))
            b["w2"] = fit(P("model", None))
        return b

    return {
        "embed": fit(P("model", None)),
        "pos": rep,
        "blocks": [block(i) for i in range(cfg.layers)],
        "ln_f": {"g": rep, "b": rep},
    }


def _ln(x, p):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-5) * p["g"] + p["b"]


def forward(params, tokens, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None, attn_impl: str = "ring",
            kv_sink: Optional[list] = None, last_only: bool = False,
            last_index=None):
    """tokens [B, T] int -> logits [B, T, vocab] (or [B, vocab] of just
    the final position with last_only — prefill skips the O(T x vocab)
    head it would discard). `last_index` is the dynamic counterpart: a
    traced scalar position whose single row feeds the head (the bucketed
    serving prefill pads T to a power-of-two bucket, so the true last
    prompt position is an argument, not the static T-1). With `kv_sink`
    (a list), each block appends its (k, v) [B, T, H, Dh] — the prefill
    hook for cached decoding, so serving reuses THIS block math."""
    B, T = tokens.shape
    if mesh is not None and "model" in mesh.axis_names:
        from ..parallel.embedding import sharded_lookup

        x = sharded_lookup(params["embed"], tokens, mesh, "model")
    else:
        x = params["embed"][tokens]
    x = x + params["pos"][:T][None]

    for blk in params["blocks"]:
        h = _ln(x, blk["ln1"])
        q = (h @ blk["wq"]).reshape(B, T, cfg.heads, cfg.dim // cfg.heads)
        k = (h @ blk["wk"]).reshape(B, T, cfg.heads, cfg.dim // cfg.heads)
        v = (h @ blk["wv"]).reshape(B, T, cfg.heads, cfg.dim // cfg.heads)
        if kv_sink is not None:
            kv_sink.append((k, v))
        o = sequence_parallel_attention(
            q, k, v, mesh=mesh, axis="seq", impl=attn_impl, causal=True
        )
        x = x + o.reshape(B, T, cfg.dim) @ blk["wo"]
        h = _ln(x, blk["ln2"])
        if "moe" in blk:
            from ..parallel.moe import expert_parallel_moe, reference_moe

            mp = blk["moe"]
            flat = h.reshape(B * T, cfg.dim)
            if mesh is not None and "expert" in mesh.axis_names and \
                    mesh.shape["expert"] > 1:
                y = expert_parallel_moe(
                    flat, mp["gate_w"], mp["w1"], mp["b1"], mp["w2"],
                    mp["b2"], mesh=mesh,
                    capacity_factor=cfg.moe_capacity_factor,
                )
            else:
                y = reference_moe(flat, mp["gate_w"], mp["w1"], mp["b1"],
                                  mp["w2"], mp["b2"])
            x = x + y.reshape(B, T, cfg.dim)
        else:
            x = x + jax.nn.gelu(h @ blk["w1"]) @ blk["w2"]

    if last_index is not None:
        x = jax.lax.dynamic_index_in_dim(x, last_index, axis=1,
                                         keepdims=False)
    elif last_only:
        x = x[:, -1]
    x = _ln(x, params["ln_f"])
    return x @ params["embed"].T  # weight-tied output head


def loss_fn(params, tokens, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None, attn_impl: str = "ring"):
    """Next-token cross entropy over tokens [B, T+1] (input/target split)."""
    inp, tgt = tokens[:, :-1], tokens[:, 1:]
    logits = forward(params, inp, cfg, mesh=mesh, attn_impl=attn_impl)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return nll.mean()


def make_train_step(cfg: TransformerConfig, lr=1e-2,
                    mesh: Optional[Mesh] = None, attn_impl: str = "ring"):
    """SGD train step; jit it with in_shardings from param_specs when a
    mesh is used."""

    def step(params, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, tokens, cfg, mesh=mesh, attn_impl=attn_impl
        )
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    return step


# ---------------------------------------------------------------------
# incremental decoding (serving): per-layer KV cache + one-token steps.
# The reference era served RNN generation through beam search
# (RecurrentGradientMachine.h:307); the transformer-equivalent serving
# primitive is cached autoregressive decode — prefill computes the
# prompt's K/V once, then each new token attends over the cache instead
# of re-running the whole prefix (O(T) per token, not O(T^2)).
# ---------------------------------------------------------------------


def init_kv_cache(cfg: TransformerConfig, batch: int, max_len=None,
                  dtype=None):
    """Per-layer K/V buffers [B, L, H, Dh], zero-initialised."""
    L = int(max_len or cfg.max_len)
    dh = cfg.dim // cfg.heads
    shape = (batch, L, cfg.heads, dh)
    dt = dtype or cfg.dtype
    return [
        {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
        for _ in range(cfg.layers)
    ]


def _cached_attention(q, cache_k, cache_v, pos):
    """q [B,H,Dh] against the cache [B,L,H,Dh]; positions > pos masked.
    `pos` is a scalar (one shared decode position — generate's path) or
    a [B] vector of PER-ROW positions (the slotted serving cache, where
    every row is an independent request at its own depth). Masked
    positions contribute exactly 0 (exp(-inf) == 0, 0 * finite == 0),
    so stale/dead-slot cache rows cannot perturb live rows."""
    B, L, H, dh = cache_k.shape
    scores = jnp.einsum("bhd,blhd->bhl", q, cache_k) / math.sqrt(dh)
    pos = jnp.asarray(pos)
    if pos.ndim == 0:
        mask = (jnp.arange(L) <= pos)[None, None, :]
    else:
        mask = (jnp.arange(L)[None, :] <= pos[:, None])[:, None, :]
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhl,blhd->bhd", probs, cache_v)


def _write_kv(buf, new, pos):
    """Write one new K or V row [B, H, Dh] into the cache [B, L, H, Dh]
    at position `pos`: a contiguous dynamic_update_slice for the scalar
    case (generate — every row at the same depth), a per-row scatter for
    vector pos [B] (slotted serving — each slot at its own depth).
    Out-of-range vector positions are DROPPED by scatter semantics, so a
    retired slot parked at the clamp boundary never corrupts neighbors."""
    if jnp.ndim(pos) == 0:
        return jax.lax.dynamic_update_slice_in_dim(
            buf, new[:, None].astype(buf.dtype), pos, axis=1
        )
    B = buf.shape[0]
    return buf.at[jnp.arange(B), pos].set(new.astype(buf.dtype))


def decode_step(params, token, pos, cache, cfg: TransformerConfig):
    """One decode step: token [B] int at position `pos` -> (logits
    [B, vocab], updated cache). `pos` is a scalar (generate: all rows at
    the same depth) or a [B] vector of per-row positions (the slotted
    serving cache — many independent requests in one batched step); the
    per-row math is identical either way, so the serving engine's
    decode is bit-identical to generate's row by row."""
    B = token.shape[0]
    dh = cfg.dim // cfg.heads
    x = params["embed"][token] + params["pos"][pos]
    new_cache = []
    for blk, kv in zip(params["blocks"], cache):
        h = _ln(x, blk["ln1"])
        q = (h @ blk["wq"]).reshape(B, cfg.heads, dh)
        k = (h @ blk["wk"]).reshape(B, cfg.heads, dh)
        v = (h @ blk["wv"]).reshape(B, cfg.heads, dh)
        ck = _write_kv(kv["k"], k, pos)
        cv = _write_kv(kv["v"], v, pos)
        new_cache.append({"k": ck, "v": cv})
        o = _cached_attention(q, ck, cv, pos).reshape(B, cfg.dim)
        x = x + o @ blk["wo"]
        h = _ln(x, blk["ln2"])
        if "moe" in blk:
            from ..parallel.moe import reference_moe

            mp = blk["moe"]
            x = x + reference_moe(
                h, mp["gate_w"], mp["w1"], mp["b1"], mp["w2"], mp["b2"]
            )
        else:
            x = x + jax.nn.gelu(h @ blk["w1"]) @ blk["w2"]
    x = _ln(x, params["ln_f"])
    return x @ params["embed"].T, new_cache


def prefill(params, tokens, cfg: TransformerConfig, max_len=None):
    """Run the prompt [B, T0] once through forward() (kv_sink hook),
    filling the cache; returns (logits of the LAST prompt position
    [B, vocab], cache). Reuses forward's block math exactly — no
    duplicated transformer loop to drift."""
    B, T0 = tokens.shape
    cache = init_kv_cache(cfg, B, max_len=max_len)
    sink: list = []
    logits = forward(
        params, tokens, cfg, mesh=None, attn_impl="reference",
        kv_sink=sink, last_only=True,
    )
    for i, (k, v) in enumerate(sink):
        cache[i] = {
            "k": jax.lax.dynamic_update_slice_in_dim(
                cache[i]["k"], k.astype(cache[i]["k"].dtype), 0, axis=1
            ),
            "v": jax.lax.dynamic_update_slice_in_dim(
                cache[i]["v"], v.astype(cache[i]["v"].dtype), 0, axis=1
            ),
        }
    return logits, cache


def prefill_chunk(params, cache, chunk, start_pos, slot, cfg: TransformerConfig,
                  true_len=None):
    """Multi-token incremental prefill: extend slot `slot` of a slotted
    cache ([S, L, H, Dh] per layer) by a [C]-token `chunk` whose first
    token sits at position `start_pos` (tokens 0..start_pos-1 must
    already be cached — written by earlier chunks or device-copied from
    a prefix pool). Each chunk row attends to cache[0:start_pos] plus
    the intra-chunk causal prefix, so running a prompt through
    consecutive chunks is mathematically the monolithic prefill — and
    BIT-identical to it, because every op mirrors forward()'s numerics
    exactly: reference_attention's scale-into-q einsum and -1e30 mask
    (NOT _cached_attention's divide-after-matmul/-inf variant — the two
    differ in low bits), softmax in the score dtype, the same reshape/
    matmul order per block, and forward(last_index=...)'s head on the
    true last row.

    `chunk` may be padded (pow-2 bucketing: compiled shapes stay
    O(log max_len), the same discipline as the monolithic prefill);
    `true_len` is the number of real tokens. Padded rows write their
    K/V OUT OF RANGE (position L — scatter drops them, the same parking
    trick the batched decode uses for dead slots), so the cache beyond
    start_pos+true_len is never dirtied, and their attention output is
    garbage that nothing reads. `start_pos`/`slot`/`true_len` are
    traced scalars: one compile per chunk bucket, not per position.

    Returns (logits [vocab] of the true last chunk row, new cache).
    The logits are only meaningful on a prompt's FINAL chunk (where
    start_pos + true_len == T0); earlier chunks exist for their cache
    writes. MoE caveat (same as decode_step): reference_moe's capacity
    cutoff couples rows, so MoE blocks are not bit-stable across
    chunking — the serving family is dense."""
    from ..parallel.attention import _NEG_INF

    (C,) = chunk.shape
    S, L, H, dh = cache[0]["k"].shape
    if true_len is None:
        true_len = C
    scale = 1.0 / math.sqrt(dh)
    offs = jnp.arange(C)
    positions = start_pos + offs  # [C] global rows of the chunk
    # padded rows park out of range: scatter DROPS them
    wpos = jnp.where(offs < true_len, positions, jnp.int32(L))
    x = params["embed"][chunk][None] + params["pos"][positions][None]
    new_cache = []
    for blk, kv in zip(params["blocks"], cache):
        h = _ln(x, blk["ln1"])
        q = (h @ blk["wq"]).reshape(1, C, cfg.heads, dh)
        k = (h @ blk["wk"]).reshape(1, C, cfg.heads, dh)
        v = (h @ blk["wv"]).reshape(1, C, cfg.heads, dh)
        ck = kv["k"].at[slot, wpos].set(k[0].astype(kv["k"].dtype))
        cv = kv["v"].at[slot, wpos].set(v[0].astype(kv["v"].dtype))
        new_cache.append({"k": ck, "v": cv})
        slot_k = jax.lax.dynamic_slice(ck, (slot, 0, 0, 0), (1, L, H, dh))
        slot_v = jax.lax.dynamic_slice(cv, (slot, 0, 0, 0), (1, L, H, dh))
        # reference_attention numerics, verbatim: scale folded into q
        # BEFORE the matmul, -1e30 mask, softmax in the score dtype
        s = jnp.einsum("bthd,bshd->bhts", q * scale, slot_k)
        mask = jnp.arange(L)[None, :] <= positions[:, None]  # [C, L]
        s = jnp.where(mask[None, None], s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhts,bshd->bthd", p, slot_v)
        x = x + o.reshape(1, C, cfg.dim) @ blk["wo"]
        h = _ln(x, blk["ln2"])
        if "moe" in blk:
            from ..parallel.moe import reference_moe

            mp = blk["moe"]
            flat = h.reshape(C, cfg.dim)
            y = reference_moe(flat, mp["gate_w"], mp["w1"], mp["b1"],
                              mp["w2"], mp["b2"])
            x = x + y.reshape(1, C, cfg.dim)
        else:
            x = x + jax.nn.gelu(h @ blk["w1"]) @ blk["w2"]
    xl = jax.lax.dynamic_index_in_dim(x, true_len - 1, axis=1,
                                      keepdims=False)  # [1, dim]
    xl = _ln(xl, params["ln_f"])
    return (xl @ params["embed"].T)[0], new_cache


# ---------------------------------------------------------------------
# paged KV cache (serving, ISSUE 7): the cache is a pool of fixed-size
# token BLOCKS ([NB, Bt, H, Dh] per layer) and each slot owns a block
# TABLE (row of physical block ids) instead of a contiguous cache row —
# PagedAttention (Kwon et al., SOSP '23) in static-shape JAX idiom. HBM
# residency scales with blocks actually written, not MAX_SLOTS*max_len;
# prefix reuse becomes table aliasing (two slots naming the same
# physical block) instead of device copies.
#
# Each primitive takes kernel="gather"|"fused" (ISSUE 13):
#   gather — attend over a contiguous per-slot view `_paged_view`
#            materialises as TRANSIENT activation scratch
#            [S, MAXB*Bt, H, Dh] per layer (freed after the step, but
#            an HBM write+read of the whole gathered context per step);
#   fused  — the Pallas kernels in parallel/paged_attention.py walk
#            the block table INSIDE the kernel (scalar-prefetch index
#            maps), streaming K/V blocks from the pool with online
#            softmax — no view ever exists. Fused-vs-gather logits
#            agree to float tolerance (online softmax reorders the
#            reduction), token-identically in greedy decode — the same
#            low-bit class as the padded-prefill drift (PR 2).
#
# Each primitive also takes kv_quant="none"|"int8"|"fp8" (ISSUE 14):
# the pool stores quantized codes with per-(physical block, head)
# absmax scale side-bands (k_scale/v_scale [NB, H] per layer), writes
# quantize at the scatter (_quant_scatter's commit-at-open rule), and
# reads dequantize in-kernel (fused) or on the gather view. "none" is
# byte-identical to the pre-quant code path.
# ---------------------------------------------------------------------


def _paged_kernel_check(kernel: str):
    if kernel not in ("gather", "fused"):
        raise ValueError(
            "paged kernel must be 'gather' or 'fused' (got %r)"
            % (kernel,))


# ---------------------------------------------------------------------
# per-block KV quantization (ISSUE 14): the pool stores int8/fp8 with a
# per-(physical block, head) absmax scale side-band [NB, H] per layer
# and band. Scales are keyed by PHYSICAL block id, so prefix aliasing
# (two tables naming one block) shares the scale for free and
# copy-on-write copies payload+scale in the same compiled op. qmax is
# the storage format's largest representable magnitude: 127 for int8,
# 448 for float8_e4m3fn (no inf — casts past it would garbage, so
# writes clip to it explicitly).
# ---------------------------------------------------------------------

_KV_QMAX = {"int8": 127.0, "fp8": 448.0}


def _kv_quant_check(kv_quant: str):
    if kv_quant not in ("none", "int8", "fp8"):
        raise ValueError(
            "kv_quant must be 'none', 'int8', or 'fp8' (got %r)"
            % (kv_quant,))


def kv_block_bytes(layers_n: int, heads: int, dh: int,
                   block_tokens: int, kv_quant: str = "none",
                   act_itemsize: int = 4) -> int:
    """One physical KV block's HBM cost at a storage dtype: K+V
    payload rows over all layers, plus the per-(block, head) f32
    scale side-bands when quantized. THE one formula — the engine's
    allocator accounting (ServingEngine.kv_block_bytes), bench.py's
    fixed-byte-budget pool sizing, and bench_offline's roofline all
    call it, so the three can never drift. Per payload byte the
    int8/fp8 scale overhead is 4 / (block_tokens x dh) — ~0.4% at
    the Bt=16, dh=64 defaults."""
    _kv_quant_check(kv_quant)
    item = 1 if kv_quant != "none" else int(act_itemsize)
    b = 2 * layers_n * block_tokens * heads * dh * item
    if kv_quant != "none":
        b += 2 * layers_n * heads * 4
    return b


def kv_storage_dtype(kv_quant: str):
    """Pool storage dtype for a kv_quant setting; None = the model
    dtype (unquantized). Raises on fp8 when this jax build has no
    float8_e4m3fn — a loud gate, never a silent f32 fallback."""
    _kv_quant_check(kv_quant)
    if kv_quant == "int8":
        return jnp.int8
    if kv_quant == "fp8":
        if not hasattr(jnp, "float8_e4m3fn"):
            raise ValueError(
                "kv_quant='fp8' needs jnp.float8_e4m3fn (this jax "
                "build has none) — use 'int8' or 'none'")
        return jnp.float8_e4m3fn
    return None


def init_paged_kv_cache(cfg: TransformerConfig, num_blocks: int,
                        block_tokens: int, dtype=None,
                        kv_quant: str = "none"):
    """Per-layer pooled K/V block buffers [NB, Bt, H, Dh]. With
    `kv_quant` ('int8' | 'fp8') the payload stores the quantized code
    and each layer gains per-(block, head) f32 absmax-scale side-bands
    'k_scale'/'v_scale' [NB, H] (committed at block fill — see
    `_quant_scatter`). kv_quant='none' returns the exact pre-quant
    structure, so default engines stay trace-identical."""
    dh = cfg.dim // cfg.heads
    NB, Bt = int(num_blocks), int(block_tokens)
    shape = (NB, Bt, cfg.heads, dh)
    st = kv_storage_dtype(kv_quant)
    if st is None:
        dt = dtype or cfg.dtype
        return [
            {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
            for _ in range(cfg.layers)
        ]
    return [
        {"k": jnp.zeros(shape, st), "v": jnp.zeros(shape, st),
         "k_scale": jnp.zeros((NB, cfg.heads), jnp.float32),
         "v_scale": jnp.zeros((NB, cfg.heads), jnp.float32)}
        for _ in range(cfg.layers)
    ]


def _quant_scatter(buf, scale, pk, off, vals, qmax,
                   commit_from_call=False):
    """Quantize rows `vals` [..., H, Dh] and scatter them into the
    int8/fp8 pool `buf` [NB, Bt, H, Dh] at (pk, off) [...]; returns
    (new buf, new scale [NB, H]).

    Scale discipline (the absmax commit-at-open rule): a block is
    OPENED when some row of THIS call writes its in-block offset 0 —
    opened blocks (re)commit their per-head scale (erasing the stale
    scale a recycled pool block carries from its previous tenant).
    The commit source is the opening ROW's absmax by default; with
    `commit_from_call` it is the absmax over every row this call
    writes into the block. Chunk prefill uses call-commit (the whole
    fill is deterministic — prompt blocks are never re-opened);
    decode and verify MUST use row-commit: a verify window's extra
    rows are speculative drafts, and folding a rejected draft into
    the scale would make the committed scale — and every later
    clipped write — depend on drafts that never became tokens,
    breaking the spec-invariance guarantee (rejected positions are
    re-written by later windows, and the off-0 re-write re-commits,
    so the QUIESCENT cache is bit-identical to the plain decode
    path's). Rows landing in a block this call did NOT open re-use
    the committed scale and CLIP to it (decode appends mid-block,
    continuation chunks, draft re-writes) — the LLM.int8-style absmax
    trade: later outliers saturate rather than re-scaling rows
    already stored. Parked rows (pk == NB, the engine's
    dead-slot/padded sentinel) drop payload, scale commit, AND open
    marker alike — out-of-range scatters drop, so parking stays exact
    on the quant path and a sentinel-parked write can never dirty a
    block or its scale.

    Known limit (the absmax trade's extreme): a block OPENED by an
    all-zero row commits scale 0, and every row later appended to it
    dequantizes to exactly 0 for the block's lifetime — total loss,
    not clipping. No invariance-safe rescue exists inside per-block
    scales (a re-commit on the first nonzero append would let verify
    windows leak rejected-draft magnitudes back into the scale, and
    an epsilon floor still clips appends to ~0). It is accepted
    because an exactly-zero per-head projection requires h @ wk == 0
    in every lane through a LayerNormed activation — unreachable for
    real checkpoints short of hand-zeroed weight/embedding rows —
    and the serving_quant agreement gate is the arbiter if a model
    ever gets near it."""
    NB = buf.shape[0]
    H, dh = vals.shape[-2], vals.shape[-1]
    n = math.prod(vals.shape[:-2])
    fpk = jnp.reshape(pk, (n,))
    foff = jnp.reshape(off, (n,))
    fv = jnp.reshape(vals, (n, H, dh)).astype(jnp.float32)
    amax = jnp.abs(fv).max(axis=-1)  # [n, H]
    # commit-source rows scatter-max into the candidate scales
    # (duplicate pk rows combine by max; parked rows at NB drop, and
    # in row-commit mode non-opening rows park themselves)
    src_pk = fpk if commit_from_call else jnp.where(
        foff == 0, fpk, jnp.int32(NB))
    cand = jnp.zeros((NB, H), jnp.float32).at[src_pk].max(amax / qmax)
    opened = jnp.zeros((NB, 1), jnp.float32).at[fpk].max(
        (foff == 0).astype(jnp.float32)[:, None]) > 0
    new_scale = jnp.where(opened, cand, scale)
    # quantize each row with the post-commit scale of ITS block; a
    # zero scale (an all-zero fill, or a never-opened block nothing
    # will read) divides by 1 instead — codes stay finite and exact 0
    # round-trips to exact 0
    s_rows = new_scale[jnp.clip(fpk, 0, NB - 1)][..., None]  # [n, H, 1]
    safe = jnp.where(s_rows > 0, s_rows, 1.0)
    scaled = fv / safe
    if buf.dtype == jnp.int8:
        q = jnp.clip(jnp.round(scaled), -qmax, qmax).astype(jnp.int8)
    else:  # fp8: clip to the format's finite max BEFORE the cast
        q = jnp.clip(scaled, -qmax, qmax).astype(buf.dtype)
    return buf.at[fpk, foff].set(q), new_scale


def _paged_deq_view(buf, scale, tables):
    """Dequantized gather view: `_paged_view` of the quantized pool,
    upcast to f32 and multiplied by each block's per-head scale
    (broadcast over the block's Bt rows) — the gather fallback's read
    path, running the SAME numerics the fused kernel applies in VMEM
    so CPU CI interprets identical math. Unallocated (-1) entries
    clamp like `_paged_view`; their garbage codes times their garbage
    (finite) scales are position-masked to exactly 0 by every
    caller."""
    NB, Bt, H, dh = buf.shape
    v = _paged_view(buf, tables).astype(jnp.float32)
    s = scale[jnp.clip(tables, 0, NB - 1)]  # [..., MAXB, H]
    s = jnp.repeat(s, Bt, axis=-2)          # [..., MAXB*Bt, H]
    return v * s[..., None]


def _paged_view(buf, tables):
    """Gather a contiguous per-slot view [S, MAXB*Bt, H, Dh] out of the
    block pool [NB, Bt, H, Dh] through block tables [S, MAXB].
    Unallocated table entries (-1) clamp to block 0 — the rows they
    surface are garbage, but every caller masks attention by position,
    and position masks always exclude unwritten depths, so garbage
    rows contribute exactly 0 (finite * zero-prob)."""
    NB, Bt, H, dh = buf.shape
    v = buf[jnp.clip(tables, 0, NB - 1)]
    lead = tables.shape[:-1] + (tables.shape[-1] * Bt, H, dh)
    return v.reshape(lead)


def _phys_rows(tables, wpos, NB, Bt):
    """Map global write positions to (physical block, in-block offset).
    A position past the table span (the engine parks dead/padded rows
    at MAXB*Bt, the paged analogue of the slab's position-L trick) or
    landing on an unallocated (-1) entry resolves to block NB — out of
    range, so the scatter DROPS the write."""
    maxb = tables.shape[-1]
    bi = wpos // Bt
    safe = jnp.clip(bi, 0, maxb - 1)
    if tables.ndim == 1:
        phys = tables[safe]
    elif safe.ndim == tables.ndim:
        phys = jnp.take_along_axis(tables, safe, axis=-1)
    else:  # one position per table row (the decode step's [S] case)
        phys = jnp.take_along_axis(tables, safe[..., None], axis=-1)[..., 0]
    phys = jnp.where((bi < maxb) & (phys >= 0), phys, jnp.int32(NB))
    return phys, wpos % Bt


def _adapter_delta(h, a, b, scale):
    """LoRA-style low-rank delta for one projection: h @ A @ B * scale
    with PER-SLOT adapter gathers (ISSUE 12 — Punica/S-LoRA batching:
    N tenants' deltas over one base model in one compiled step). `a`
    is [d, r] (one slot's adapter — the prefill-chunk case) or
    [S, d, r] (per-slot gathered — decode [S, d] and verify [S, K, d]
    activations); `b`/`scale` match. The ZERO adapter (A = B = 0,
    scale = 0) contributes exact float zeros, so a request with no
    adapter decodes token-identically to the base model — anything @ 0
    is 0, 0 * 0 is 0, and x + 0 never moves an argmax (the engine's
    zero-adapter identity test pins it)."""
    if a.ndim == 2:  # one slot (the prefill chunk's scalar index)
        return (h @ a) @ b * scale
    if h.ndim == 2:  # decode: [S, d] x [S, d, r]
        t = jnp.einsum("sd,sdr->sr", h, a)
        return jnp.einsum("sr,srd->sd", t, b) * scale[:, None]
    # verify: [S, K, d] x [S, d, r]
    t = jnp.einsum("skd,sdr->skr", h, a)
    return jnp.einsum("skr,srd->skd", t, b) * scale[:, None, None]


def _adapter_qv(h, blk, li, adapters, idx):
    """q/v projections with the per-slot adapter delta folded in —
    shared by the three paged steps so the adapter math cannot drift
    between decode, verify, and prefill chunks. `idx` is the per-slot
    adapter-index side-band ([] for the chunk's single slot, [S]
    otherwise); `adapters` holds the stacked device pool
    ([P, layers, ...] — serving/adapters.py). Returns (q, v) UNshaped
    (the callers reshape to heads)."""
    q = h @ blk["wq"]
    v = h @ blk["wv"]
    if adapters is not None:
        sc = adapters["scale"][idx]
        # cast the (f32 pool) delta back to the activation dtype
        # BEFORE adding: on bf16 configs an uncast add would promote
        # q/v to f32 and change downstream attention precision even
        # for the zero adapter — the token-identity invariant must
        # hold at the base model's own precision
        dq = _adapter_delta(h, adapters["a_q"][idx, li],
                            adapters["b_q"][idx, li], sc)
        dv = _adapter_delta(h, adapters["a_v"][idx, li],
                            adapters["b_v"][idx, li], sc)
        q = q + dq.astype(q.dtype)
        v = v + dv.astype(v.dtype)
    return q, v


def paged_decode_step(params, token, pos, tables, cache,
                      cfg: TransformerConfig, adapters=None,
                      adapter_idx=None, kernel="gather",
                      kv_quant="none"):
    """One decode step over the paged pool: token [S] at per-row
    positions `pos` [S], block tables [S, MAXB] -> (logits [S, vocab],
    updated cache). Mirrors decode_step's numerics verbatim
    (_cached_attention's divide-after-matmul/-inf mask) on the gathered
    per-slot view — or, with kernel="fused", attends through the block
    table inside the Pallas kernel (parallel/paged_attention.py: same
    scaling family, online softmax, no materialised view) — so a paged
    engine row decodes to the same tokens the slab engine (and
    sequential generate()) produces. A parked row (pos >= MAXB*Bt)
    writes nothing; its logits are garbage nothing reads. With
    `adapters`/`adapter_idx` [S], each slot's q/v projections gain its
    tenant's LoRA delta gathered from the stacked adapter pool (ISSUE
    12 — index 0 is the zero adapter, exact no-op); the adapter gather
    is INSIDE this one compiled step, so N tenants retrace nothing.
    With `kv_quant` ('int8' | 'fp8'), writes quantize at the scatter
    (`_quant_scatter`: a block-opening row commits the block's scale,
    appends re-use it) and reads dequantize inside the fused kernel
    (scales ride as scalar-prefetch operands) or on the gather view —
    'none' is byte-identical to the pre-quant step."""
    _paged_kernel_check(kernel)
    _kv_quant_check(kv_quant)
    quant = kv_quant != "none"
    qmax = _KV_QMAX.get(kv_quant)
    B = token.shape[0]
    dh = cfg.dim // cfg.heads
    NB, Bt = cache[0]["k"].shape[0], cache[0]["k"].shape[1]
    x = params["embed"][token] + params["pos"][pos]
    new_cache = []
    for li, (blk, kv) in enumerate(zip(params["blocks"], cache)):
        h = _ln(x, blk["ln1"])
        q, v = _adapter_qv(h, blk, li, adapters, adapter_idx)
        q = q.reshape(B, cfg.heads, dh)
        k = (h @ blk["wk"]).reshape(B, cfg.heads, dh)
        v = v.reshape(B, cfg.heads, dh)
        pk, off = _phys_rows(tables, pos, NB, Bt)
        if quant:
            ck, ksc = _quant_scatter(kv["k"], kv["k_scale"], pk, off,
                                     k, qmax)
            cv, vsc = _quant_scatter(kv["v"], kv["v_scale"], pk, off,
                                     v, qmax)
            new_cache.append({"k": ck, "v": cv,
                              "k_scale": ksc, "v_scale": vsc})
        else:
            ksc = vsc = None
            ck = kv["k"].at[pk, off].set(k.astype(kv["k"].dtype))
            cv = kv["v"].at[pk, off].set(v.astype(kv["v"].dtype))
            new_cache.append({"k": ck, "v": cv})
        if kernel == "fused":
            from ..parallel.paged_attention import paged_decode_attention

            o = paged_decode_attention(
                q, ck, cv, tables, pos, k_scale=ksc, v_scale=vsc
            ).reshape(B, cfg.dim)
        elif quant:
            # f32 dequantized view: cast the attention output back to
            # the activation dtype so quantization never silently
            # promotes a bf16 model's residual stream (the fused
            # kernel's out dtype is q's already)
            o = _cached_attention(
                q, _paged_deq_view(ck, ksc, tables),
                _paged_deq_view(cv, vsc, tables), pos
            ).astype(x.dtype).reshape(B, cfg.dim)
        else:
            o = _cached_attention(
                q, _paged_view(ck, tables), _paged_view(cv, tables), pos
            ).reshape(B, cfg.dim)
        x = x + o @ blk["wo"]
        h = _ln(x, blk["ln2"])
        if "moe" in blk:
            from ..parallel.moe import reference_moe

            mp = blk["moe"]
            x = x + reference_moe(
                h, mp["gate_w"], mp["w1"], mp["b1"], mp["w2"], mp["b2"]
            )
        else:
            x = x + jax.nn.gelu(h @ blk["w1"]) @ blk["w2"]
    x = _ln(x, params["ln_f"])
    return x @ params["embed"].T, new_cache


def paged_prefill_chunk(params, cache, chunk, start_pos, table_row,
                        cfg: TransformerConfig, true_len=None,
                        adapters=None, adapter_idx=None,
                        kernel="gather", kv_quant="none"):
    """prefill_chunk over the paged pool: extend the slot whose block
    table is `table_row` [MAXB] by a [C]-token chunk starting at
    `start_pos`. Identical math to prefill_chunk (reference_attention's
    scale-into-q einsum and -1e30 mask — see its docstring for why),
    with the slot's contiguous cache replaced by the gathered block
    view (kernel="gather") or by the in-kernel table walk
    (kernel="fused" — parallel/paged_attention.py, same scale-into-q
    family); padded rows (offs >= true_len) park their writes past the
    table span, where the scatter drops them. `adapters`/`adapter_idx`
    (a SCALAR here — one slot prefills per chunk call) fold the slot's
    tenant LoRA delta into q/v exactly like paged_decode_step, so the
    cached K/V a chunk writes are the adapted model's. `kv_quant`
    quantizes at the scatter — a chunk COMMITS the scale of every
    block it opens (absmax over the chunk's rows in that block) and
    clips into blocks earlier chunks committed — and dequantizes on
    the read, fused or gathered, like paged_decode_step."""
    from ..parallel.attention import _NEG_INF

    _paged_kernel_check(kernel)
    _kv_quant_check(kv_quant)
    quant = kv_quant != "none"
    qmax = _KV_QMAX.get(kv_quant)
    (C,) = chunk.shape
    NB, Bt, H, dh = cache[0]["k"].shape
    Lv = table_row.shape[0] * Bt
    if true_len is None:
        true_len = C
    scale = 1.0 / math.sqrt(dh)
    offs = jnp.arange(C)
    positions = start_pos + offs  # [C] global rows of the chunk
    wpos = jnp.where(offs < true_len, positions, jnp.int32(Lv))
    x = params["embed"][chunk][None] + params["pos"][positions][None]
    new_cache = []
    for li, (blk, kv) in enumerate(zip(params["blocks"], cache)):
        h = _ln(x, blk["ln1"])
        q, v = _adapter_qv(h, blk, li, adapters, adapter_idx)
        q = q.reshape(1, C, cfg.heads, dh)
        k = (h @ blk["wk"]).reshape(1, C, cfg.heads, dh)
        v = v.reshape(1, C, cfg.heads, dh)
        pk, off = _phys_rows(table_row, wpos, NB, Bt)
        if quant:
            # call-commit: the chunk's whole fill of each opened block
            # is real prompt content (never speculative), so the
            # block scale sees every row — the best absmax available
            ck, ksc = _quant_scatter(kv["k"], kv["k_scale"], pk, off,
                                     k[0], qmax, commit_from_call=True)
            cv, vsc = _quant_scatter(kv["v"], kv["v_scale"], pk, off,
                                     v[0], qmax, commit_from_call=True)
            new_cache.append({"k": ck, "v": cv,
                              "k_scale": ksc, "v_scale": vsc})
        else:
            ksc = vsc = None
            ck = kv["k"].at[pk, off].set(k[0].astype(kv["k"].dtype))
            cv = kv["v"].at[pk, off].set(v[0].astype(kv["v"].dtype))
            new_cache.append({"k": ck, "v": cv})
        if kernel == "fused":
            from ..parallel.paged_attention import (
                paged_prefill_attention)

            o = paged_prefill_attention(
                q[0], ck, cv, table_row, start_pos,
                k_scale=ksc, v_scale=vsc)[None]
        else:
            if quant:
                slot_k = _paged_deq_view(ck, ksc, table_row[None])
                slot_v = _paged_deq_view(cv, vsc, table_row[None])
            else:
                slot_k = _paged_view(ck, table_row[None])  # [1, Lv, H, dh]
                slot_v = _paged_view(cv, table_row[None])
            s = jnp.einsum("bthd,bshd->bhts", q * scale, slot_k)
            mask = jnp.arange(Lv)[None, :] <= positions[:, None]
            s = jnp.where(mask[None, None], s, _NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhts,bshd->bthd", p, slot_v).astype(x.dtype)
        x = x + o.reshape(1, C, cfg.dim) @ blk["wo"]
        h = _ln(x, blk["ln2"])
        if "moe" in blk:
            from ..parallel.moe import reference_moe

            mp = blk["moe"]
            flat = h.reshape(C, cfg.dim)
            y = reference_moe(flat, mp["gate_w"], mp["w1"], mp["b1"],
                              mp["w2"], mp["b2"])
            x = x + y.reshape(1, C, cfg.dim)
        else:
            x = x + jax.nn.gelu(h @ blk["w1"]) @ blk["w2"]
    xl = jax.lax.dynamic_index_in_dim(x, true_len - 1, axis=1,
                                      keepdims=False)  # [1, dim]
    xl = _ln(xl, params["ln_f"])
    return (xl @ params["embed"].T)[0], new_cache


def paged_verify_step(params, cache, window, pos, wpos, tables,
                      cfg: TransformerConfig, adapters=None,
                      adapter_idx=None, kernel="gather",
                      kv_quant="none"):
    """Speculative-decoding verify: run a K-token `window` [S, K] per
    slot (the pending token followed by K-1 drafted tokens) through the
    paged cache in ONE batched step, returning logits for every window
    position [S, K, vocab]. Row (s, i) sits at global position
    pos[s] + i and attends the slot's cache up to and including itself
    (the intra-window causal prefix falls out of the position mask,
    because earlier window rows were just written at earlier
    positions). `wpos` [S, K] are the WRITE positions, precomputed by
    the caller so dead slots and rows past a request's token budget
    park (>= MAXB*Bt -> dropped); the mask/embedding positions are
    always pos[s] + i. logits[s, i] is "the next token after
    window[s, :i+1]" — exactly decode_step's answer when drafts
    0..i match what the model would have produced, which is what the
    engine's acceptance rule checks. Chunk-family numerics
    (scale-into-q, -1e30 mask), the same low-bit-vs-decode_step class
    prefill_chunk documents; kernel="fused" runs the same family
    through the in-kernel table walk (parallel/paged_attention.py).
    `kv_quant` quantizes the window's writes at the scatter (a window
    row opening a fresh block commits its scale; re-writes of rejected
    draft positions clip to the committed scale until the block is
    re-opened) and dequantizes the reads, fused or gathered."""
    from ..parallel.attention import _NEG_INF

    _paged_kernel_check(kernel)
    _kv_quant_check(kv_quant)
    quant = kv_quant != "none"
    qmax = _KV_QMAX.get(kv_quant)
    S, K = window.shape
    NB, Bt, H, dh = cache[0]["k"].shape
    Lv = tables.shape[1] * Bt
    scale = 1.0 / math.sqrt(dh)
    positions = pos[:, None] + jnp.arange(K)[None, :]  # [S, K]
    x = params["embed"][window] + params["pos"][positions]
    new_cache = []
    for li, (blk, kv) in enumerate(zip(params["blocks"], cache)):
        h = _ln(x, blk["ln1"])
        q, v = _adapter_qv(h, blk, li, adapters, adapter_idx)
        q = q.reshape(S, K, cfg.heads, dh)
        k = (h @ blk["wk"]).reshape(S, K, cfg.heads, dh)
        v = v.reshape(S, K, cfg.heads, dh)
        pk, off = _phys_rows(tables, wpos, NB, Bt)  # [S, K]
        if quant:
            ck, ksc = _quant_scatter(kv["k"], kv["k_scale"], pk, off,
                                     k, qmax)
            cv, vsc = _quant_scatter(kv["v"], kv["v_scale"], pk, off,
                                     v, qmax)
            new_cache.append({"k": ck, "v": cv,
                              "k_scale": ksc, "v_scale": vsc})
        else:
            ksc = vsc = None
            ck = kv["k"].at[pk, off].set(k.astype(kv["k"].dtype))
            cv = kv["v"].at[pk, off].set(v.astype(kv["v"].dtype))
            new_cache.append({"k": ck, "v": cv})
        if kernel == "fused":
            from ..parallel.paged_attention import (
                paged_verify_attention)

            o = paged_verify_attention(q, ck, cv, tables, pos,
                                       k_scale=ksc, v_scale=vsc)
        else:
            if quant:
                kview = _paged_deq_view(ck, ksc, tables)
                vview = _paged_deq_view(cv, vsc, tables)
            else:
                kview = _paged_view(ck, tables)  # [S, Lv, H, dh]
                vview = _paged_view(cv, tables)
            s = jnp.einsum("bthd,bshd->bhts", q * scale, kview)
            mask = jnp.arange(Lv)[None, None, :] <= positions[:, :, None]
            s = jnp.where(mask[:, None], s, _NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhts,bshd->bthd", p, vview).astype(x.dtype)
        x = x + o.reshape(S, K, cfg.dim) @ blk["wo"]
        h = _ln(x, blk["ln2"])
        if "moe" in blk:
            from ..parallel.moe import reference_moe

            mp = blk["moe"]
            flat = h.reshape(S * K, cfg.dim)
            y = reference_moe(flat, mp["gate_w"], mp["w1"], mp["b1"],
                              mp["w2"], mp["b2"])
            x = x + y.reshape(S, K, cfg.dim)
        else:
            x = x + jax.nn.gelu(h @ blk["w1"]) @ blk["w2"]
    x = _ln(x, params["ln_f"])
    return x @ params["embed"].T, new_cache


def logits_trap(logits):
    """Per-row non-finite TRAP over final logits (ISSUE 15): True where
    a row's logits contain any NaN/Inf, or its softmax denominator is
    non-finite or non-positive (an all-`-inf` row would sample from a
    zero-mass distribution — as corrupt as a NaN, and invisible to a
    plain isfinite check on the argmax path). A few extra reductions
    FOLDED into the caller's already-compiled step — never a second
    trace, never a second pass over the activations. `logits` is
    [..., V]; the result drops the vocab axis."""
    finite = jnp.isfinite(logits).all(axis=-1)
    # softmax denominator at the sampling dtype: max-subtracted like
    # jax.random.categorical itself, so the reduction traps exactly
    # the distribution the sampler would draw from
    f32 = logits.astype(jnp.float32)
    denom = jnp.sum(jnp.exp(f32 - jnp.max(f32, axis=-1, keepdims=True)),
                    axis=-1)
    return ~finite | ~jnp.isfinite(denom) | (denom <= 0.0)


def logit_amax(logits, mask=None):
    """Scalar max-|logit| over the (optionally masked) rows — the
    serving sentinel's EWMA signal (ISSUE 15): wrong-but-FINITE compute
    (a flipped exponent bit, a corrupted weight tile) usually shows as
    a magnitude excursion long before anything goes NaN. Masked rows
    (dead slots) contribute 0. Folded into the compiled step like
    `logits_trap`."""
    a = jnp.max(jnp.abs(logits.astype(jnp.float32)), axis=-1)
    if mask is not None:
        while mask.ndim < a.ndim:
            mask = mask[..., None]
        a = jnp.where(mask, a, 0.0)
    return jnp.max(a)


def decode_window_retire(alive, nxt, pos, limits, eos_ids):
    """In-window retirement mask for the megabatch decode scan (ISSUE
    19) — the branch-free device mirror of the host scheduler's
    `_emit` rule, applied per scan iteration so a K-token window
    retires slots exactly where the sequential host loop would:

      * a slot that samples its EOS token this iteration emits that
        token and goes dead for the REST of the window (EOS itself is
        kept — same as the host, which appends then retires);
      * a slot whose advanced position reaches ``limits - 1`` (i.e. it
        has now emitted ``max_new_tokens`` tokens, the host's
        ``len(tokens) >= max_new_tokens`` budget rule at the decode
        invariant ``pos = T0 + len(tokens) - 1``) emits that final
        token and parks;
      * dead slots do not advance — their position is frozen so the
        caller's ``where(alive, pos, out_of_range)`` parking keeps all
        of their remaining scatter writes out of range, and their
        emitted lane carries the ``-1`` padding the host discards.

    ``eos_ids`` is a per-slot int32 band with ``-1`` meaning "no EOS
    configured" (the ``>= 0`` guard below), so a vocab-less sentinel
    never matches a real token. Pure element-wise jnp — safe inside
    any traced scan body, no data-dependent Python branching."""
    live = alive.astype(jnp.int32)
    npos = pos + live
    hit_eos = (eos_ids >= 0) & (nxt == eos_ids)
    nalive = alive & ~hit_eos & (npos < limits - 1)
    return nalive, npos


def paged_block_fingerprint(cache, bid):
    """Folded-f32 checksum of ONE physical KV block across every layer
    and cache band (payload rows AND, on a quantized pool, the
    per-head scale side-bands) — the ISSUE 15 fingerprint op. Rides
    the block-id addressing exactly like PR 14's quant scales: the
    caller hands a physical block id, the reduction reads
    `buf[bid]` per band. Position-weighted (element index mod a small
    prime) so a transposition inside the block moves the sum, and
    per-band/per-layer folded with distinct multipliers so a value
    migrating between K and V (or between layers) cannot cancel.
    Deterministic for fixed shapes on a fixed backend — the engine
    compares a recomputed fingerprint against the one committed when
    the block closed, so only run-to-run determinism matters, never
    cross-backend bit equality. Cheap: one pass over a single block's
    bytes, jitted ONCE by the engine (a new trace would violate the
    one-compiled-step discipline the serving tests pin)."""
    acc = jnp.float32(0.0)
    for li, kv in enumerate(cache):
        for bi, band in enumerate(sorted(kv)):
            x = kv[band][bid].astype(jnp.float32).reshape(-1)
            w = (jnp.arange(x.shape[0], dtype=jnp.float32) % 97.0) + 1.0
            fold = jnp.float32(1.0 + 0.013 * (li * 7 + bi + 1))
            acc = acc + jnp.sum(x * w) * fold
    return acc


__all__ += ["init_paged_kv_cache", "paged_decode_step",
            "paged_prefill_chunk", "paged_verify_step",
            "kv_storage_dtype", "kv_block_bytes",
            "logits_trap", "logit_amax", "paged_block_fingerprint",
            "decode_window_retire"]


def generate(params, prompt, cfg: TransformerConfig, max_new_tokens,
             temperature=0.0, key=None, max_len=None, eos_id=None):
    """Autoregressive generation: prefill the prompt [B, T0], then
    `max_new_tokens` cached decode steps inside ONE compiled loop (the
    host never re-enters it). temperature<=0 is greedy; otherwise
    softmax sampling with `key`. Returns [B, T0+max_new].

    `eos_id` opts into the reference's end-of-sequence semantics
    (RecurrentGradientMachine.h:309): a row that emits eos_id freezes
    (keeps re-emitting eos), and the loop EXITS EARLY once every row is
    done — a lax.while_loop instead of the fixed-trip scan, with the
    unwritten tail back-filled with eos (identical to what the frozen
    rows would have produced). Default None keeps the fixed-trip
    free-running behavior."""
    B, T0 = prompt.shape
    L = int(max_len or cfg.max_len)
    # the positional table bounds every position regardless of cache
    # size — JAX gather would silently clamp out-of-range indices
    L = min(L, int(params["pos"].shape[0]))
    if T0 + max_new_tokens > L:
        raise ValueError(
            "generate needs T0+max_new <= max_len (%d + %d > %d, "
            "positional table %d)"
            % (T0, max_new_tokens, L, int(params["pos"].shape[0]))
        )
    if temperature > 0 and key is None:
        raise ValueError("sampling (temperature > 0) requires `key`")
    logits, cache = prefill(params, prompt, cfg, max_len=L)
    key = key if key is not None else jax.random.PRNGKey(0)

    def pick(logits, k):
        if temperature <= 0:
            return jnp.argmax(logits, axis=-1).astype(prompt.dtype)
        return jax.random.categorical(
            k, logits.astype(jnp.float32) / temperature, axis=-1
        ).astype(prompt.dtype)

    def body(carry, i):
        logits, cache, k = carry
        k, sub = jax.random.split(k)
        tok = pick(logits, sub)
        logits, cache = decode_step(params, tok, T0 + i, cache, cfg)
        return (logits, cache, k), tok

    if eos_id is None:
        (_, _, _), toks = jax.lax.scan(
            body, (logits, cache, key), jnp.arange(max_new_tokens)
        )
        return jnp.concatenate([prompt, toks.T], axis=1)

    # eos semantics + early exit: buffer writes under lax.while_loop
    eos = jnp.asarray(eos_id, prompt.dtype)
    buf0 = jnp.zeros((B, max_new_tokens), prompt.dtype)

    def w_cond(state):
        i, alive, _, _, _, _ = state
        return (i < max_new_tokens) & jnp.any(alive)

    def w_body(state):
        i, alive, buf, logits, cache, k = state
        k, sub = jax.random.split(k)
        tok = pick(logits, sub)
        tok = jnp.where(alive, tok, eos)  # frozen rows re-emit eos
        buf = jax.lax.dynamic_update_index_in_dim(buf, tok, i, axis=1)
        alive = alive & (tok != eos)
        logits, cache = decode_step(params, tok, T0 + i, cache, cfg)
        return i + 1, alive, buf, logits, cache, k

    state = (
        jnp.asarray(0),
        jnp.ones((B,), bool),
        buf0,
        logits,
        cache,
        key,
    )
    steps_done, alive, buf, _, _, _ = jax.lax.while_loop(
        w_cond, w_body, state
    )
    # unwritten tail (all rows were done): exactly eos
    fill = jnp.arange(max_new_tokens)[None, :] >= steps_done
    buf = jnp.where(fill, eos, buf)
    if not isinstance(steps_done, jax.core.Tracer):
        LAST_DECODE_STATS["greedy_steps_executed"] = int(steps_done)
        LAST_DECODE_STATS["greedy_max_steps"] = int(max_new_tokens)
    return jnp.concatenate([prompt, buf], axis=1)


__all__ += ["init_kv_cache", "decode_step", "prefill", "prefill_chunk",
            "generate"]


# diagnostics of the last eager beam_search_generate call: executed vs
# maximum decode steps (early exit stops at all-beams-dead)
LAST_DECODE_STATS = {}


def beam_search_generate(params, prompt, cfg: TransformerConfig,
                         max_new_tokens, beam_size=4, alpha=0.0,
                         max_len=None):
    """Beam-search generation over the KV cache (the transformer
    counterpart of the legacy RecurrentGradientMachine beam decode,
    RecurrentGradientMachine.h:309, kernels_control.py beam_search).

    Beams live flattened on the batch dim ([B*W, ...]) so every decode
    step is the SAME cached computation greedy uses; after top-k the
    caches gather along the beam dim by parent index. Finished beams
    (emitted eos) freeze: they re-emit eos with their frozen score.
    Returns (tokens [B, W, T0+max_new], scores [B, W]) sorted best
    first; alpha applies GNMT length normalisation at the final sort.
    eos is cfg.vocab - 1 by convention of this toy-vocab family.
    """
    B, T0 = prompt.shape
    W = int(beam_size)
    if max_new_tokens < 1:
        raise ValueError("beam_search_generate needs max_new_tokens >= 1")
    L = min(int(max_len or cfg.max_len), int(params["pos"].shape[0]))
    if T0 + max_new_tokens > L:
        raise ValueError(
            "beam_search_generate needs T0+max_new <= max_len "
            "(%d + %d > %d)" % (T0, max_new_tokens, L)
        )
    eos = cfg.vocab - 1

    logits, cache = prefill(params, prompt, cfg, max_len=L)  # [B, V]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    # seed beams from the prompt's top-W first tokens
    top_lp, top_tok = jax.lax.top_k(logp, W)  # [B, W]

    def tile_beam(x):
        return jnp.repeat(x, W, axis=0)  # [B*W, ...]

    cache = jax.tree_util.tree_map(tile_beam, cache)
    # fixed-size token buffer [B, W, T0+max_new]: scan carries must keep
    # their shape, so steps write in place instead of concatenating
    T_out = T0 + max_new_tokens
    tokens = jnp.zeros((B, W, T_out), prompt.dtype)
    tokens = tokens.at[:, :, :T0].set(tile_beam(prompt).reshape(B, W, T0))
    tokens = tokens.at[:, :, T0].set(top_tok)
    scores = top_lp  # [B, W] cumulative logprob
    alive = top_tok != eos  # [B, W]
    V = cfg.vocab

    def body(carry, i):
        tokens, scores, alive, cache = carry
        pos = T0 + i  # position of the newest written token
        last = jax.lax.dynamic_index_in_dim(
            tokens, pos, axis=2, keepdims=False
        ).reshape(B * W)
        lg, cache = decode_step(params, last, pos, cache, cfg)
        lp = jax.nn.log_softmax(lg.astype(jnp.float32), -1).reshape(B, W, V)
        # frozen beams contribute exactly one continuation: eos at zero
        # added cost (their score must not change or multiply)
        frozen_row = jnp.full((V,), -jnp.inf).at[eos].set(0.0)
        lp = jnp.where(alive[..., None], lp, frozen_row[None, None])
        cand = scores[..., None] + lp  # [B, W, V]
        flat = cand.reshape(B, W * V)
        new_scores, idx = jax.lax.top_k(flat, W)  # [B, W]
        parent = idx // V  # [B, W] which beam it extends
        tok = idx % V
        # reorder histories + caches by parent beam, write the new token
        tokens = jnp.take_along_axis(
            tokens, parent[..., None], axis=1
        )
        tokens = jax.lax.dynamic_update_index_in_dim(
            tokens, tok, pos + 1, axis=2
        )
        alive = (
            jnp.take_along_axis(alive, parent, axis=1) & (tok != eos)
        )
        gather = (
            parent + jnp.arange(B)[:, None] * W
        ).reshape(B * W)  # flat indices into [B*W]

        def reorder(c):
            return c[gather]

        cache = jax.tree_util.tree_map(reorder, cache)
        return (tokens, new_scores, alive, cache), None

    # early exit (reference RecurrentGradientMachine.h:309): stop the
    # moment every beam of every source has emitted eos. lax.while_loop
    # instead of a fixed-trip scan; positions past the exit step are
    # back-filled with eos — exactly what the skipped iterations would
    # have written (dead beams re-emit eos at frozen score), so the
    # result is bit-identical to the full schedule.
    def w_cond(state):
        i, carry = state
        _, _, alive_c, _ = carry
        return (i < max_new_tokens - 1) & jnp.any(alive_c)

    def w_body(state):
        i, carry = state
        carry, _ = body(carry, i)
        return i + 1, carry

    steps_done, (tokens, scores, alive, _) = jax.lax.while_loop(
        w_cond, w_body, (jnp.asarray(0), (tokens, scores, alive, cache))
    )
    # positions beyond the last written token (T0 + steps_done) hold the
    # zero-init; the skipped all-dead steps would have written eos
    fill = jnp.arange(T_out) > (T0 + steps_done)
    tokens = jnp.where(fill[None, None, :], jnp.asarray(eos, tokens.dtype),
                       tokens)
    if not isinstance(steps_done, jax.core.Tracer):
        LAST_DECODE_STATS["steps_executed"] = int(steps_done)
        LAST_DECODE_STATS["max_steps"] = int(max_new_tokens - 1)
    # GNMT length penalty: ((5 + len) / 6)^alpha
    lens = (tokens[:, :, T0:] != eos).sum(-1) + 1
    penal = jnp.power((5.0 + lens.astype(jnp.float32)) / 6.0, alpha)
    final = scores / penal  # penal > 0 always (lens >= 1)
    order = jnp.argsort(-final, axis=1)
    tokens = jnp.take_along_axis(tokens, order[..., None], axis=1)
    final = jnp.take_along_axis(final, order, axis=1)
    return tokens, final


__all__ += ["beam_search_generate"]
