"""MNIST conv net (reference book test recognize_digits_conv:
python/paddle/v2/fluid/tests/book/test_recognize_digits.py)."""

from __future__ import annotations

from ..fluid import layers, nets


def lenet(images, class_dim=10):
    """conv-pool x2 + fc softmax head, NCHW [N,1,28,28]."""
    conv_pool_1 = nets.simple_img_conv_pool(
        input=images,
        filter_size=5,
        num_filters=20,
        pool_size=2,
        pool_stride=2,
        act="relu",
    )
    conv_pool_2 = nets.simple_img_conv_pool(
        input=conv_pool_1,
        filter_size=5,
        num_filters=50,
        pool_size=2,
        pool_stride=2,
        act="relu",
    )
    return layers.fc(input=conv_pool_2, size=class_dim, act="softmax")


def mlp(images, class_dim=10):
    """3-layer MLP head (reference recognize_digits_mlp)."""
    hidden1 = layers.fc(input=images, size=128, act="relu")
    hidden2 = layers.fc(input=hidden1, size=64, act="relu")
    return layers.fc(input=hidden2, size=class_dim, act="softmax")
