"""SSD single-shot detector (reference capability: the gserver SSD stack
— PriorBoxLayer.cpp, MultiBoxLossLayer.cpp, DetectionOutputLayer.cpp —
and the era's caffe-style SSD configs). A compact TPU-first build: small
conv backbone, two detection feature maps, per-map loc/conf conv heads,
prior boxes concatenated across maps, trained with ssd_loss and served
through detection_output (decode + multiclass NMS).

Everything is static-shape: priors per image are fixed by the feature
map geometry, ground truth rides packed [G, 4] + LoD exactly like every
other ragged feed, so one XLA program covers any batch composition.
"""

from __future__ import annotations

from ..fluid import layers

__all__ = ["ssd_lite", "ssd_detector"]


def _backbone(image):
    """Three conv stages; returns the two detection feature maps."""
    c1 = layers.conv2d(
        input=image, num_filters=16, filter_size=3, padding=1, act="relu"
    )
    p1 = layers.pool2d(input=c1, pool_size=2, pool_stride=2)
    c2 = layers.conv2d(
        input=p1, num_filters=32, filter_size=3, padding=1, act="relu"
    )
    p2 = layers.pool2d(input=c2, pool_size=2, pool_stride=2)  # stride 4
    c3 = layers.conv2d(
        input=p2, num_filters=64, filter_size=3, padding=1, act="relu"
    )
    p3 = layers.pool2d(input=c3, pool_size=2, pool_stride=2)  # stride 8
    return p2, p3


def _head(feat, n_priors, num_classes, batch):
    """Loc + conf conv heads over one feature map, flattened to
    [N, HW*priors, 4] / [N, HW*priors, C]."""
    loc = layers.conv2d(
        input=feat, num_filters=n_priors * 4, filter_size=3, padding=1
    )
    conf = layers.conv2d(
        input=feat, num_filters=n_priors * num_classes, filter_size=3,
        padding=1,
    )
    h, w = feat.shape[2], feat.shape[3]

    def _flat(t, last):
        t = layers.transpose(t, [0, 2, 3, 1])
        return layers.reshape(t, [batch, int(h) * int(w) * n_priors, last])

    return _flat(loc, 4), _flat(conf, num_classes)


def ssd_lite(image, num_classes, image_size, batch, min_sizes=(0.2, 0.45)):
    """Build the SSD graph over `image` [N,3,S,S].

    Returns (loc [N,P,4], conf [N,P,C], priors [P,4], prior_vars [P,4]).
    """
    f1, f2 = _backbone(image)
    heads, priors, prior_vars = [], [], []
    for feat, ms in ((f1, min_sizes[0]), (f2, min_sizes[1])):
        # priors per location: min_size x {1, 2, 1/2 aspect} = 3
        box, var = layers.prior_box(
            input=feat,
            image=image,
            min_sizes=[ms * image_size],
            aspect_ratios=[2.0],
            flip=True,
            clip=True,
            variance=[0.1, 0.1, 0.2, 0.2],
        )
        n_priors = int(box.shape[2])  # [H, W, P, 4] static layer shape
        loc, conf = _head(feat, n_priors, num_classes, batch)
        heads.append((loc, conf))
        priors.append(layers.reshape(box, [-1, 4]))
        prior_vars.append(layers.reshape(var, [-1, 4]))
    loc = layers.concat([h[0] for h in heads], axis=1)
    conf = layers.concat([h[1] for h in heads], axis=1)
    pb = layers.concat(priors, axis=0)
    pbv = layers.concat(prior_vars, axis=0)
    return loc, conf, pb, pbv


def ssd_detector(image, gt_box, gt_label, num_classes, image_size, batch):
    """Training head: per-image multibox loss (mean over the batch) plus
    the eval detections [label, score, x1, y1, x2, y2]."""
    loc, conf, pb, pbv = ssd_lite(image, num_classes, image_size, batch)
    cost = layers.ssd_loss(
        location=loc, confidence=conf, gt_box=gt_box, gt_label=gt_label,
        prior_box=pb, prior_box_var=pbv,
    )
    avg_cost = layers.mean(x=cost)
    # class probabilities: softmax over the CLASS dim of [N, P, C], then
    # to the [N, C, P] layout multiclass_nms consumes
    scores = layers.transpose(layers.softmax(conf), [0, 2, 1])
    detections = layers.detection_output(
        scores=scores, loc=loc, prior_box=pb, prior_box_var=pbv,
        score_threshold=0.1, nms_threshold=0.45, keep_top_k=8,
    )
    return avg_cost, detections
