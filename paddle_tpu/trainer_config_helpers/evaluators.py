"""Evaluator DSL wrappers (reference
trainer_config_helpers/evaluators.py): metric nodes attached to the
config graph; the trainer fetches them per batch and v2.trainer.test()
accumulates them with the right semantics (weighted mean for ratio
metrics, running totals for sums — v2/trainer.py).

Each wrapper builds a lazy Layer node; v2/topology.py lowers it onto the
fluid metric kernels (accuracy, auc, precision_recall, chunk_eval,
edit_distance, detection_map, pnpair_eval).
"""

from __future__ import annotations

from ..v2.layer import Layer, _as_list

__all__ = [
    "evaluator_base",
    "classification_error_evaluator",
    "auc_evaluator",
    "pnpair_evaluator",
    "precision_recall_evaluator",
    "ctc_error_evaluator",
    "chunk_evaluator",
    "sum_evaluator",
    "column_sum_evaluator",
    "detection_map_evaluator",
    "value_printer_evaluator",
    "gradient_printer_evaluator",
    "maxid_printer_evaluator",
    "maxframe_printer_evaluator",
    "seqtext_printer_evaluator",
    "classification_error_printer_evaluator",
]


def classification_error_evaluator(input, label, name=None, top_k=1,
                                   **kwargs):
    """error = 1 - top_k accuracy (reference evaluators.py:220)."""
    return Layer("classification_error_evaluator", name,
                 _as_list(input) + _as_list(label), {"top_k": top_k})


def auc_evaluator(input, label, name=None, **kwargs):
    return Layer("auc_evaluator", name,
                 _as_list(input) + _as_list(label), {})


def sum_evaluator(input, name=None, **kwargs):
    return Layer("sum_evaluator", name, _as_list(input), {})


def column_sum_evaluator(input, name=None, **kwargs):
    return Layer("column_sum_evaluator", name, _as_list(input), {})


def precision_recall_evaluator(input, label, positive_label=None,
                               name=None, **kwargs):
    """Macro-averaged F1 over classes, or the positive class's F1 when
    `positive_label` is given (reference PrecisionRecallEvaluator)."""
    return Layer("precision_recall_evaluator", name,
                 _as_list(input) + _as_list(label),
                 {"positive_label": positive_label})


def pnpair_evaluator(input, label, query_id, weight=None, name=None,
                     **kwargs):
    """Within-query positive/negative pair ranking ratio (reference
    PnpairEvaluator); pairs weight by w_i * w_j when `weight` given."""
    parents = [_as_list(input)[0], _as_list(label)[0],
               _as_list(query_id)[0]]
    if weight is not None:
        parents.append(_as_list(weight)[0])
    return Layer("pnpair_evaluator", name, parents,
                 {"weighted": weight is not None})


def ctc_error_evaluator(input, label, name=None, **kwargs):
    """Normalised edit distance between the CTC greedy decode of `input`
    and `label` (reference CTCErrorEvaluator)."""
    return Layer("ctc_error_evaluator", name,
                 [_as_list(input)[0], _as_list(label)[0]], {})


def chunk_evaluator(input, label, chunk_scheme, num_chunk_types,
                    name=None, excluded_chunk_types=None, **kwargs):
    """Chunking F1 (reference ChunkEvaluator): decoded tag sequence vs
    label under an IOB/IOE/IOBES scheme."""
    return Layer("chunk_evaluator", name,
                 [_as_list(input)[0], _as_list(label)[0]], {
                     "chunk_scheme": chunk_scheme,
                     "num_chunk_types": num_chunk_types,
                     "excluded_chunk_types": excluded_chunk_types,
                 })


def detection_map_evaluator(input, label, overlap_threshold=0.5,
                            background_id=0, num_classes=None, name=None,
                            **kwargs):
    """Per-batch VOC mAP over detection_output rows (reference
    DetectionMAPEvaluator; graph form of fluid/evaluator.py
    DetectionMAP). `input` is a detection_output_layer node; `label`
    the ground-truth sequence ([class, x1, y1, x2, y2(, difficult)]
    rows per image)."""
    return Layer("detection_map_evaluator", name,
                 [_as_list(input)[0], _as_list(label)[0]], {
                     "overlap_threshold": overlap_threshold,
                     "background_id": background_id,
                     "num_classes": num_classes,
                 })


def evaluator_base(input, type=None, label=None, name=None, **kwargs):
    """Generic dispatch by evaluator type string (reference
    evaluator_base): routes onto the concrete wrappers above."""
    table = {
        "classification_error": classification_error_evaluator,
        "last-column-auc": auc_evaluator,
        "sum": sum_evaluator,
        "last-column-sum": column_sum_evaluator,
        "precision_recall": precision_recall_evaluator,
    }
    fn = table.get(type)
    if fn is None:
        raise ValueError("unknown evaluator type %r" % type)
    if label is not None:
        return fn(input=input, label=label, name=name, **kwargs)
    return fn(input=input, name=name, **kwargs)


def _printer(kind):
    def wrapper(input, name=None, **kwargs):
        return Layer(kind, name, _as_list(input), {})

    wrapper.__name__ = kind + "_evaluator"
    wrapper.__doc__ = (
        "Debug printer (reference %sPrinter): identity node whose value "
        "the trainer logs per batch — on TPU the fetch itself is the "
        "print." % kind
    )
    return wrapper


value_printer_evaluator = _printer("printer")
gradient_printer_evaluator = _printer("printer")
maxid_printer_evaluator = _printer("maxid_printer")
maxframe_printer_evaluator = _printer("printer")
classification_error_printer_evaluator = _printer("printer")


def seqtext_printer_evaluator(input, result_file, id_input=None,
                              dict_file=None, name=None, **kwargs):
    """Write generated id sequences as dictionary words to result_file
    (reference SequenceTextPrinter, evaluators.py:697 — result_file is
    the required second positional): the trainer CLI's generation job
    consumes the recorded (dict_file, result_file) pair after decoding
    (trainer/__init__.py run_config)."""
    from . import get_config_state

    if not isinstance(result_file, str):
        raise TypeError(
            "seqtext_printer_evaluator(input, result_file, ...): "
            "result_file must be a path string, got %r" % (result_file,)
        )
    if id_input is not None and isinstance(id_input, str):
        raise TypeError("id_input must be a layer, not a string")
    node = Layer("printer", name, _as_list(input), {})
    get_config_state().setdefault("seqtext_printers", []).append({
        "input": _as_list(input)[0].name,
        "id_input": _as_list(id_input)[0].name if id_input is not None
        else None,
        "dict_file": dict_file,
        "result_file": result_file,
    })
    return node
