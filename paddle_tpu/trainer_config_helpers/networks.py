"""Composite network helpers (reference
python/paddle/trainer_config_helpers/networks.py): pure compositions
over the layer DSL — conv/pool blocks, separable conv, text conv, GRU /
LSTM units and groups, bidirectional RNNs, attention blocks, and the
VGG reference nets.

Every helper lowers onto existing DSL wrappers (one fluid Program, one
fused XLA computation) — there is no new kernel surface here.

Attention note (documented divergence): simple_attention /
dot_product_attention / multi_head_attention compose at the SEQUENCE
level — the query ("decoder state") is a per-sequence vector expanded
over the attended sequence. The reference calls these inside a
recurrent_group step with the source as a StaticInput sequence; here
the equivalent in-step decoder path is the scan-lowered DynamicRNN
(tests/test_machine_translation.py).
"""

from __future__ import annotations

import paddle_tpu.trainer_config_helpers as tch

__all__ = [
    "simple_img_conv_pool", "img_conv_bn_pool", "img_separable_conv",
    "sequence_conv_pool", "text_conv_pool",
    "simple_gru", "simple_gru2", "gru_unit", "gru_group",
    "lstmemory_unit", "lstmemory_group",
    "bidirectional_gru", "bidirectional_lstm",
    "simple_attention", "dot_product_attention", "multi_head_attention",
    "small_vgg", "vgg_16_network", "inputs", "outputs",
]

outputs = tch.outputs


def inputs(layers, *args):
    """Declare feed order from layer nodes (reference networks.py
    inputs())."""
    nodes = tch._as_list(layers) + list(args)
    tch.Inputs(*[n.name for n in nodes])


# ---------------------------------------------------------------------
# image blocks
# ---------------------------------------------------------------------


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         name=None, pool_type=None, act=None, groups=1,
                         conv_stride=1, conv_padding=0, bias_attr=None,
                         num_channel=None, param_attr=None,
                         pool_stride=1, pool_padding=0, **kwargs):
    """conv -> pool (reference networks.py simple_img_conv_pool)."""
    conv = tch.img_conv_layer(
        input=input, filter_size=filter_size, num_filters=num_filters,
        num_channels=num_channel, stride=conv_stride,
        padding=conv_padding, groups=groups, act=act,
        bias_attr=bias_attr, param_attr=param_attr,
        name=None if name is None else name + "_conv",
    )
    return tch.img_pool_layer(
        input=conv, pool_size=pool_size, stride=pool_stride,
        padding=pool_padding, pool_type=pool_type, name=name,
    )


def img_conv_bn_pool(input, filter_size, num_filters, pool_size,
                     name=None, pool_type=None, act=None, groups=1,
                     conv_stride=1, conv_padding=0, conv_bias_attr=None,
                     num_channel=None, conv_param_attr=None,
                     pool_stride=1, pool_padding=0, **kwargs):
    """conv -> batch_norm(act) -> pool (reference img_conv_bn_pool)."""
    conv = tch.img_conv_layer(
        input=input, filter_size=filter_size, num_filters=num_filters,
        num_channels=num_channel, stride=conv_stride,
        padding=conv_padding, groups=groups, act=None,
        bias_attr=conv_bias_attr, param_attr=conv_param_attr,
        name=None if name is None else name + "_conv",
    )
    bn = tch.batch_norm_layer(input=conv, act=act)
    return tch.img_pool_layer(
        input=bn, pool_size=pool_size, stride=pool_stride,
        padding=pool_padding, pool_type=pool_type, name=name,
    )


def img_separable_conv(input, num_channels, num_out_channels, filter_size,
                       stride=1, padding=0, depth_multiplier=1, act=None,
                       bias_attr=None, param_attr=None, name=None,
                       **kwargs):
    """Depthwise conv (groups = channels) then 1x1 pointwise conv
    (reference img_separable_conv)."""
    depthwise = tch.img_conv_layer(
        input=input, filter_size=filter_size,
        num_filters=num_channels * depth_multiplier,
        num_channels=num_channels, stride=stride, padding=padding,
        groups=num_channels, act=None, bias_attr=bias_attr,
        param_attr=param_attr,
        name=None if name is None else name + "_dw",
    )
    return tch.img_conv_layer(
        input=depthwise, filter_size=1, num_filters=num_out_channels,
        stride=1, padding=0, act=act, bias_attr=bias_attr,
        param_attr=param_attr, name=name,
    )


def sequence_conv_pool(input, context_len, hidden_size, name=None,
                       context_start=None, pool_type=None,
                       fc_param_attr=None, fc_bias_attr=None,
                       fc_act=None, **kwargs):
    """Context window -> fc -> sequence pool (reference
    sequence_conv_pool): the text-convolution block of the sentiment /
    text-classification configs."""
    with tch.mixed_layer(
        size=hidden_size,
        name=None if name is None else name + "_conv",
    ) as m:
        m += tch.context_projection(
            input=input, context_len=context_len,
            context_start=context_start,
        )
    fc = tch.fc_layer(
        input=m, size=hidden_size,
        act=fc_act or tch.TanhActivation(),
        param_attr=fc_param_attr, bias_attr=fc_bias_attr,
    )
    return tch.pooling_layer(
        input=fc, pooling_type=pool_type or tch.MaxPooling(), name=name,
    )


text_conv_pool = sequence_conv_pool


# ---------------------------------------------------------------------
# recurrent units / groups
# ---------------------------------------------------------------------


def simple_gru(input, size, name=None, reverse=False,
               mixed_param_attr=None, mixed_bias_param_attr=None,
               gru_bias_attr=None, gru_param_attr=None, act=None,
               gate_act=None, **kwargs):
    """3H input projection + fused GRU recurrence (reference
    simple_gru = mixed_layer + grumemory)."""
    with tch.mixed_layer(
        size=size * 3, bias_attr=mixed_bias_param_attr,
        name=None if name is None else name + "_transform",
    ) as m:
        m += tch.full_matrix_projection(
            input=input, param_attr=mixed_param_attr,
        )
    return tch.grumemory(input=m, size=size, reverse=reverse, name=name)


def simple_gru2(input, size, name=None, reverse=False,
                mixed_param_attr=None, mixed_bias_attr=None,
                gru_param_attr=None, gru_bias_attr=None, act=None,
                gate_act=None, **kwargs):
    """Identical math to simple_gru (the reference variant differs only
    in parameter layout for speed, networks.py simple_gru2)."""
    return simple_gru(
        input=input, size=size, name=name, reverse=reverse,
        mixed_param_attr=mixed_param_attr,
        mixed_bias_param_attr=mixed_bias_attr,
        gru_bias_attr=gru_bias_attr, gru_param_attr=gru_param_attr,
        act=act, gate_act=gate_act,
    )


def gru_unit(input, memory_boot=None, size=None, name=None,
             gru_bias_attr=None, gru_param_attr=None, act=None,
             gate_act=None, naive=False, **kwargs):
    """One GRU step with its own output memory — use inside a
    recurrent_group step function (reference gru_unit)."""
    out_name = name or tch.Layer("gru_unit_anchor", None, [], {}).name
    mem = tch.memory(name=out_name, size=size, boot_layer=memory_boot)
    return tch.gru_step_layer(
        input=input, output_mem=mem, size=size, name=out_name,
        act=act, gate_act=gate_act, param_attr=gru_param_attr,
        bias_attr=gru_bias_attr,
    )


def gru_group(input, memory_boot=None, size=None, name=None,
              reverse=False, gru_bias_attr=None, gru_param_attr=None,
              act=None, gate_act=None, naive=False, **kwargs):
    """recurrent_group wrapping gru_unit (reference gru_group): the
    step-level form of a GRU over a sequence (already 3H-projected)."""

    def step(x):
        return gru_unit(
            input=x, memory_boot=memory_boot, size=size,
            name=None if name is None else name + "_unit",
            gru_bias_attr=gru_bias_attr, gru_param_attr=gru_param_attr,
            act=act, gate_act=gate_act,
        )

    return recurrent_group_alias(step, input, reverse=reverse, name=name)


# recurrent_group is imported lazily so the module can be star-imported
# into configs without shadowing
def recurrent_group_alias(step, input, reverse=False, name=None):
    return tch.recurrent_group(step, input, reverse=reverse, name=name)


def lstmemory_unit(input, out_memory=None, name=None, size=None,
                   param_attr=None, act=None, gate_act=None,
                   state_act=None, input_proj_bias_attr=None,
                   lstm_bias_attr=None, **kwargs):
    """One LSTM step with hidden+cell memories — use inside a
    recurrent_group step (reference lstmemory_unit): the input and the
    previous hidden are projected to 4H, the cell rides a second memory
    closed by get_output_layer(..., 'state')."""
    if size is None:
        raise ValueError("lstmemory_unit needs an explicit size")
    out_name = name or tch.Layer("lstm_unit_anchor", None, [], {}).name
    if out_memory is None:
        out_mem = tch.memory(name=out_name, size=size)
    else:
        out_mem = out_memory
    state_mem = tch.memory(name=out_name + "_state", size=size)
    # two projections of DIFFERENT input widths: a shared ParamAttr
    # name would alias one weight for both — derive distinct names
    pa_in = pa_rec = None
    if param_attr is not None:
        base = getattr(param_attr, "name", None)
        pa_in = tch.ParamAttr(name=(base + "_in") if base else None)
        pa_rec = tch.ParamAttr(name=(base + "_rec") if base else None)
    with tch.mixed_layer(
        size=size * 4, bias_attr=input_proj_bias_attr,
        name=out_name + "_input_proj",
    ) as m:
        m += tch.full_matrix_projection(input=input, param_attr=pa_in)
        m += tch.full_matrix_projection(input=out_mem, param_attr=pa_rec)
    step_l = tch.lstm_step_layer(
        input=m, state=state_mem, size=size, name=out_name,
        act=act, gate_act=gate_act, state_act=state_act,
    )
    tch.get_output_layer(input=step_l, arg_name="state",
                         name=out_name + "_state")
    return step_l


def lstmemory_group(input, size=None, name=None, out_memory=None,
                    reverse=False, param_attr=None, act=None,
                    gate_act=None, state_act=None,
                    input_proj_bias_attr=None, lstm_bias_attr=None,
                    **kwargs):
    """recurrent_group wrapping lstmemory_unit (reference
    lstmemory_group)."""

    def step(x):
        return lstmemory_unit(
            input=x, out_memory=out_memory, size=size,
            name=None if name is None else name + "_unit",
            param_attr=param_attr, act=act, gate_act=gate_act,
            state_act=state_act,
            input_proj_bias_attr=input_proj_bias_attr,
            lstm_bias_attr=lstm_bias_attr,
        )

    return recurrent_group_alias(step, input, reverse=reverse, name=name)


def bidirectional_gru(input, size, name=None, return_seq=False, **kwargs):
    """Forward + backward simple_gru, concatenated (reference
    bidirectional_gru): last fwd step + first bwd step when
    return_seq=False, full sequences otherwise."""
    fwd = simple_gru(input=input, size=size, reverse=False,
                     name=None if name is None else name + "_fwd")
    bwd = simple_gru(input=input, size=size, reverse=True,
                     name=None if name is None else name + "_bwd")
    if return_seq:
        return tch.concat_layer(input=[fwd, bwd], name=name)
    return tch.concat_layer(
        input=[tch.last_seq(input=fwd), tch.first_seq(input=bwd)],
        name=name,
    )


def bidirectional_lstm(input, size, name=None, return_seq=False,
                       **kwargs):
    """Forward + backward simple_lstm, concatenated (reference
    bidirectional_lstm)."""
    fwd = tch.simple_lstm(input=input, size=size,
                          name=None if name is None else name + "_fwd")
    bwd = tch.simple_lstm(input=input, size=size, reverse=True,
                          name=None if name is None else name + "_bwd")
    if return_seq:
        return tch.concat_layer(input=[fwd, bwd], name=name)
    return tch.concat_layer(
        input=[tch.last_seq(input=fwd), tch.first_seq(input=bwd)],
        name=name,
    )


# ---------------------------------------------------------------------
# attention blocks (sequence-level — see module docstring)
# ---------------------------------------------------------------------


def _node_width(node):
    """Feature width of a DSL node (size attr, or a data layer's dim)."""
    a = getattr(node, "attrs", {})
    if a.get("size"):
        return int(a["size"])
    t = a.get("type")
    if t is not None:
        return int(t.dim)
    if getattr(node, "parents", None):
        return _node_width(node.parents[0])
    raise ValueError("cannot infer width of %r" % node)


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     transform_param_attr=None, softmax_param_attr=None,
                     weight_act=None, name=None, **kwargs):
    """Bahdanau additive attention (reference simple_attention):
    tanh(W s + encoded_proj) -> per-step scalar -> sequence softmax ->
    weighted sum of encoded_sequence."""
    proj_size = _node_width(encoded_proj)
    with tch.mixed_layer(
        size=proj_size,
        name=None if name is None else name + "_transform",
    ) as state_proj:
        state_proj += tch.full_matrix_projection(
            input=decoder_state, param_attr=transform_param_attr,
        )
    expanded = tch.expand_layer(input=state_proj,
                                expand_as=encoded_proj)
    combined = tch.addto_layer(input=[expanded, encoded_proj],
                               act=tch.TanhActivation())
    weight = tch.fc_layer(
        input=combined, size=1,
        act=weight_act or tch.SequenceSoftmaxActivation(),
        param_attr=softmax_param_attr, bias_attr=False,
        name=None if name is None else name + "_weight",
    )
    scaled = tch.scaling_layer(input=encoded_sequence, weight=weight)
    return tch.pooling_layer(input=scaled,
                             pooling_type=tch.SumPooling(), name=name)


def dot_product_attention(encoded_sequence, attended_sequence,
                          transformed_state, softmax_param_attr=None,
                          name=None, **kwargs):
    """Dot-product attention (reference dot_product_attention): scores
    are <state, encoded_t>, softmaxed over the sequence, applied to
    attended_sequence."""
    expanded = tch.expand_layer(input=transformed_state,
                                expand_as=encoded_sequence)
    scores = tch.dot_prod_layer(a=expanded, b=encoded_sequence)
    with tch.mixed_layer(
        size=1, act=tch.SequenceSoftmaxActivation(),
        name=None if name is None else name + "_weight",
    ) as weight:
        weight += tch.identity_projection(input=scores)
    scaled = tch.scaling_layer(input=attended_sequence, weight=weight)
    return tch.pooling_layer(input=scaled,
                             pooling_type=tch.SumPooling(), name=name)


def multi_head_attention(query, key, value, key_proj_size,
                         value_proj_size, head_num,
                         attention_type="dot-product attention",
                         softmax_param_attr=None, name=None, **kwargs):
    """Multi-head attention (reference multi_head_attention): per head,
    project query/key/value, score (dot-product or additive), sequence
    softmax, weighted value sum; heads concatenate."""
    heads = []
    for h in range(head_num):
        hname = "%s_h%d" % (name or "mha", h)
        q_h = tch.fc_layer(input=query, size=key_proj_size,
                           bias_attr=False, name=hname + "_q")
        k_h = tch.fc_layer(input=key, size=key_proj_size,
                           bias_attr=False, name=hname + "_k")
        v_h = tch.fc_layer(input=value, size=value_proj_size,
                           bias_attr=False, name=hname + "_v")
        if "dot" in attention_type:
            heads.append(dot_product_attention(
                encoded_sequence=k_h, attended_sequence=v_h,
                transformed_state=q_h, name=hname))
        else:
            heads.append(simple_attention(
                encoded_sequence=v_h, encoded_proj=k_h,
                decoder_state=query, name=hname))
    return tch.concat_layer(input=heads, name=name)


# ---------------------------------------------------------------------
# VGG reference nets
# ---------------------------------------------------------------------


def _vgg(input_image, num_channels, num_classes, groups, fc_dim=4096,
         drop_rate=0.5):
    tmp = input_image
    filters = [64, 128, 256, 512, 512]
    for i, g in enumerate(groups):
        tmp = tch.img_conv_group(
            input=tmp, conv_num_filter=[filters[min(i, 4)]] * g,
            conv_filter_size=3, conv_padding=1,
            conv_act=tch.ReluActivation(),
            num_channels=num_channels if i == 0 else None,
            pool_size=2, pool_stride=2, pool_type=tch.MaxPooling(),
        )
    tmp = tch.fc_layer(input=tmp, size=fc_dim,
                       act=tch.ReluActivation())
    tmp = tch.dropout_layer(input=tmp, dropout_rate=drop_rate)
    tmp = tch.fc_layer(input=tmp, size=fc_dim,
                       act=tch.ReluActivation())
    tmp = tch.dropout_layer(input=tmp, dropout_rate=drop_rate)
    return tch.fc_layer(input=tmp, size=num_classes,
                        act=tch.SoftmaxActivation())


def small_vgg(input_image, num_channels, num_classes, **kwargs):
    """The CIFAR-scale VGG (reference small_vgg: 4 conv groups of
    [2, 2, 3, 3], fc 512)."""
    return _vgg(input_image, num_channels, num_classes,
                groups=[2, 2, 3, 3], fc_dim=512)


def vgg_16_network(input_image, num_channels, num_classes=1000,
                   **kwargs):
    """VGG-16 (reference vgg_16_network: conv groups [2, 2, 3, 3, 3],
    fc 4096)."""
    return _vgg(input_image, num_channels, num_classes,
                groups=[2, 2, 3, 3, 3], fc_dim=4096)
