"""Optimizer settings objects as a module (reference
trainer_config_helpers/optimizers.py)."""

from . import (  # noqa: F401
    AdaDeltaOptimizer,
    AdaGradOptimizer,
    AdamaxOptimizer,
    AdamOptimizer,
    BaseSGDOptimizer,
    DecayedAdaGradOptimizer,
    MomentumOptimizer,
    Optimizer,
    RMSPropOptimizer,
    settings,
)

__all__ = [
    "Optimizer", "BaseSGDOptimizer", "MomentumOptimizer",
    "AdamaxOptimizer", "AdamOptimizer", "AdaGradOptimizer",
    "RMSPropOptimizer", "DecayedAdaGradOptimizer", "AdaDeltaOptimizer",
    "settings",
]
