"""Pooling type objects as a module (reference
trainer_config_helpers/poolings.py)."""

from . import (  # noqa: F401
    AvgPooling,
    BasePoolingType,
    CudnnAvgInclPadPooling,
    CudnnAvgPooling,
    CudnnMaxPooling,
    MaxPooling,
    MaxWithMaskPooling,
    SquareRootNPooling,
    SumPooling,
)

__all__ = [
    "BasePoolingType", "MaxPooling", "AvgPooling", "MaxWithMaskPooling",
    "CudnnMaxPooling", "CudnnAvgPooling", "CudnnAvgInclPadPooling",
    "SumPooling", "SquareRootNPooling",
]
