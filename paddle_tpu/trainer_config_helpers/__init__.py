"""trainer_config_helpers: the legacy config DSL (reference
python/paddle/trainer_config_helpers/ — 137 layer wrappers feeding
config_parser.py's ModelConfig protobuf; SURVEY §1.1).

Here the DSL is a thin second surface over the SAME lazy layer graph the
v2 API uses (paddle_tpu.v2.layer) — both replay into one fluid Program
(SURVEY §7.1: "two API surfaces, one core"). Configs written for
`paddle train --config=cfg.py` run via `python -m paddle_tpu.trainer`,
which execs the config with this module star-imported, then trains the
recorded outputs with the recorded settings.

Image-layer geometry: the legacy stack carries (channels, height, width)
through layer configs (config_parser.py); here each DSL node records
`im_shape`, and the first img_conv on a flat data layer inserts a reshape
node (square images inferred as sqrt(size/channels), matching
config_parser's default).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

from ..v2.layer import Layer

__all__ = [
    # config-level
    "get_config_arg", "settings", "define_py_data_sources2", "outputs",
    # layers
    "data_layer", "fc_layer", "img_conv_layer", "img_pool_layer",
    "img_conv_group",
    "batch_norm_layer", "concat_layer", "addto_layer", "dropout_layer",
    "embedding_layer", "img_cmrnorm_layer", "simple_lstm", "lstmemory",
    "grumemory", "last_seq", "first_seq", "max_id",
    "classification_cost", "cross_entropy", "regression_cost", "mse_cost",
    # activations
    "ReluActivation", "SoftmaxActivation", "LinearActivation",
    "TanhActivation", "SigmoidActivation", "IdentityActivation",
    # pooling types
    "MaxPooling", "AvgPooling", "SumPooling",
    # optimizers / regularization
    "MomentumOptimizer", "AdamOptimizer", "AdaGradOptimizer",
    "RMSPropOptimizer", "L2Regularization",
]


# ---------------------------------------------------------------------
# parse-time config state (reset by the CLI before exec'ing a config)
# ---------------------------------------------------------------------

_state: Dict[str, Any] = {}


def reset_config(config_args: Optional[Dict[str, str]] = None):
    _state.clear()
    _state.update(
        settings={}, outputs=[], data_sources=None,
        config_args=dict(config_args or {}),
    )


reset_config()


def get_config_state() -> Dict[str, Any]:
    return _state


def get_config_arg(name, type_=str, default=None):
    """CLI --config_args overrides (reference config_parser get_config_arg,
    used by every benchmark script e.g. benchmark/paddle/image/resnet.py:7)."""
    v = _state["config_args"].get(name)
    if v is None:
        return default
    if type_ is bool:
        return str(v) not in ("0", "False", "false", "")
    return type_(v)


def settings(batch_size=256, learning_rate=1e-3, learning_method=None,
             regularization=None, gradient_clipping_threshold=None, **kwargs):
    _state["settings"] = dict(
        batch_size=int(batch_size),
        learning_rate=float(learning_rate),
        learning_method=learning_method,
        regularization=regularization,
        gradient_clipping_threshold=gradient_clipping_threshold,
        extra=kwargs,
    )


def define_py_data_sources2(train_list, test_list, module, obj, args=None):
    _state["data_sources"] = dict(
        train_list=train_list, test_list=test_list, module=module, obj=obj,
        args=dict(args or {}),
    )


def outputs(*layers):
    _state["outputs"].extend(layers)


# ---------------------------------------------------------------------
# activations / pooling / optimizers (reference activations.py,
# poolings.py, optimizers.py)
# ---------------------------------------------------------------------


class _Act(object):
    name: Optional[str] = None


def _mkact(cls_name, act):
    return type(cls_name, (_Act,), {"name": act})


ReluActivation = _mkact("ReluActivation", "relu")
SoftmaxActivation = _mkact("SoftmaxActivation", "softmax")
LinearActivation = _mkact("LinearActivation", None)
IdentityActivation = LinearActivation
TanhActivation = _mkact("TanhActivation", "tanh")
SigmoidActivation = _mkact("SigmoidActivation", "sigmoid")


class _Pooling(object):
    name = "max"


class MaxPooling(_Pooling):
    name = "max"


class AvgPooling(_Pooling):
    name = "avg"


class SumPooling(_Pooling):
    name = "sum"


class MomentumOptimizer(object):
    def __init__(self, momentum=0.9, sparse=False):
        self.momentum = momentum

    def make(self, lr):
        from .. import fluid

        return fluid.optimizer.Momentum(learning_rate=lr, momentum=self.momentum)


class AdamOptimizer(object):
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8):
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def make(self, lr):
        from .. import fluid

        return fluid.optimizer.Adam(
            learning_rate=lr, beta1=self.beta1, beta2=self.beta2,
            epsilon=self.epsilon,
        )


class AdaGradOptimizer(object):
    def make(self, lr):
        from .. import fluid

        return fluid.optimizer.Adagrad(learning_rate=lr)


class RMSPropOptimizer(object):
    def __init__(self, rho=0.95, epsilon=1e-6):
        self.rho, self.epsilon = rho, epsilon

    def make(self, lr):
        from .. import fluid

        return fluid.optimizer.RMSProp(
            learning_rate=lr, rho=self.rho, epsilon=self.epsilon
        )


class L2Regularization(object):
    def __init__(self, rate):
        self.rate = float(rate)


# ---------------------------------------------------------------------
# layers — legacy names over the shared lazy node graph
# ---------------------------------------------------------------------


def _act_name(act):
    if act is None:
        return None
    if isinstance(act, type):
        act = act()
    return act.name


def data_layer(name, size, height=None, width=None, **kwargs):
    t = _DataType(size)
    node = Layer("data", name, [], {"type": t})
    node.im_shape = None
    if height and width:
        node.im_shape = (size // (height * width), height, width)
    return node


class _DataType(object):
    """Minimal stand-in for v2 data_type: dense flat vector of `dim`
    (legacy data_layer is untyped; label layers are int by usage)."""

    def __init__(self, dim, seq=0, is_index=False):
        self.dim = dim
        self.seq_type = seq
        self.type = 3 if is_index else 0  # DataType.Index / Dense


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def fc_layer(input, size, act=None, name=None, bias_attr=None, **kwargs):
    return Layer("fc", name, _as_list(input), {
        "size": size, "act": _act_name(act), "param_attr": None,
        "bias_attr": bias_attr,
    })


def _ensure_image(node, num_channels):
    """Insert a reshape node when the input is still flat (square images,
    config_parser's inference) and return (input_node, (c, h, w))."""
    shape = getattr(node, "im_shape", None)
    if shape is not None:
        return node, shape
    if node.kind == "data":
        size = node.attrs["type"].dim
        c = num_channels or 3
        hw = int(round(math.sqrt(size // c)))
        shape = (c, hw, hw)
        r = Layer("im_reshape", None, [node], {"shape": list(shape)})
        r.im_shape = shape
        return r, shape
    raise ValueError(
        "img layer input %r has no image shape; give num_channels on the "
        "first conv or height/width on the data layer" % node.name
    )


def _conv_out(h, f, s, p):
    return (h + 2 * p - f) // s + 1


def img_conv_layer(input, filter_size, num_filters, num_channels=None,
                   stride=1, padding=0, groups=1, act=None, bias_attr=None,
                   name=None, **kwargs):
    inp, (c, h, w) = _ensure_image(_as_list(input)[0], num_channels)
    node = Layer("img_conv", name, [inp], {
        "filter_size": filter_size, "num_filters": num_filters,
        "num_channels": c, "stride": stride, "padding": padding,
        "groups": groups, "act": _act_name(act),
        "bias": bias_attr is not False,
    })
    node.im_shape = (
        num_filters,
        _conv_out(h, filter_size, stride, padding),
        _conv_out(w, filter_size, stride, padding),
    )
    return node


def img_pool_layer(input, pool_size, stride=1, padding=0, pool_type=None,
                   name=None, **kwargs):
    inp = _as_list(input)[0]
    c, h, w = inp.im_shape
    ptype = "max"
    if pool_type is not None:
        p = pool_type if isinstance(pool_type, _Pooling) else pool_type()
        ptype = "avg" if p.name in ("avg", "sum") else "max"
    node = Layer("img_pool", name, [inp], {
        "pool_size": pool_size, "stride": stride, "padding": padding,
        "pool_type": ptype,
    })
    node.im_shape = (
        c, _conv_out(h, pool_size, stride, padding),
        _conv_out(w, pool_size, stride, padding),
    )
    return node


def batch_norm_layer(input, act=None, name=None, **kwargs):
    inp = _as_list(input)[0]
    node = Layer("batch_norm", name, [inp], {"act": _act_name(act)})
    node.im_shape = getattr(inp, "im_shape", None)
    return node


def img_cmrnorm_layer(input, size=5, scale=0.0001, power=0.75, name=None,
                      **kwargs):
    """Cross-map response normalization = LRN (reference img_cmrnorm_layer
    -> NormLayer; fluid lrn_op)."""
    inp = _as_list(input)[0]
    node = Layer("lrn", name, [inp], {
        "size": size, "scale": scale, "power": power,
    })
    node.im_shape = getattr(inp, "im_shape", None)
    return node


def addto_layer(input, act=None, name=None, bias_attr=None, **kwargs):
    nodes = _as_list(input)
    node = Layer("addto", name, nodes, {"act": _act_name(act)})
    node.im_shape = getattr(nodes[0], "im_shape", None)
    return node


def concat_layer(input, name=None, **kwargs):
    nodes = _as_list(input)
    node = Layer("concat", name, nodes, {})
    shapes = [getattr(n, "im_shape", None) for n in nodes]
    if all(s is not None for s in shapes):
        node.im_shape = (
            sum(s[0] for s in shapes), shapes[0][1], shapes[0][2],
        )
        node.attrs["concat_images"] = True  # channel concat, not flat
    return node


def dropout_layer(input, dropout_rate, name=None, **kwargs):
    inp = _as_list(input)[0]
    node = Layer("dropout", name, [inp], {"rate": dropout_rate})
    node.im_shape = getattr(inp, "im_shape", None)
    return node


def embedding_layer(input, size, name=None, **kwargs):
    node = _as_list(input)[0]
    # legacy: a data layer feeding an embedding is an id sequence
    t = node.attrs["type"]
    t.type = 3  # Index
    t.seq_type = 1
    return Layer("embedding", name, [node], {"size": size})


def lstmemory(input, size=None, reverse=False, act=None, name=None, **kwargs):
    return Layer("lstmemory", name, _as_list(input), {
        "size": size, "reverse": reverse,
    })


def simple_lstm(input, size, name=None, **kwargs):
    f = fc_layer(input=input, size=size * 4)
    return Layer("lstmemory", name, [f], {"size": size, "reverse": False})


def grumemory(input, size=None, reverse=False, name=None, **kwargs):
    return Layer("gru", name, _as_list(input), {"size": size, "reverse": reverse})


def last_seq(input, name=None, **kwargs):
    return Layer("last_seq", name, _as_list(input), {})


def first_seq(input, name=None, **kwargs):
    return Layer("first_seq", name, _as_list(input), {})


def max_id(input, name=None, **kwargs):
    return Layer("max_id", name, _as_list(input), {})


def _label_node(label):
    t = label.attrs["type"]
    t.type = 3  # Index; legacy label layers are integer slots sized n_class
    t.dim = max(t.dim, 1)
    return label


def classification_cost(input, label, name=None, **kwargs):
    return Layer("classification_cost", name, [input, _label_node(label)], {})


def cross_entropy(input, label, name=None, **kwargs):
    return Layer("cross_entropy_cost", name, [input, _label_node(label)], {})


def mse_cost(input, label, name=None, **kwargs):
    return Layer("mse_cost", name, [input, label], {})


regression_cost = mse_cost


def img_conv_group(input, conv_num_filter, conv_filter_size=3,
                   conv_padding=1, conv_act=None, num_channels=None,
                   pool_size=2, pool_stride=2, pool_type=None,
                   conv_with_batchnorm=False, name=None, **kwargs):
    """Stacked convs + one pool (reference trainer_config_helpers/networks
    img_conv_group, used by the VGG benchmark config)."""
    tmp = _as_list(input)[0]
    for i, nf in enumerate(conv_num_filter):
        tmp = img_conv_layer(
            input=tmp,
            filter_size=conv_filter_size,
            num_filters=nf,
            num_channels=num_channels if i == 0 else None,
            stride=1,
            padding=conv_padding,
            act=conv_act,
        )
        if conv_with_batchnorm:
            tmp = batch_norm_layer(input=tmp, act=None)
    return img_pool_layer(
        input=tmp, pool_size=pool_size, stride=pool_stride,
        pool_type=pool_type,
    )
