"""trainer_config_helpers: the legacy config DSL (reference
python/paddle/trainer_config_helpers/ — 137 layer wrappers feeding
config_parser.py's ModelConfig protobuf; SURVEY §1.1).

Here the DSL is a thin second surface over the SAME lazy layer graph the
v2 API uses (paddle_tpu.v2.layer) — both replay into one fluid Program
(SURVEY §7.1: "two API surfaces, one core"). Configs written for
`paddle train --config=cfg.py` run via `python -m paddle_tpu.trainer`,
which execs the config with this module star-imported, then trains the
recorded outputs with the recorded settings.

Image-layer geometry: the legacy stack carries (channels, height, width)
through layer configs (config_parser.py); here each DSL node records
`im_shape`, and the first img_conv on a flat data layer inserts a reshape
node (square images inferred as sqrt(size/channels), matching
config_parser's default).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

from ..v2.layer import Layer

__all__ = [
    # config-level
    "get_config_arg", "settings", "define_py_data_sources2", "outputs",
    "Inputs", "Outputs", "TrainData", "TestData", "SimpleData",
    "ParamAttr", "ExtraAttr", "ExtraLayerAttribute",
    # layers
    "data_layer", "fc_layer", "img_conv_layer", "img_pool_layer",
    "img_conv_group",
    "batch_norm_layer", "concat_layer", "addto_layer", "dropout_layer",
    "embedding_layer", "img_cmrnorm_layer", "simple_lstm", "lstmemory",
    "grumemory", "last_seq", "first_seq", "max_id", "maxid_layer",
    "eos_layer", "expand_layer", "pooling_layer", "seq_concat_layer",
    "classification_cost", "cross_entropy", "regression_cost", "mse_cost",
    # mixed layer + projections
    "mixed_layer", "full_matrix_projection", "trans_full_matrix_projection",
    "identity_projection", "table_projection", "context_projection",
    "dotmul_projection", "scaling_projection",
    # recurrent machinery + generation
    "recurrent_group", "memory", "StaticInput", "GeneratedInput",
    "SubsequenceInput", "beam_search",
    # activations
    "ReluActivation", "SoftmaxActivation", "LinearActivation",
    "TanhActivation", "SigmoidActivation", "IdentityActivation",
    "BReluActivation", "SoftReluActivation", "SquareActivation",
    "ExpActivation", "STanhActivation", "AbsActivation", "LogActivation",
    "SequenceSoftmaxActivation", "SqrtActivation", "ReciprocalActivation",
    "SoftSignActivation", "BaseActivation",
    # layer-surface compatibility objects
    "AggregateLevel", "ExpandLevel", "LayerType", "LayerOutput",
    "BaseGeneratedInput", "layer_support", "print_layer",
    "convex_comb_layer",
    # pooling types
    "MaxPooling", "AvgPooling", "SumPooling",
    # optimizers / regularization
    "MomentumOptimizer", "AdamOptimizer", "AdaGradOptimizer",
    "RMSPropOptimizer", "L2Regularization",
]


# ---------------------------------------------------------------------
# parse-time config state (reset by the CLI before exec'ing a config)
# ---------------------------------------------------------------------

_state: Dict[str, Any] = {}


def reset_config(config_args: Optional[Dict[str, str]] = None):
    _state.clear()
    _state.update(
        settings={}, outputs=[], data_sources=None,
        config_args=dict(config_args or {}),
    )
    Layer._registry = _state["layers_by_name"] = {}


reset_config()


def get_config_state() -> Dict[str, Any]:
    return _state


def get_config_arg(name, type_=str, default=None):
    """CLI --config_args overrides (reference config_parser get_config_arg,
    used by every benchmark script e.g. benchmark/paddle/image/resnet.py:7)."""
    v = _state["config_args"].get(name)
    if v is None:
        return default
    if type_ is bool:
        return str(v) not in ("0", "False", "false", "")
    return type_(v)


def settings(batch_size=256, learning_rate=1e-3, learning_method=None,
             regularization=None, gradient_clipping_threshold=None, **kwargs):
    _state["settings"] = dict(
        batch_size=int(batch_size),
        learning_rate=float(learning_rate),
        learning_method=learning_method,
        regularization=regularization,
        gradient_clipping_threshold=gradient_clipping_threshold,
        extra=kwargs,
    )


def define_py_data_sources2(train_list, test_list, module, obj, args=None):
    _state["data_sources"] = dict(
        train_list=train_list, test_list=test_list, module=module, obj=obj,
        args=dict(args or {}),
    )


def outputs(*layers):
    _state["outputs"].extend(layers)


def Inputs(*names):
    """Legacy config_parser Inputs(): declares feed order; recorded so the
    CLI can validate provider slots (the graph itself already knows its
    data layers)."""
    _state["input_names"] = list(names)


def Outputs(*names):
    """Legacy config_parser Outputs(): outputs by layer NAME."""
    _state["output_names"] = list(names)


class SimpleData(object):
    """Legacy SimpleData provider config (reference
    trainer/tests/sample_trainer_config.conf): dense rows of `feat_dim`
    floats read from `files`."""

    def __init__(self, files=None, feat_dim=1, context_len=0,
                 buffer_capacity=0, **kwargs):
        self.files = files
        self.feat_dim = feat_dim


def TrainData(provider):
    _state["train_data"] = provider


def TestData(provider):
    _state["test_data"] = provider


# ---------------------------------------------------------------------
# activations / pooling / optimizers (reference activations.py,
# poolings.py, optimizers.py)
# ---------------------------------------------------------------------


class _Act(object):
    name: Optional[str] = None


def _mkact(cls_name, act):
    return type(cls_name, (_Act,), {"name": act})


ReluActivation = _mkact("ReluActivation", "relu")
SoftmaxActivation = _mkact("SoftmaxActivation", "softmax")
LinearActivation = _mkact("LinearActivation", None)
IdentityActivation = LinearActivation
TanhActivation = _mkact("TanhActivation", "tanh")
SigmoidActivation = _mkact("SigmoidActivation", "sigmoid")
BReluActivation = _mkact("BReluActivation", "brelu")
# reference SoftRelu = ln(1 + e^x) (activations.py SoftReluActivation),
# which is softplus in fluid terms
SoftReluActivation = _mkact("SoftReluActivation", "softplus")
SquareActivation = _mkact("SquareActivation", "square")
SequenceSoftmaxActivation = _mkact("SequenceSoftmaxActivation", "sequence_softmax")
ExpActivation = _mkact("ExpActivation", "exp")
STanhActivation = _mkact("STanhActivation", "stanh")
AbsActivation = _mkact("AbsActivation", "abs")
LogActivation = _mkact("LogActivation", "log")
SqrtActivation = _mkact("SqrtActivation", "sqrt")
ReciprocalActivation = _mkact("ReciprocalActivation", "reciprocal")
SoftSignActivation = _mkact("SoftSignActivation", "softsign")
# reference activations.py exports the base class too
BaseActivation = _Act


class ParamAttr(object):
    """Legacy attrs.py ParameterAttribute: the subset that affects this
    core — `name` gives deterministic (shareable) parameter identity;
    initialization spread/learning-rate fields are accepted and recorded."""

    def __init__(self, name=None, is_static=False, initial_std=None,
                 initial_mean=None, initial_max=None, initial_min=None,
                 l1_rate=None, l2_rate=None, learning_rate=None,
                 momentum=None, gradient_clipping_threshold=None,
                 sparse_update=False, update_hooks=None, **kwargs):
        self.name = name
        self.is_static = is_static
        self.initial_std = initial_std
        self.initial_mean = initial_mean
        self.learning_rate = learning_rate
        self.update_hooks = update_hooks
        # legacy sparse-row updates (reference attrs.py:130, the
        # SparseRemoteParameterUpdater surface) select the SelectedRows
        # sparse-gradient path when the parameter feeds an embedding
        self.sparse_update = sparse_update


class ExtraLayerAttribute(object):
    def __init__(self, error_clipping_threshold=None, drop_rate=None,
                 device=None, **kwargs):
        self.drop_rate = drop_rate


ExtraAttr = ExtraLayerAttribute


class _Pooling(object):
    name = "max"


class MaxPooling(_Pooling):
    name = "max"


class AvgPooling(_Pooling):
    name = "avg"


class SumPooling(_Pooling):
    name = "sum"


class MomentumOptimizer(object):
    def __init__(self, momentum=0.9, sparse=False):
        self.momentum = momentum

    def make(self, lr):
        from .. import fluid

        return fluid.optimizer.Momentum(learning_rate=lr, momentum=self.momentum)


class AdamOptimizer(object):
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8):
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def make(self, lr):
        from .. import fluid

        return fluid.optimizer.Adam(
            learning_rate=lr, beta1=self.beta1, beta2=self.beta2,
            epsilon=self.epsilon,
        )


class AdaGradOptimizer(object):
    def make(self, lr):
        from .. import fluid

        return fluid.optimizer.Adagrad(learning_rate=lr)


class RMSPropOptimizer(object):
    def __init__(self, rho=0.95, epsilon=1e-6):
        self.rho, self.epsilon = rho, epsilon

    def make(self, lr):
        from .. import fluid

        return fluid.optimizer.RMSProp(
            learning_rate=lr, rho=self.rho, epsilon=self.epsilon
        )


# L2Regularization is defined further down, under BaseRegularization


# ---------------------------------------------------------------------
# layers — legacy names over the shared lazy node graph
# ---------------------------------------------------------------------


def _act_name(act):
    if act is None:
        return None
    if isinstance(act, type):
        act = act()
    return act.name


def data_layer(name, size, height=None, width=None, **kwargs):
    t = _DataType(size)
    node = Layer("data", name, [], {"type": t})
    node.im_shape = None
    if height and width:
        node.im_shape = (size // (height * width), height, width)
    return node


class _DataType(object):
    """Minimal stand-in for v2 data_type: dense flat vector of `dim`
    (legacy data_layer is untyped; label layers are int by usage)."""

    def __init__(self, dim, seq=0, is_index=False):
        self.dim = dim
        self.seq_type = seq
        self.type = 3 if is_index else 0  # DataType.Index / Dense


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _apply_layer_attr(node, kwargs):
    """Honor ExtraLayerAttribute knobs that change the graph: drop_rate
    wraps the layer in dropout (reference config_parser applies
    layer_attr.drop_rate to any layer's output); device hints are
    accepted (per-tensor sharding replaces pinning on TPU)."""
    attr = kwargs.get("layer_attr")
    rate = getattr(attr, "drop_rate", None)
    if rate:
        return dropout_layer(input=node, dropout_rate=float(rate))
    return node


def fc_layer(input, size, act=None, name=None, bias_attr=None,
             param_attr=None, **kwargs):
    node = Layer("fc", name, _as_list(input), {
        "size": size, "act": _act_name(act), "param_attr": param_attr,
        "bias_attr": bias_attr,
    })
    return _apply_layer_attr(node, kwargs)


def _node_flat_width(node):
    a = getattr(node, "attrs", {})
    if a.get("size"):
        return int(a["size"])
    t = a.get("type")
    if t is not None:
        return int(t.dim)
    return None


def _factor_hw(size, c):
    """Reference config_parser geometry fallback (config_parser.py:1210):
    width = floor(sqrt(pixels)), height = pixels / width."""
    pixels = size // c
    w = int(math.sqrt(pixels))
    h = pixels // max(w, 1)
    if h * w * c != size:
        raise ValueError(
            "cannot factor size %d into %d channels x H x W" % (size, c)
        )
    return h, w


def _ensure_image(node, num_channels):
    """Insert a reshape node when the input is still flat (data layers —
    and any flat layer given an explicit num_channels — are [N, size];
    geometry follows config_parser's inference) and return
    (input_node, (c, h, w))."""
    shape = getattr(node, "im_shape", None)
    if shape is not None and node.kind != "data":
        return node, shape
    size = _node_flat_width(node)
    if node.kind == "data" or (num_channels and size):
        if shape is None:
            c = num_channels or 3
            h, w = _factor_hw(size, c)
            shape = (c, h, w)
        r = Layer("im_reshape", None, [node], {"shape": list(shape)})
        r.im_shape = shape
        return r, shape
    raise ValueError(
        "img layer input %r has no image shape; give num_channels on the "
        "first conv/pool or height/width on the data layer" % node.name
    )


def _conv_out(h, f, s, p):
    return (h + 2 * p - f) // s + 1


def _pool_out(d, ps, st, pd, ceil_mode):
    """Pooling output extent (shared by img_pool_layer / img_pool3d_layer;
    reference parse_pool ceil/floor semantics)."""
    span = d + 2 * pd - ps
    return (-(-span // st) if ceil_mode else span // st) + 1


def img_conv_layer(input, filter_size, num_filters, num_channels=None,
                   stride=1, padding=0, groups=1, act=None, bias_attr=None,
                   name=None, **kwargs):
    inp, (c, h, w) = _ensure_image(_as_list(input)[0], num_channels)
    node = Layer("img_conv", name, [inp], {
        "filter_size": filter_size, "num_filters": num_filters,
        "num_channels": c, "stride": stride, "padding": padding,
        "groups": groups, "act": _act_name(act),
        "bias": bias_attr is not False,
    })
    node.im_shape = (
        num_filters,
        _conv_out(h, filter_size, stride, padding),
        _conv_out(w, filter_size, stride, padding),
    )
    return node


def img_pool_layer(input, pool_size, stride=1, padding=0, pool_type=None,
                   name=None, pool_size_y=None, stride_y=None,
                   padding_y=None, num_channels=None, ceil_mode=True,
                   **kwargs):
    """Image pooling, rectangular windows supported via the *_y params
    (reference img_pool_layer / config_parser parse_pool; legacy default
    is ceil_mode=True)."""
    inp, (c, h, w) = _ensure_image(_as_list(input)[0], num_channels)
    ptype = "max"
    if pool_type is not None:
        p = pool_type if isinstance(pool_type, _Pooling) else pool_type()
        ptype = "avg" if p.name in ("avg", "sum") else "max"
    ph = pool_size_y if pool_size_y is not None else pool_size
    sh = stride_y if stride_y is not None else stride
    dh = padding_y if padding_y is not None else padding
    node = Layer("img_pool", name, [inp], {
        "pool_size": [ph, pool_size], "stride": [sh, stride],
        "padding": [dh, padding], "pool_type": ptype,
        "ceil_mode": bool(ceil_mode),
    })

    node.im_shape = (
        c, _pool_out(h, ph, sh, dh, ceil_mode),
        _pool_out(w, pool_size, stride, padding, ceil_mode),
    )
    return node


def batch_norm_layer(input, act=None, name=None, **kwargs):
    inp = _as_list(input)[0]
    node = Layer("batch_norm", name, [inp], {"act": _act_name(act)})
    node.im_shape = getattr(inp, "im_shape", None)
    return node


def img_cmrnorm_layer(input, size=5, scale=0.0001, power=0.75, name=None,
                      **kwargs):
    """Cross-map response normalization = LRN (reference img_cmrnorm_layer
    -> NormLayer; fluid lrn_op)."""
    inp = _as_list(input)[0]
    node = Layer("lrn", name, [inp], {
        "size": size, "scale": scale, "power": power,
    })
    node.im_shape = getattr(inp, "im_shape", None)
    return node


def addto_layer(input, act=None, name=None, bias_attr=None, **kwargs):
    nodes = _as_list(input)
    node = Layer("addto", name, nodes, {"act": _act_name(act)})
    node.im_shape = getattr(nodes[0], "im_shape", None)
    return node


def concat_layer(input, name=None, **kwargs):
    nodes = _as_list(input)
    node = Layer("concat", name, nodes, {})
    shapes = [getattr(n, "im_shape", None) for n in nodes]
    if all(s is not None for s in shapes):
        node.im_shape = (
            sum(s[0] for s in shapes), shapes[0][1], shapes[0][2],
        )
        node.attrs["concat_images"] = True  # channel concat, not flat
    return node


def dropout_layer(input, dropout_rate, name=None, **kwargs):
    inp = _as_list(input)[0]
    node = Layer("dropout", name, [inp], {"rate": dropout_rate})
    node.im_shape = getattr(inp, "im_shape", None)
    return node


def embedding_layer(input, size, name=None, param_attr=None, **kwargs):
    node = _as_list(input)[0]
    # legacy: a data layer feeding an embedding is an id sequence
    t = node.attrs["type"]
    t.type = 3  # Index
    t.seq_type = 1
    return Layer("embedding", name, [node], {
        "size": size, "param_attr": param_attr,
    })


def lstmemory(input, size=None, reverse=False, act=None, name=None, **kwargs):
    return Layer("lstmemory", name, _as_list(input), {
        "size": size, "reverse": reverse,
    })


def simple_lstm(input, size, name=None, **kwargs):
    f = fc_layer(input=input, size=size * 4)
    return Layer("lstmemory", name, [f], {"size": size, "reverse": False})


def grumemory(input, size=None, reverse=False, name=None, **kwargs):
    return Layer("gru", name, _as_list(input), {"size": size, "reverse": reverse})


def last_seq(input, name=None, **kwargs):
    return Layer("last_seq", name, _as_list(input), {})


def first_seq(input, name=None, **kwargs):
    return Layer("first_seq", name, _as_list(input), {})


def max_id(input, name=None, **kwargs):
    return Layer("max_id", name, _as_list(input), {})


def _label_node(label):
    t = label.attrs["type"]
    t.type = 3  # Index; legacy label layers are integer slots sized n_class
    t.dim = max(t.dim, 1)
    return label


def classification_cost(input, label, name=None, weight=None, **kwargs):
    parents = [input, _label_node(label)]
    if weight is not None:
        parents.append(weight)
    return Layer("classification_cost", name, parents,
                 {"weighted": weight is not None})


def cross_entropy(input, label, name=None, **kwargs):
    return Layer("cross_entropy_cost", name, [input, _label_node(label)], {})


def mse_cost(input, label, name=None, **kwargs):
    return Layer("mse_cost", name, [input, label], {})


regression_cost = mse_cost


# ---------------------------------------------------------------------
# mixed_layer + projections (reference layers.py mixed_layer:657,
# full_matrix_projection:500, identity_projection:540, table_projection,
# context_projection, gserver MixedLayer + projections/)
# ---------------------------------------------------------------------


class _Projection(object):
    def __init__(self, ptype, input, **attrs):
        self.ptype = ptype
        self.input = input
        self.attrs = attrs


def full_matrix_projection(input, size=0, param_attr=None, **kwargs):
    return _Projection("full_matrix", input, param_attr=param_attr)


def trans_full_matrix_projection(input, size=0, param_attr=None, **kwargs):
    return _Projection("trans_full_matrix", input, param_attr=param_attr)


def identity_projection(input, offset=None, size=None, **kwargs):
    return _Projection("identity", input, offset=offset, size=size)


def table_projection(input, size=0, param_attr=None, **kwargs):
    return _Projection("table", input, param_attr=param_attr)


def context_projection(input, context_len, context_start=None,
                       padding_attr=False, **kwargs):
    return _Projection(
        "context", input, context_len=context_len,
        context_start=context_start,
    )


def dotmul_projection(input, param_attr=None, **kwargs):
    return _Projection("dotmul", input, param_attr=param_attr)


def scaling_projection(input, param_attr=None, **kwargs):
    return _Projection("scaling", input, param_attr=param_attr)


class MixedLayerNode(Layer):
    """`with mixed_layer(...) as m: m += projection` — a Layer node whose
    attrs collect projections; usable as a context manager and as a
    regular layer input afterwards."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __iadd__(self, proj):
        if not isinstance(proj, _Projection):
            raise TypeError("mixed_layer += expects a projection")
        self.attrs["projections"].append(proj)
        self.parents.append(proj.input)
        self.parents.extend(getattr(proj, "extra_inputs", []))
        return self


def mixed_layer(size=0, input=None, name=None, act=None, bias_attr=None,
                **kwargs):
    node = MixedLayerNode("mixed", name, [], {
        "size": size, "act": _act_name(act), "bias_attr": bias_attr,
        "projections": [],
    })
    if input is not None:
        for proj in _as_list(input):
            node += proj
    return node


# ---------------------------------------------------------------------
# recurrent_group / memory / StaticInput (reference layers.py
# recurrent_group:4082, memory:3590; RecurrentGradientMachine)
# ---------------------------------------------------------------------


class StaticInput(object):
    """Non-sequence input visible unchanged at every step."""

    def __init__(self, input, size=None, is_seq=False, **kwargs):
        self.input = input
        self.size = size


_rg_stack: List[List[Layer]] = []


def memory(name, size=None, boot_layer=None, is_seq=False, **kwargs):
    """State carried across recurrent_group steps: reads the PREVIOUS
    step's value of the layer called `name` (the step must produce a
    layer with that name); `boot_layer` seeds step 0."""
    if not _rg_stack:
        raise RuntimeError("memory() must be called inside a "
                           "recurrent_group step function")
    node = Layer("rg_memory", None, [], {
        "ref_name": name, "size": size,
        "boot_name": boot_layer.name if boot_layer is not None else None,
    })
    node._boot_layer = boot_layer
    _rg_stack[-1].append(node)
    return node


class SubsequenceInput(object):
    """Marks a NESTED-sequence input to recurrent_group (reference
    layers.py SubsequenceInput): each outer step consumes one
    sub-sequence. In the memory-less generation lowering the packed
    tokens are the per-source batch either way, so the marker unwraps
    to its layer."""

    def __init__(self, input):  # noqa: A002 - reference name
        self.input = input


def recurrent_group(step, input, reverse=False, name=None, **kwargs):
    """Runs `step` once per timestep over the sequence inputs (lowered to
    ONE lax.scan via fluid DynamicRNN — core/kernels_control.py). Plain
    layer inputs are per-step sequences; StaticInput is read-only."""
    raw_inputs = _as_list(input)
    has_subseq = any(isinstance(i, SubsequenceInput) for i in raw_inputs)
    inputs = [
        i.input if isinstance(i, SubsequenceInput) else i
        for i in raw_inputs
    ]
    seq_nodes, static_nodes, placeholders = [], [], []
    for inp in inputs:
        if isinstance(inp, StaticInput):
            ph = Layer("rg_static_in", None, [], {})
            ph._outer = inp.input
            static_nodes.append(ph)
        else:
            ph = Layer("rg_step_in", None, [], {})
            ph._outer = inp
            seq_nodes.append(ph)
        placeholders.append(ph)

    _rg_stack.append([])
    prev_collector = Layer._step_nodes
    Layer._step_nodes = step_nodes = []
    try:
        out = step(*placeholders)
    finally:
        mems = _rg_stack.pop()
        Layer._step_nodes = prev_collector
    if isinstance(out, (list, tuple)):
        raise NotImplementedError(
            "recurrent_group with multiple step outputs is not supported "
            "yet; return the primary output layer"
        )
    is_nested_gen = getattr(out, "kind", None) == "beam_gen" and not mems
    if has_subseq and not is_nested_gen:
        raise NotImplementedError(
            "SubsequenceInput recurrent groups are supported only for "
            "the memory-less nested-GENERATION form (beam_search in the "
            "step); nested training groups need per-subsequence "
            "iteration — use DynamicRNN composition instead"
        )
    if is_nested_gen:
        # nested generation (reference sample_trainer_nest_rnn_gen.conf):
        # a memory-less outer group whose step runs beam_search is a MAP
        # over the outer sequence's tokens — rewire the beam's static
        # inputs from the per-step placeholders to the packed outer
        # sequences (every token becomes one generation source; the
        # packed order IS the reference's concat-over-outer-steps order)
        ph_to_outer = {ph: ph._outer for ph in placeholders}

        def _reaches_placeholder(node, seen=None):
            seen = seen if seen is not None else set()
            if id(node) in seen:
                return False
            seen.add(id(node))
            if getattr(node, "kind", None) in ("rg_step_in",
                                               "rg_static_in"):
                return True
            return any(
                _reaches_placeholder(par, seen)
                for par in getattr(node, "parents", [])
            )

        for sph in out.attrs["static_phs"]:
            if sph._outer in ph_to_outer:
                sph._outer = ph_to_outer[sph._outer]
            elif _reaches_placeholder(sph._outer):
                raise NotImplementedError(
                    "nested generation supports only DIRECT "
                    "SubsequenceInput -> StaticInput pass-through; layer "
                    "%r transforms the outer step input before the "
                    "beam's StaticInput" % sph._outer.name
                )
        out.parents = [sph._outer for sph in out.attrs["static_phs"]]
        if name and Layer._registry is not None:
            Layer._registry.setdefault(name, out)
        return out
    parents = [ph._outer for ph in placeholders] + [
        m._boot_layer for m in mems if m._boot_layer is not None
    ]
    node = Layer("recurrent_group", name, parents, {
        "reverse": bool(reverse),
        "step_out": out,
        "placeholders": placeholders,
        "mems": mems,
        "step_nodes": step_nodes,
    })
    return node


class BaseGeneratedInput(object):
    """Base for generation-mode step inputs (reference layers.py:4203)."""

    def __init__(self):
        self.bos_id = None
        self.eos_id = None


class GeneratedInput(BaseGeneratedInput):
    """Generation-mode step input: the embedding of the previous step's
    predicted word (reference layers.py GeneratedInput / the generation
    path of RecurrentGradientMachine)."""

    def __init__(self, size, embedding_name, embedding_size, **kwargs):
        super(GeneratedInput, self).__init__()
        self.size = size
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size


def beam_search(step, input, bos_id, eos_id, beam_size=1,
                num_results_per_sample=None, max_length=10, name=None,
                **kwargs):
    """Legacy generation (reference layers.py beam_search ->
    RecurrentGradientMachine::generateSequence/beamSearch,
    RecurrentGradientMachine.h:307,309): run `step` up to `max_length`
    times, feeding back the embedded best words, keeping `beam_size`
    candidates per source. Lowered to the fluid While + beam_search +
    beam_search_decode machinery (compiled fori_loop,
    core/kernels_control.py); returns the decoded sentence-id layer."""
    if num_results_per_sample is None:
        num_results_per_sample = beam_size
    if num_results_per_sample > beam_size:
        raise ValueError(
            "num_results_per_sample=%d exceeds beam_size=%d"
            % (num_results_per_sample, beam_size)
        )
    inputs = _as_list(input)
    gen = None
    placeholders, static_phs = [], []
    for inp in inputs:
        if isinstance(inp, GeneratedInput):
            ph = Layer("rg_gen_in", None, [], {"size": inp.embedding_size})
            gen = inp
            placeholders.append(ph)
        elif isinstance(inp, StaticInput):
            ph = Layer("rg_static_in", None, [], {})
            ph._outer = inp.input
            static_phs.append(ph)
            placeholders.append(ph)
        else:
            raise TypeError(
                "beam_search inputs must be StaticInput/GeneratedInput"
            )
    if gen is None:
        raise ValueError("beam_search needs a GeneratedInput")

    _rg_stack.append([])
    try:
        out = step(*placeholders)
    finally:
        mems = _rg_stack.pop()
    parents = [ph._outer for ph in static_phs] + [
        m._boot_layer for m in mems if m._boot_layer is not None
    ]
    node = Layer("beam_gen", name, parents, {
        "step_out": out,
        "placeholders": placeholders,
        "static_phs": static_phs,
        "mems": mems,
        "gen": gen,
        "bos_id": int(bos_id),
        "eos_id": int(eos_id),
        "beam_size": int(beam_size),
        "num_results_per_sample": int(num_results_per_sample),
        "max_length": int(max_length),
    })
    # reference default generation output name (config_parser registers
    # the decode layer as "__beam_search_predict__"; rnn_gen confs say
    # Outputs("__beam_search_predict__"))
    if Layer._registry is not None:
        Layer._registry.setdefault("__beam_search_predict__", node)
    return node


def expand_layer(input, expand_as, name=None, **kwargs):
    """Repeat each row of `input` per `expand_as`'s sequence layout
    (reference expand_layer -> fluid sequence_expand)."""
    return Layer("seq_expand", name, [input, expand_as], {})


def pooling_layer(input, pooling_type=None, name=None, **kwargs):
    ptype = "max"
    if pooling_type is not None:
        p = pooling_type if isinstance(pooling_type, _Pooling) else pooling_type()
        ptype = {"max": "max", "avg": "average", "sum": "sum",
                 "sqrt": "sqrt"}[p.name]
    return Layer("seq_pool", name, [input], {"pool_type": ptype})


def seq_concat_layer(a, b, name=None, **kwargs):
    return Layer("concat", name, [a, b], {})


def maxid_layer(input, name=None, **kwargs):
    return Layer("max_id", name, _as_list(input), {})


def eos_layer(input, eos_id, name=None, **kwargs):
    """1 where the id equals eos_id (reference EosIdCheckLayer)."""
    return Layer("eos", name, _as_list(input), {"eos_id": int(eos_id)})


def img_conv_group(input, conv_num_filter, conv_filter_size=3,
                   conv_padding=1, conv_act=None, num_channels=None,
                   pool_size=2, pool_stride=2, pool_type=None,
                   conv_with_batchnorm=False, name=None, **kwargs):
    """Stacked convs + one pool (reference trainer_config_helpers/networks
    img_conv_group, used by the VGG benchmark config)."""
    tmp = _as_list(input)[0]
    for i, nf in enumerate(conv_num_filter):
        tmp = img_conv_layer(
            input=tmp,
            filter_size=conv_filter_size,
            num_filters=nf,
            num_channels=num_channels if i == 0 else None,
            stride=1,
            padding=conv_padding,
            act=conv_act,
        )
        if conv_with_batchnorm:
            tmp = batch_norm_layer(input=tmp, act=None)
    return img_pool_layer(
        input=tmp, pool_size=pool_size, stride=pool_stride,
        pool_type=pool_type,
    )


# ---------------------------------------------------------------------
# breadth wrappers (reference layers.py; each lowers onto an existing
# fluid layer/kernel — see v2/topology.py for the lowering)
# ---------------------------------------------------------------------


def _simple(kind, inputs, **attrs):
    name = attrs.pop("name", None)
    return Layer(kind, name, _as_list(inputs), attrs)


def cos_sim(a, b, scale=1.0, name=None, **kwargs):
    return _simple("cos_sim", [a, b], name=name, scale=scale)


def trans_layer(input, name=None, **kwargs):
    return _simple("trans", input, name=name)


def power_layer(input, weight, name=None, **kwargs):
    """y_ij = x_ij ^ w_i (reference PowerLayer)."""
    return _simple("power", [input, weight], name=name)


def scaling_layer(input, weight, name=None, **kwargs):
    """row i scaled by weight row i (reference ScalingLayer)."""
    return _simple("scaling", [input, weight], name=name)


def interpolation_layer(input, weight, name=None, **kwargs):
    """w*a + (1-w)*b over input=[a, b] (reference InterpolationLayer)."""
    a, b = _as_list(input)
    return _simple("interpolation", [a, b, weight], name=name)


def slope_intercept_layer(input, slope=1.0, intercept=0.0, name=None,
                          **kwargs):
    return _simple("slope_intercept", input, name=name,
                   slope=float(slope), intercept=float(intercept))


def sum_to_one_norm_layer(input, name=None, **kwargs):
    return _simple("sum_to_one_norm", input, name=name)


def row_l2_norm_layer(input, name=None, **kwargs):
    return _simple("row_l2_norm", input, name=name)


def dot_prod_layer(a, b, name=None, **kwargs):
    return _simple("dot_prod", [a, b], name=name)


def out_prod_layer(a, b, name=None, **kwargs):
    return _simple("out_prod", [a, b], name=name)


def l2_distance_layer(a, b, name=None, **kwargs):
    return _simple("l2_distance", [a, b], name=name)


def pad_layer(input, pad_c=None, pad_h=None, pad_w=None, name=None, **kwargs):
    inp, (c, h, w) = _ensure_image(_as_list(input)[0], None)
    pad_c, pad_h, pad_w = pad_c or [0, 0], pad_h or [0, 0], pad_w or [0, 0]
    node = _simple("pad_img", inp, name=name,
                   pad_c=list(pad_c), pad_h=list(pad_h), pad_w=list(pad_w))
    node.im_shape = (c + sum(pad_c), h + sum(pad_h), w + sum(pad_w))
    return node


def clip_layer(input, min, max, name=None, **kwargs):  # noqa: A002
    return _simple("clip", input, name=name, min=float(min), max=float(max))


def multiplex_layer(input, name=None, **kwargs):
    """input[0] = int selector, rest = candidates (reference Multiplex)."""
    ins = _as_list(input)
    if ins[0].kind == "data":
        ins[0].attrs["type"].type = 3  # the selector is an id slot
    return _simple("multiplex", ins, name=name)


def row_conv_layer(input, context_len, act=None, name=None, **kwargs):
    return _simple("row_conv", input, name=name,
                   context_len=int(context_len), act=_act_name(act))


def maxout_layer(input, groups, num_channels=None, name=None, **kwargs):
    inp, (c, h, w) = _ensure_image(_as_list(input)[0], num_channels)
    node = _simple("maxout", inp, name=name, groups=int(groups))
    node.im_shape = (c // int(groups), h, w)
    return node


def block_expand_layer(input, block_x=1, block_y=1, stride_x=1, stride_y=1,
                       padding_x=0, padding_y=0, num_channels=None,
                       name=None, **kwargs):
    """Image -> sequence of blocks (reference BlockExpandLayer; fluid
    im2sequence)."""
    input, _ = _ensure_image(_as_list(input)[0], num_channels)
    return _simple("block_expand", input, name=name,
                   block=[int(block_y), int(block_x)],
                   stride=[int(stride_y), int(stride_x)],
                   padding=[int(padding_y), int(padding_x)],
                   num_channels=num_channels)


def seq_reshape_layer(input, reshape_size, name=None, **kwargs):
    return _simple("seq_reshape", input, name=name,
                   new_dim=int(reshape_size))


def repeat_layer(input, num_repeats, name=None, **kwargs):
    return _simple("repeat", input, name=name, num_repeats=int(num_repeats))


def recurrent_layer(input, act=None, reverse=False, name=None,
                    param_attr=None, bias_attr=None, **kwargs):
    """Simple full-matrix recurrence (reference RecurrentLayer):
    h_t = act(x_t + W h_{t-1}) — sugar over recurrent_group; with
    reverse=True the recurrence runs t = len-1 .. 0 (reference
    RecurrentLayer reversed_)."""
    act = act or TanhActivation()
    inp = _as_list(input)[0]
    if name is None:
        # auto-unique like every other wrapper (two unnamed recurrences
        # must not share a state name or weight)
        i = Layer._counters.get("recurrent_layer", 0)
        Layer._counters["recurrent_layer"] = i + 1
        name = "__recurrent_layer_%d__" % i

    def step(y):
        mem = memory(name=name + "@state", size=None)
        out_ = _simple("recurrent_step", [y, mem], name=name + "@state",
                       act=_act_name(act), param_attr=param_attr)
        return out_

    return recurrent_group(step=step, input=inp, name=name,
                           reverse=reverse)


def ctc_layer(input, label, size=None, blank=None, norm_by_times=False,
              name=None, **kwargs):
    return _simple("ctc_cost", [input, _label_node(label)], name=name,
                   blank=int(blank if blank is not None else (size or 1) - 1),
                   norm_by_times=norm_by_times)


def warp_ctc_layer(input, label, size=None, blank=0, norm_by_times=False,
                   name=None, **kwargs):
    """Reference warp_ctc_layer: blank DEFAULTS TO 0 (ctc_layer's blank
    defaults to size-1)."""
    return _simple("ctc_cost", [input, _label_node(label)], name=name,
                   blank=int(blank), norm_by_times=norm_by_times)


def crf_layer(input, label, size=None, param_attr=None, name=None, **kwargs):
    return _simple("crf_cost", [input, _label_node(label)], name=name,
                   param_attr=param_attr)


def crf_decoding_layer(input, size=None, param_attr=None, label=None,
                       name=None, **kwargs):
    return _simple("crf_decode", [input], name=name, param_attr=param_attr)


def nce_layer(input, label, num_classes, num_neg_samples=10, name=None,
              weight=None, neg_distribution=None, **kwargs):
    parents = _as_list(input) + [_label_node(label)]
    if weight is not None:
        parents.append(weight)
    return _simple("nce_cost", parents,
                   name=name,
                   num_classes=int(num_classes),
                   num_neg_samples=int(num_neg_samples),
                   weighted=weight is not None,
                   neg_distribution=(
                       list(neg_distribution) if neg_distribution else None
                   ))


def hsigmoid(input, label, num_classes, name=None, **kwargs):
    return _simple("hsigmoid_cost", _as_list(input) + [_label_node(label)],
                   name=name,
                   num_classes=int(num_classes))


def rank_cost(left, right, label, name=None, **kwargs):
    return _simple("rank_cost", [left, right, label], name=name)


def huber_regression_cost(input, label, delta=1.0, name=None, **kwargs):
    return _simple("huber_cost", [input, label], name=name,
                   delta=float(delta))


def multi_binary_label_cross_entropy(input, label, name=None, **kwargs):
    return _simple("multi_binary_ce", [input, label], name=name)


def smooth_l1_cost(input, label, name=None, **kwargs):
    return _simple("smooth_l1_cost", [input, label], name=name)


def sum_cost(input, name=None, **kwargs):
    return _simple("sum_cost", input, name=name)


def square_error_cost(input, label, name=None, **kwargs):
    return mse_cost(input, label, name=name)


def scale_shift_layer(input, name=None, param_attr=None, bias_attr=None,
                      **kwargs):
    """y = w*x + b with ONE learned scale and bias (reference
    ScaleShiftLayer)."""
    return _simple("scale_shift", input, name=name, param_attr=param_attr,
                   bias_attr=bias_attr)


def gated_unit_layer(input, size, act=None, name=None, **kwargs):
    """act(fc(x)) * sigmoid(fc(x)) (reference gated_unit_layer)."""
    proj = fc_layer(input=input, size=size, act=act)
    gate = fc_layer(input=input, size=size,
                    act=SigmoidActivation())
    return _simple("elem_mul", [proj, gate], name=name)


__all__ += [
    "cos_sim", "trans_layer", "power_layer", "scaling_layer",
    "interpolation_layer", "slope_intercept_layer", "sum_to_one_norm_layer",
    "row_l2_norm_layer", "dot_prod_layer", "out_prod_layer",
    "l2_distance_layer", "pad_layer", "clip_layer", "multiplex_layer",
    "row_conv_layer", "maxout_layer", "block_expand_layer",
    "seq_reshape_layer", "repeat_layer", "recurrent_layer", "ctc_layer",
    "warp_ctc_layer", "crf_layer", "crf_decoding_layer", "nce_layer",
    "hsigmoid", "rank_cost", "huber_regression_cost",
    "multi_binary_label_cross_entropy", "smooth_l1_cost", "sum_cost",
    "square_error_cost", "scale_shift_layer", "gated_unit_layer",
]


def sampling_id_layer(input, name=None, **kwargs):
    """Sample a class id per row from probabilities (reference
    SamplingIdLayer)."""
    return _simple("sampling_id", input, name=name)


def bilinear_interp_layer(input, out_size_x, out_size_y, name=None,
                          **kwargs):
    inp, (c, h, w) = _ensure_image(_as_list(input)[0], None)
    node = _simple("bilinear_interp", inp, name=name,
                   out_h=int(out_size_y), out_w=int(out_size_x))
    node.im_shape = (c, int(out_size_y), int(out_size_x))
    return node


def conv_shift_layer(a, b, name=None, **kwargs):
    """Circular convolution of a's rows by b's (odd-width) rows
    (reference ConvShiftLayer)."""
    return _simple("conv_shift", [a, b], name=name)


def switch_order_layer(input, reshape_axis=None, name=None, **kwargs):
    """NCHW -> NHWC (reference SwitchOrderLayer)."""
    inp, (c, h, w) = _ensure_image(_as_list(input)[0], None)
    return _simple("switch_order", inp, name=name, shape=[c, h, w])


def spp_layer(input, pyramid_height=2, num_channels=None, pool_type=None,
              name=None, **kwargs):
    """Spatial pyramid pooling (reference SpatialPyramidPoolLayer): pool
    the map at pyramid levels 1x1, 2x2, ... and concat the flats."""
    inp, (c, h, w) = _ensure_image(_as_list(input)[0], num_channels)
    ptype = "max"
    if pool_type is not None:
        p = pool_type if isinstance(pool_type, _Pooling) else pool_type()
        ptype = "avg" if p.name in ("avg", "sum") else "max"
    return _simple("spp", inp, name=name, pyramid_height=int(pyramid_height),
                   pool_type=ptype, im_shape=[c, h, w])


def factorization_machine(input, factor_size, param_attr=None, name=None,
                          **kwargs):
    """Second-order FM interaction term (reference
    FactorizationMachineLayer): 0.5 * sum_f[(x V)_f^2 - (x^2)(V^2)_f]."""
    return _simple("factorization_machine", input, name=name,
                   factor_size=int(factor_size), param_attr=param_attr)


def huber_classification_cost(input, label, name=None, **kwargs):
    """Huberised hinge loss on +-1 labels (reference
    HuberTwoClassification)."""
    return _simple("huber_cls_cost", [input, _label_node(label)], name=name)


def dotmul_operator(a=None, b=None, scale=1.0, **kwargs):
    """Element-wise a*b term inside a mixed_layer (reference
    DotMulOperator; two-input mixed operator)."""
    if not isinstance(a, Layer) or not isinstance(b, Layer):
        raise TypeError(
            "dotmul_operator needs two layers: dotmul_operator(a=x, b=y)"
        )
    proj = _Projection("dotmul_op", a, scale=float(scale))
    proj.extra_inputs = [b]
    return proj


__all__ += [
    "sampling_id_layer", "bilinear_interp_layer", "conv_shift_layer",
    "switch_order_layer", "spp_layer", "factorization_machine",
    "huber_classification_cost", "dotmul_operator",
]


def seq_slice_layer(input, starts=None, ends=None, name=None, **kwargs):
    """Per-sequence subranges (reference seq_slice_layer): keeps rows
    [starts_i, ends_i) of each sequence. starts/ends are layers of one
    int per sequence; None means begin/end of each sequence."""
    return Layer("seq_slice", name,
                 [input] + [x for x in (starts, ends) if x is not None],
                 {"has_starts": starts is not None,
                  "has_ends": ends is not None})


def sub_seq_layer(input, offsets, sizes, name=None, **kwargs):
    """Sub-sequences by (offset, size) per sequence (reference
    SubSequenceLayer)."""
    return Layer("sub_seq", name, [input, offsets, sizes], {})


def lstm_step_layer(input, state, size=None, act=None,
                    gate_act=None, state_act=None, name=None, **kwargs):
    """One LSTM step inside a recurrent_group (reference LstmStepLayer):
    `input` is the 4H pre-projection, `state` the cell memory. Returns
    the hidden; the updated cell is reachable via
    get_output_layer(..., arg_name='state')."""
    return Layer("lstm_step", name, [input, state], {
        "size": size,
    })


def gru_step_layer(input, output_mem, size=None, act=None, gate_act=None,
                   name=None, param_attr=None, bias_attr=None, **kwargs):
    """One GRU step inside a recurrent_group (reference GruStepLayer):
    `input` is the 3H pre-projection, `output_mem` the hidden memory."""
    return Layer("gru_step", name, [input, output_mem], {
        "size": size, "param_attr": param_attr, "bias_attr": bias_attr,
    })


gru_step_naive_layer = gru_step_layer


def get_output_layer(input, arg_name="state", name=None, **kwargs):
    """Secondary output of a multi-output step layer (reference
    GetOutputLayer): e.g. the cell state of lstm_step_layer."""
    return Layer("get_output", name, [input], {"arg_name": arg_name})


def tensor_layer(a, b, size, act=None, name=None, param_attr=None,
                 **kwargs):
    """Bilinear tensor product (reference TensorLayer):
    out_k = a W_k b^T with W_k [da, db], k < size."""
    return Layer("tensor", name, [a, b], {
        "size": int(size), "act": _act_name(act), "param_attr": param_attr,
    })


def selective_fc_layer(input, size, select=None, act=None, name=None,
                       param_attr=None, bias_attr=None, **kwargs):
    """Reference selective_fc_layer; with select=None it equals fc (the
    full-output case, which is what training configs use — the
    inference-time column selection is a serving optimisation the fused
    XLA matmul does not need)."""
    if select is not None:
        raise NotImplementedError(
            "selective_fc with a selection input: the full-matmul path "
            "makes column selection unnecessary on TPU"
        )
    return fc_layer(input=input, size=size, act=act, name=name,
                    param_attr=param_attr, bias_attr=bias_attr)


__all__ += [
    "seq_slice_layer", "sub_seq_layer", "lstm_step_layer",
    "gru_step_layer", "gru_step_naive_layer", "get_output_layer",
    "tensor_layer", "selective_fc_layer",
]


def printer_layer(input, format=None, name=None, **kwargs):
    """Pass-through that prints values at run time is a debug aid the
    fused-XLA executor cannot interleave; parity surface: identity
    (reference PrintLayer prints to the trainer log)."""
    return _simple("identity", input, name=name)


def resize_layer(input, size, name=None, **kwargs):
    """Reshape rows to width `size` (reference ResizeLayer)."""
    return _simple("resize", input, name=name, size=int(size))


def rotate_layer(input, height=None, width=None, name=None, **kwargs):
    """90-degree CLOCKWISE rotation of each feature map (reference
    RotateLayer: out(c, H-1-r) = in(r, c)); height/width declare the
    geometry when the input has none."""
    src = _as_list(input)[0]
    if height and width and getattr(src, "im_shape", None) is None:
        size = src.attrs["type"].dim
        src.im_shape = (size // (height * width), int(height), int(width))
    inp, (c, h, w) = _ensure_image(src, None)
    node = _simple("rotate", inp, name=name)
    node.im_shape = (c, w, h)
    return node


def cross_channel_norm_layer(input, name=None, param_attr=None, **kwargs):
    """L2-normalise across channels per spatial position, with a learned
    per-channel scale (reference CrossChannelNormLayer, the SSD conv4_3
    norm)."""
    inp, (c, h, w) = _ensure_image(_as_list(input)[0], None)
    node = _simple("cross_channel_norm", inp, name=name,
                   channels=c, param_attr=param_attr)
    node.im_shape = (c, h, w)
    return node


def slice_projection(input, slices, **kwargs):
    """Column slices of the input concatenated (reference
    slice_projection): slices = [(start, end), ...]."""
    return _Projection("slice", input, slices=[
        (int(a), int(b)) for a, b in slices
    ])


__all__ += [
    "printer_layer", "resize_layer", "rotate_layer",
    "cross_channel_norm_layer", "slice_projection",
]


# ---------------------------------------------------------------------
# breadth round 5: detection, image geometry, 3-D conv/pool, ranking
# costs — the last block of reference layers.py wrappers (priorbox:1117,
# multibox_loss:1178, detection_output:1052, roi_pool:1311, crop:6205,
# prelu:6565, img_conv3d:6788, img_pool3d:2709, scale_sub_region:7302,
# kmax_seq_score:6471, sub_nested_seq:6133, lambda_cost:5771,
# cross_entropy_with_selfnorm:5884, cross_entropy_over_beam:6384,
# linear_comb:5207, conv_operator:4789, conv_projection:4869,
# gru_step_naive:3951)
# ---------------------------------------------------------------------


def _triple3(v):
    return list(v) if isinstance(v, (list, tuple)) else [v] * 3


def crop_layer(input, offset, axis=2, shape=None, name=None, **kwargs):
    """Crop along trailing axes of an NCHW image (reference CropLayer):
    `offset`/`shape` cover axes [axis:] of the 4-D tensor."""
    inp, (c, h, w) = _ensure_image(_as_list(input)[0], None)
    node = Layer("crop", name, [inp], {
        "offset": list(offset), "axis": axis,
        "shape": list(shape) if shape is not None else None,
    })
    if shape is not None:
        full = [c, h, w]
        full[axis - 1:] = list(shape)[: 4 - axis]
        node.im_shape = tuple(full)
    else:
        node.im_shape = (c, h, w)
    return node


def prelu_layer(input, name=None, partial_sum=1, channel_shared=None,
                num_channels=None, param_attr=None, **kwargs):
    """Parametric ReLU (reference PReluLayer): partial_sum groups inputs
    sharing one alpha — 1 = element-wise, one channel's extent =
    channel-wise, the whole width = all-shared."""
    inp = _as_list(input)[0]
    shape = getattr(inp, "im_shape", None)
    if channel_shared:
        mode = "all"
    elif partial_sum == 1:
        # reference: each element its own weight
        mode = "element"
    elif shape is not None and partial_sum >= shape[0] * shape[1] * shape[2]:
        mode = "all"
    elif shape is not None and partial_sum == shape[1] * shape[2]:
        mode = "channel"
    else:
        mode = "channel" if shape is not None else "all"
    node = Layer("prelu", name, [inp], {
        "mode": mode, "param_attr": param_attr,
    })
    node.im_shape = shape
    return node


def priorbox_layer(input, image, aspect_ratio, variance, min_size,
                   max_size=[], name=None, **kwargs):
    """SSD anchor generation (reference PriorBoxLayer): the node's main
    output is the [P, 4] box tensor; the variances ride as an auxiliary
    `<name>@var` binding consumed by detection_output/multibox_loss."""
    return Layer("priorbox", name, [input, image], {
        "aspect_ratio": list(aspect_ratio), "variance": list(variance),
        "min_size": _as_list(min_size), "max_size": _as_list(max_size),
    })


def detection_output_layer(input_loc, input_conf, priorbox, num_classes,
                           nms_threshold=0.45, nms_top_k=400,
                           keep_top_k=200, confidence_threshold=0.01,
                           background_id=0, name=None, **kwargs):
    """SSD inference head (reference DetectionOutputLayer): decode
    per-prior offsets against the priors, softmax confidences, NMS."""
    locs, confs = _as_list(input_loc), _as_list(input_conf)
    node = Layer("detection_output", name, locs + confs + [priorbox], {
        "n_loc": len(locs), "num_classes": num_classes,
        "nms_threshold": nms_threshold, "nms_top_k": nms_top_k,
        "keep_top_k": keep_top_k,
        "confidence_threshold": confidence_threshold,
        "background_id": background_id,
    })
    return node


def multibox_loss_layer(input_loc, input_conf, priorbox, label, num_classes,
                        overlap_threshold=0.5, neg_pos_ratio=3.0,
                        neg_overlap=0.5, background_id=0, name=None,
                        **kwargs):
    """SSD training loss (reference MultiBoxLossLayer): `label` is a
    sequence whose rows are [class, xmin, ymin, xmax, ymax(, difficult)]
    ground-truth boxes per image."""
    locs, confs = _as_list(input_loc), _as_list(input_conf)
    return Layer("multibox_loss", name, locs + confs + [priorbox, label], {
        "n_loc": len(locs), "num_classes": num_classes,
        "overlap_threshold": overlap_threshold,
        "neg_pos_ratio": neg_pos_ratio, "neg_overlap": neg_overlap,
        "background_id": background_id,
    })


def roi_pool_layer(input, rois, pooled_width, pooled_height, spatial_scale,
                   num_channels=None, name=None, **kwargs):
    """ROI max pooling (reference ROIPoolLayer)."""
    inp, (c, h, w) = _ensure_image(_as_list(input)[0], num_channels)
    node = Layer("roi_pool", name, [inp, rois], {
        "pooled_width": pooled_width, "pooled_height": pooled_height,
        "spatial_scale": spatial_scale,
    })
    node.im_shape = (c, pooled_height, pooled_width)
    return node


def scale_sub_region_layer(input, indices, value, name=None, **kwargs):
    """Scale a per-sample (C, H, W) box by `value` (reference
    ScaleSubRegionLayer); indices rows are 1-based inclusive
    [c0, c1, h0, h1, w0, w1]."""
    inp, shape = _ensure_image(_as_list(input)[0], None)
    node = Layer("scale_sub_region", name, [inp, indices],
                 {"value": value})
    node.im_shape = shape
    return node


def img_conv3d_layer(input, filter_size, num_filters, name=None,
                     num_channels=None, act=None, groups=1, stride=1,
                     padding=0, bias_attr=None, param_attr=None,
                     shared_biases=True, layer_attr=None, trans=False,
                     layer_type=None, **kwargs):
    """3-D convolution over NCDHW volumes (reference Conv3DLayer). Flat
    data inputs are reshaped assuming cubic volumes (side =
    cbrt(size/channels)), matching config_parser's square-image default
    extended to 3-D."""
    inp = _as_list(input)[0]
    vol = getattr(inp, "vol_shape", None)
    if vol is None:
        if inp.kind != "data":
            raise ValueError(
                "img_conv3d_layer input %r has no volume shape; feed it "
                "a data layer (cubic volume inferred) or another 3-D "
                "layer" % inp.name
            )
        size = inp.attrs["type"].dim
        c = num_channels or 3
        side = int(round((size // c) ** (1.0 / 3)))
        vol = (c, side, side, side)
        inp = Layer("vol_reshape", None, [inp], {"shape": list(vol)})
        inp.vol_shape = vol
    node = Layer("img_conv3d", name, [inp], {
        "filter_size": filter_size, "num_filters": num_filters,
        "act": _act_name(act),
        "groups": groups, "stride": stride, "padding": padding,
        "bias": bias_attr is not False, "param_attr": param_attr,
    })
    fs, st, pd = (_triple3(filter_size), _triple3(stride),
                  _triple3(padding))
    node.vol_shape = (num_filters,) + tuple(
        _conv_out(d, f, s, p) for d, f, s, p in zip(vol[1:], fs, st, pd)
    )
    return node


def img_pool3d_layer(input, pool_size, name=None, num_channels=None,
                     pool_type=None, stride=1, padding=0, layer_attr=None,
                     ceil_mode=True, **kwargs):
    """3-D pooling over NCDHW volumes (reference Pool3DLayer)."""
    ptype = "avg" if isinstance(pool_type, AvgPooling) or pool_type is AvgPooling else "max"
    inp = _as_list(input)[0]
    vol = getattr(inp, "vol_shape", None)
    if vol is None:
        raise ValueError(
            "img_pool3d_layer input %r has no volume shape; it must come "
            "from img_conv3d_layer (or another 3-D layer)" % inp.name
        )
    node = Layer("img_pool3d", name, [inp], {
        "pool_size": pool_size,
        "pool_type": ptype, "stride": stride, "padding": padding,
        "ceil_mode": ceil_mode,
    })
    node.vol_shape = (vol[0],) + tuple(
        _pool_out(d, ps, st, pd, ceil_mode)
        for d, ps, st, pd in zip(vol[1:], _triple3(pool_size),
                                 _triple3(stride), _triple3(padding))
    )
    return node


def linear_comb_layer(weights, vectors, size=None, name=None, **kwargs):
    """Weighted sum of sub-vectors (reference ConvexCombinationLayer):
    out[j] = sum_i weights[i] * vectors[i*size + j]."""
    return Layer("linear_comb", name, [weights, vectors], {"size": size})


def kmax_seq_score_layer(input, name=None, beam_size=1, **kwargs):
    """Within-sequence indices of the top-`beam_size` scores per
    sequence (reference KmaxSeqScoreLayer), -1 padded."""
    return Layer("kmax_seq_score", name, _as_list(input),
                 {"beam_size": beam_size})


def sub_nested_seq_layer(input, selected_indices, name=None, **kwargs):
    """Select sub-sequences of a nested sequence by per-sequence indices
    (reference SubNestedSequenceLayer)."""
    return Layer("sub_nested_seq", name, [input, selected_indices], {})


def lambda_cost(input, score, name=None, NDCG_num=5, max_sort_size=-1,
                **kwargs):
    """LambdaRank listwise cost (reference LambdaCost): `input` is the
    model score sequence, `score` the relevance labels. Full-sort
    (max_sort_size=-1) semantics."""
    return Layer("lambda_cost", name, [input, score],
                 {"NDCG_num": NDCG_num})


def cross_entropy_with_selfnorm(input, label, name=None, coeff=1.0,
                                softmax_selfnorm_alpha=0.1, **kwargs):
    """Self-normalised CE (reference MultiClassCrossEntropyWithSelfNorm,
    CostLayer.cpp:113): CE - though over an UNnormalised row - plus
    log(Z) + alpha*log(Z)^2 where Z is the row sum."""
    return Layer("ce_selfnorm", name, [input, _label_node(label)], {
        "coeff": coeff, "alpha": softmax_selfnorm_alpha,
    })


class BeamInput(object):
    """A (candidate_scores, selected_candidates, gold) triple feeding
    cross_entropy_over_beam (reference layers.py BeamInput:6362)."""

    def __init__(self, candidate_scores, selected_candidates, gold):
        self.candidate_scores = candidate_scores
        self.selected_candidates = selected_candidates
        self.gold = gold


def cross_entropy_over_beam(input, name=None, **kwargs):
    """Globally normalised CE over beam expansions (reference
    CrossEntropyOverBeam.cpp); `input` is a list of BeamInput triples."""
    beams = _as_list(input)
    parents = []
    for b in beams:
        parents += [b.candidate_scores, b.gold]
    return Layer("ce_over_beam", name, parents, {"n_beams": len(beams)})


def conv_operator(img, filter, filter_size, num_filters, num_channels=None,
                  stride=1, padding=0, **kwargs):
    """Convolution term inside a mixed_layer (reference ConvOperator):
    filter comes from a layer (dynamic weights)."""
    proj = _Projection(
        "conv_op", img, filter_size=filter_size, num_filters=num_filters,
        num_channels=num_channels, stride=stride, padding=padding,
    )
    proj.extra_inputs = [filter]
    return proj


def conv_projection(input, filter_size, num_filters, num_channels=None,
                    stride=1, padding=0, groups=1, param_attr=None,
                    **kwargs):
    """Convolution projection inside a mixed_layer (reference
    ConvProjection): learned filter parameter."""
    return _Projection(
        "conv_proj", input, filter_size=filter_size,
        num_filters=num_filters, num_channels=num_channels, stride=stride,
        padding=padding, groups=groups, param_attr=param_attr,
    )


__all__ += [
    "crop_layer", "prelu_layer", "priorbox_layer",
    "detection_output_layer", "multibox_loss_layer", "roi_pool_layer",
    "scale_sub_region_layer", "img_conv3d_layer", "img_pool3d_layer",
    "linear_comb_layer", "kmax_seq_score_layer", "sub_nested_seq_layer",
    "lambda_cost", "cross_entropy_with_selfnorm", "BeamInput",
    "cross_entropy_over_beam", "gru_step_naive_layer", "conv_operator",
    "conv_projection",
]


# composite network helpers (reference networks.py) — star-import them
# into the DSL namespace the way the reference's config environment does
from . import networks  # noqa: E402
from .networks import (  # noqa: E402,F401
    bidirectional_gru, bidirectional_lstm, dot_product_attention,
    gru_group, gru_unit, img_conv_bn_pool, img_separable_conv,
    lstmemory_group, lstmemory_unit, multi_head_attention,
    sequence_conv_pool, simple_attention, simple_gru, simple_gru2,
    simple_img_conv_pool, small_vgg, text_conv_pool, vgg_16_network,
)
from .networks import inputs as inputs  # noqa: E402,F401

__all__ += [n for n in networks.__all__ if n != "outputs"]


# evaluator wrappers (reference trainer_config_helpers/evaluators.py)
from . import evaluators  # noqa: E402
from .evaluators import (  # noqa: E402,F401
    auc_evaluator, chunk_evaluator, classification_error_evaluator,
    classification_error_printer_evaluator, column_sum_evaluator,
    ctc_error_evaluator, detection_map_evaluator, evaluator_base,
    gradient_printer_evaluator, maxframe_printer_evaluator,
    maxid_printer_evaluator, pnpair_evaluator,
    precision_recall_evaluator, seqtext_printer_evaluator,
    sum_evaluator, value_printer_evaluator,
)

__all__ += list(evaluators.__all__)


# ---------------------------------------------------------------------
# remaining optimizers / poolings / attrs / decorators (reference
# trainer_config_helpers/{optimizers,poolings,attrs,
# default_decorators}.py)
# ---------------------------------------------------------------------


class Optimizer(object):
    """Base of the DSL optimizer classes (reference optimizers.py
    Optimizer): subclasses implement make(lr) -> fluid optimizer."""

    def make(self, lr):
        raise NotImplementedError


BaseSGDOptimizer = Optimizer


class AdamaxOptimizer(Optimizer):
    def __init__(self, beta1=0.9, beta2=0.999, **kwargs):
        self.beta1, self.beta2 = beta1, beta2

    def make(self, lr):
        from .. import fluid

        return fluid.optimizer.Adamax(
            learning_rate=lr, beta1=self.beta1, beta2=self.beta2
        )


class AdaDeltaOptimizer(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, **kwargs):
        self.rho, self.epsilon = rho, epsilon

    def make(self, lr):
        from .. import fluid

        return fluid.optimizer.Adadelta(
            learning_rate=lr, rho=self.rho, epsilon=self.epsilon
        )


class DecayedAdaGradOptimizer(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, **kwargs):
        self.rho, self.epsilon = rho, epsilon

    def make(self, lr):
        from .. import fluid

        return fluid.optimizer.DecayedAdagrad(
            learning_rate=lr, decay=self.rho, epsilon=self.epsilon
        )


class BaseRegularization(object):
    """Base of the DSL regularization markers (reference optimizers.py
    BaseRegularization); L1/L2Regularization carry a `rate`."""

    def __init__(self, rate=0.0):
        self.rate = float(rate)


class L1Regularization(BaseRegularization):
    pass


class L2Regularization(BaseRegularization):  # noqa: F811
    """Rebinds the early definition under the shared base so
    isinstance(x, BaseRegularization) covers both L1 and L2."""


class ModelAverage(object):
    """Parameter averaging window (reference optimizers.py ModelAverage
    / trainer sgd average_window). IMPLEMENTED: both the v2 trainer and
    the CLI build in-graph EMA slots from this spec
    (fluid.optimizer.ModelAverage.from_spec); v2 test()/
    save_parameter_to_tar and --job=test evaluate/export the averaged
    weights."""

    def __init__(self, average_window, max_average_window=None, **kwargs):
        self.average_window = float(average_window)
        self.max_average_window = max_average_window


BasePoolingType = _Pooling


class SquareRootNPooling(_Pooling):
    name = "sqrt"


class MaxWithMaskPooling(_Pooling):
    name = "max"


# cudnn pooling variants are device hints in the reference; identical
# math here (XLA picks the implementation)
CudnnMaxPooling = MaxPooling
CudnnAvgPooling = AvgPooling
CudnnAvgInclPadPooling = AvgPooling

ParameterAttribute = ParamAttr


class HookAttr(object):
    """Parameter update hook marker (reference attrs.py HookAttribute:
    pruning masks etc). Recorded; pruning-style hooks are not executed
    by the TPU core (documented stance — static masks belong in the
    program, not a post-update hook)."""

    def __init__(self, type=None, sparsity_ratio=None, **kwargs):
        self.type = type
        self.sparsity_ratio = sparsity_ratio


HookAttribute = HookAttr


# --- default_decorators (reference default_decorators.py): utility
# decorators some external configs import directly -------------------


def wrap_name_default(prefix=None, name_prefix=None):
    """Fill a None `name` kwarg with an auto-generated unique name.
    Names draw from Layer's own per-kind counter namespace so they can
    never collide with auto-named layers (v2/layer.py Layer.__init__)."""
    p = prefix or name_prefix or "layer"

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if kwargs.get("name") is None:
                i = Layer._counters.get(p, 0)
                Layer._counters[p] = i + 1
                kwargs["name"] = "__%s_%d__" % (p, i)
            return fn(*args, **kwargs)

        return wrapper

    return deco


def _wrap_default(key, builtin_factory):
    """Reference default_decorators.wrap_param_default shape: optional
    `param_names` list (defaults to [key]) and `default_factory`
    (called with the decorated function) override the built-in."""

    def outer(param_names=None, default_factory=None, **_ignored):
        names = list(param_names) if isinstance(
            param_names, (list, tuple)
        ) else [key]
        fn = param_names if callable(param_names) else None

        def deco(f):
            import functools

            @functools.wraps(f)
            def wrapper(*args, **kwargs):
                for n in names:
                    if kwargs.get(n) is None:
                        kwargs[n] = (
                            default_factory(f) if default_factory
                            else builtin_factory()
                        )
                return f(*args, **kwargs)

            return wrapper

        return deco(fn) if fn is not None else deco

    return outer


wrap_param_attr_default = _wrap_default("param_attr", lambda: ParamAttr())
wrap_bias_attr_default = _wrap_default("bias_attr", lambda: None)
wrap_act_default = _wrap_default("act", lambda: TanhActivation())
wrap_param_default = _wrap_default("param_attr", lambda: ParamAttr())

__all__ += [
    "Optimizer", "BaseSGDOptimizer", "AdamaxOptimizer",
    "AdaDeltaOptimizer", "DecayedAdaGradOptimizer",
    "BaseRegularization", "L1Regularization", "ModelAverage",
    "BasePoolingType", "SquareRootNPooling", "MaxWithMaskPooling",
    "CudnnMaxPooling", "CudnnAvgPooling", "CudnnAvgInclPadPooling",
    "ParameterAttribute", "HookAttr", "HookAttribute",
    "wrap_name_default", "wrap_param_attr_default",
    "wrap_bias_attr_default", "wrap_act_default", "wrap_param_default",
]


# ---------------------------------------------------------------------
# layer-surface compatibility objects (reference layers.py:155,289,315,
# 393,1836,4203): enumerations and base classes that reference configs
# import by name. The sequence-level enums carry the same wire strings
# the reference config_parser understands ('non-seq'/'seq'); the rest
# are structural parity for isinstance checks and introspection.
# ---------------------------------------------------------------------


class AggregateLevel(object):
    """Which nesting level a sequence aggregation collapses
    (reference layers.py:289)."""

    TO_NO_SEQUENCE = "non-seq"
    TO_SEQUENCE = "seq"
    # compatible with previous configuration names
    EACH_TIMESTEP = TO_NO_SEQUENCE
    EACH_SEQUENCE = TO_SEQUENCE


class ExpandLevel(object):
    """Which nesting level an expansion starts from
    (reference layers.py:1836)."""

    FROM_NO_SEQUENCE = AggregateLevel.TO_NO_SEQUENCE
    FROM_SEQUENCE = AggregateLevel.TO_SEQUENCE
    # compatible with previous configuration names
    FROM_TIMESTEP = FROM_NO_SEQUENCE


class LayerType(object):
    """Layer type name constants (reference layers.py:155). This core
    identifies layers by their op graph rather than a type registry, so
    the constants exist for config/introspection compatibility."""

    DATA = "data"
    FC_LAYER = "fc"
    MIXED_LAYER = "mixed"
    LSTMEMORY = "lstmemory"
    GRUMEMORY = "gated_recurrent"
    SEQUENCE_LAST_INSTANCE = "seqlastins"
    SEQUENCE_FIRST_INSTANCE = "seqfirstins"
    SEQUENCE_RESHAPE = "seqreshape"
    POOLING_MAX = "max"
    POOLING_AVG = "average"
    COST = "cost"
    CONV_LAYER = "conv"

    @staticmethod
    def is_layer_type(type_name):
        return isinstance(type_name, str) and bool(type_name)


# wrappers here return v2-layer graph nodes; LayerOutput is the
# reference's name for that node type (layers.py:315)
LayerOutput = Layer


def layer_support(*attrs):
    """Decorator marking which ExtraLayerAttribute fields a wrapper
    honors (reference layers.py:393). Attribute enforcement here happens
    in the wrappers themselves, so the decorator only preserves the
    wrapped function's identity."""

    def decorator(method):
        return method

    return decorator


# V1-compatibility aliases (reference layers.py:1123 print_layer,
# :5353 convex_comb_layer)
print_layer = printer_layer
convex_comb_layer = linear_comb_layer
