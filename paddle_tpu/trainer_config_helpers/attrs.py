"""Attribute classes as a module (reference trainer_config_helpers/attrs.py)."""

from . import (  # noqa: F401
    ExtraAttr,
    ExtraLayerAttribute,
    HookAttr,
    ParamAttr,
    ParameterAttribute,
)

__all__ = [
    "HookAttr", "ParamAttr", "ExtraAttr",
    "ParameterAttribute", "ExtraLayerAttribute",
]
