"""Math operator sugar on DSL layer nodes (reference
trainer_config_helpers/layer_math.py): `a + b`, `a - 2.0`, `0.5 * a`,
and unary `layer_math.exp(a)` etc. build the same graph nodes the
explicit wrappers would."""

from __future__ import annotations

import paddle_tpu.trainer_config_helpers as tch
from ..v2.layer import Layer

__all__ = []


def _width(node):
    """Feature width of a DSL node: image-shaped nodes report c*h*w,
    others defer to Topology's width inference (v2/topology.py
    _node_width semantics without a Topology instance)."""
    shape = getattr(node, "im_shape", None)
    if shape:
        c, h, w = shape
        return int(c) * int(h) * int(w)
    a = getattr(node, "attrs", {})
    if a.get("size"):
        return int(a["size"])
    t = a.get("type")
    if t is not None:
        return int(t.dim)
    if getattr(node, "parents", None):
        return _width(node.parents[0])
    raise ValueError(
        "cannot infer the feature width of layer %r (%s) for layer_math"
        % (getattr(node, "name", node), getattr(node, "kind", "?"))
    )


def register_unary_math_op(op_name, act):
    def op(input, name=None):
        with tch.mixed_layer(
            size=_width(input), act=act, name=name
        ) as m:
            m += tch.identity_projection(input=input)
        return m

    op.__name__ = op_name
    op.__doc__ = "Elementwise %s over a layer (reference layer_math)." \
        % op_name
    globals()[op_name] = op
    __all__.append(op_name)


register_unary_math_op("exp", tch.ExpActivation())
register_unary_math_op("log", tch.LogActivation())
register_unary_math_op("abs", tch.AbsActivation())
register_unary_math_op("sigmoid", tch.SigmoidActivation())
register_unary_math_op("tanh", tch.TanhActivation())
register_unary_math_op("square", tch.SquareActivation())
register_unary_math_op("relu", tch.ReluActivation())
register_unary_math_op("sqrt", tch.SqrtActivation())
register_unary_math_op("reciprocal", tch.ReciprocalActivation())


def _is_number(x):
    return isinstance(x, (int, float)) and not isinstance(x, bool)


def add(layeroutput, other):
    if _is_number(other):
        return tch.slope_intercept_layer(input=layeroutput,
                                         intercept=float(other))
    if not isinstance(other, Layer):
        raise TypeError("a layer can only be added to a layer or number")
    wa, wb = _width(layeroutput), _width(other)
    if wa != wb:
        if wb == 1:
            other = tch.repeat_layer(input=other, num_repeats=wa)
        elif wa == 1:
            layeroutput = tch.repeat_layer(input=layeroutput,
                                           num_repeats=wb)
            wa = wb
        else:
            raise ValueError(
                "layers added with '+' need equal widths (or width 1): "
                "%s vs %s" % (wa, wb)
            )
    with tch.mixed_layer(size=wa or 0) as m:
        m += tch.identity_projection(input=layeroutput)
        m += tch.identity_projection(input=other)
    return m


def sub(layeroutput, other):
    if _is_number(other):
        return tch.slope_intercept_layer(input=layeroutput,
                                         intercept=-float(other))
    if not isinstance(other, Layer):
        raise TypeError(
            "a layer can only be subtracted by a layer or number"
        )
    return add(layeroutput,
               tch.slope_intercept_layer(input=other, slope=-1.0))


def rsub(layeroutput, other):
    if not (_is_number(other) or isinstance(other, Layer)):
        raise TypeError(
            "a layer can only be subtracted from a layer or number"
        )
    return add(tch.slope_intercept_layer(input=layeroutput, slope=-1.0),
               other)


def mul(layeroutput, other):
    if _is_number(other):
        return tch.slope_intercept_layer(input=layeroutput,
                                         slope=float(other))
    if not isinstance(other, Layer):
        raise TypeError("a layer can only be multiplied by a layer or "
                        "number")
    if _width(layeroutput) == 1:
        return tch.scaling_layer(input=other, weight=layeroutput)
    if _width(other) == 1:
        return tch.scaling_layer(input=layeroutput, weight=other)
    raise ValueError(
        "'*' needs a number or a width-1 layer on one side"
    )


Layer.__add__ = add
Layer.__radd__ = add
Layer.__sub__ = sub
Layer.__rsub__ = rsub
Layer.__mul__ = mul
Layer.__rmul__ = mul
