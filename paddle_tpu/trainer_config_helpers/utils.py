"""trainer_config_helpers/utils.py (reference): the deprecated()
decorator configs import."""

from __future__ import annotations

import functools
import warnings

__all__ = ["deprecated"]


def deprecated(instead):
    def deco(func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            warnings.warn(
                "%s is deprecated, use %s instead"
                % (func.__name__, instead),
                DeprecationWarning, stacklevel=2,
            )
            return func(*args, **kwargs)

        return wrapper

    return deco
