"""Default-filling decorators as a module (reference
trainer_config_helpers/default_decorators.py)."""

from . import (  # noqa: F401
    wrap_act_default,
    wrap_bias_attr_default,
    wrap_name_default,
    wrap_param_attr_default,
    wrap_param_default,
)

__all__ = [
    "wrap_name_default", "wrap_param_attr_default",
    "wrap_bias_attr_default", "wrap_act_default", "wrap_param_default",
]
