"""trainer_config_helpers/config_parser_utils.py (reference): run a
config FUNCTION (or module path) and return its lowered form. The
reference returned protobufs; here the single source of truth is the
fluid Program, so parsers return the built Topology (main_program /
startup_program attributes) or the recorded optimizer settings."""

from __future__ import annotations

__all__ = [
    "parse_network_config", "parse_optimizer_config",
    "parse_trainer_config", "reset_parser",
]


def reset_parser():
    import paddle_tpu.trainer_config_helpers as tch

    tch.reset_config()


def _run(conf, config_arg_str):
    import paddle_tpu.trainer_config_helpers as tch
    from paddle_tpu.trainer import _parse_config_args

    tch.reset_config(_parse_config_args(config_arg_str or ""))
    conf()
    return tch.get_config_state()


def parse_network_config(network_conf, config_arg_str=""):
    """network_conf: a callable building layers and calling outputs().
    Returns the Topology of the recorded outputs."""
    from paddle_tpu.trainer import resolve_config_outputs
    from paddle_tpu.v2.topology import Topology

    state = _run(network_conf, config_arg_str)
    return Topology(resolve_config_outputs(state))


def parse_optimizer_config(optimizer_conf, config_arg_str=""):
    """optimizer_conf: a callable invoking settings(...). Returns the
    recorded settings dict (learning_method / learning_rate / ...)."""
    state = _run(optimizer_conf, config_arg_str)
    return state["settings"]


def parse_trainer_config(trainer_conf, config_arg_str=""):
    """Whole-config form: returns (Topology, settings)."""
    from paddle_tpu.trainer import resolve_config_outputs
    from paddle_tpu.v2.topology import Topology

    state = _run(trainer_conf, config_arg_str)
    return Topology(resolve_config_outputs(state)), state["settings"]
