"""Layer wrappers as a module (reference
trainer_config_helpers/layers.py, 7.5k LoC of wrapper defs). All
wrappers live in the package __init__; this module mirrors the
reference's module path so `from paddle.trainer_config_helpers.layers
import fc_layer` style imports work unchanged."""

from . import __all__ as _pkg_all
from . import *  # noqa: F401,F403

__all__ = list(_pkg_all)
