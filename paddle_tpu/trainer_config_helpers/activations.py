"""Activation objects, importable as a module the way reference configs do
(reference python/paddle/trainer_config_helpers/activations.py). The
classes live in the package __init__; this module re-exports them."""

from . import (  # noqa: F401
    AbsActivation,
    BaseActivation,
    BReluActivation,
    ExpActivation,
    IdentityActivation,
    LinearActivation,
    LogActivation,
    ReciprocalActivation,
    ReluActivation,
    SequenceSoftmaxActivation,
    SigmoidActivation,
    SoftmaxActivation,
    SoftReluActivation,
    SoftSignActivation,
    SqrtActivation,
    SquareActivation,
    STanhActivation,
    TanhActivation,
)

__all__ = [
    "TanhActivation", "SigmoidActivation", "SoftmaxActivation",
    "IdentityActivation", "LinearActivation", "SequenceSoftmaxActivation",
    "ExpActivation", "ReluActivation", "BReluActivation",
    "SoftReluActivation", "STanhActivation", "AbsActivation",
    "SquareActivation", "BaseActivation", "LogActivation",
    "SqrtActivation", "ReciprocalActivation", "SoftSignActivation",
]
