"""Data source declaration as a module (reference
trainer_config_helpers/data_sources.py)."""

from . import define_py_data_sources2  # noqa: F401

__all__ = ["define_py_data_sources2"]
