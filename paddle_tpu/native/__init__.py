"""Native (C++) runtime components, loaded via ctypes.

The reference keeps its data plane native (RecordIO chunks for the Go
master, PyDataProvider2's C++ prefetch queue); this package does the same
for the TPU framework: `recordio.cc` is compiled on first use with the
ambient g++ into a shared library (no pybind11 in this environment — the
C ABI + ctypes is the binding). Pure-Python fallbacks keep the API alive
on machines without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "_build", "librecordio.so")
_SRC = os.path.join(_HERE, "recordio.cc")
_INFER_SO = os.path.join(_HERE, "_build", "libptpu_infer.so")
_INFER_SRC = os.path.join(_HERE, "inference.cc")
_lock = threading.Lock()
_lib = None
_build_error = None
_infer_lib = None
_infer_error = None


def _compile(src: str, so: str) -> str:
    os.makedirs(os.path.dirname(so), exist_ok=True)
    tmp = so + ".tmp.so"
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
        src, "-o", tmp,
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so)
    return so


def _build() -> str:
    return _compile(_SRC, _SO)


def lib():
    """The loaded shared library, building it on first use. Raises
    RuntimeError when no toolchain is available."""
    global _lib, _build_error
    with _lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            raise RuntimeError("native build failed earlier: %s" % _build_error)
        try:
            if not os.path.exists(_SO) or (
                os.path.getmtime(_SRC) > os.path.getmtime(_SO)
            ):
                _build()
            L = ctypes.CDLL(_SO)
        except Exception as e:  # keep the error for later callers
            _build_error = e
            raise RuntimeError("cannot build/load native recordio: %s" % e)
        L.rio_writer_open.restype = ctypes.c_void_p
        L.rio_writer_open.argtypes = [ctypes.c_char_p]
        L.rio_write.restype = ctypes.c_int
        L.rio_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        L.rio_writer_close.argtypes = [ctypes.c_void_p]
        L.rio_open.restype = ctypes.c_void_p
        L.rio_open.argtypes = [ctypes.c_char_p]
        L.rio_next.restype = ctypes.c_int64
        L.rio_next.argtypes = [ctypes.c_void_p]
        L.rio_data.restype = ctypes.POINTER(ctypes.c_uint8)
        L.rio_data.argtypes = [ctypes.c_void_p]
        L.rio_close.argtypes = [ctypes.c_void_p]
        L.pq_open.restype = ctypes.c_void_p
        L.pq_open.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
        ]
        L.pq_next.restype = ctypes.c_int64
        L.pq_next.argtypes = [ctypes.c_void_p]
        L.pq_data.restype = ctypes.POINTER(ctypes.c_uint8)
        L.pq_data.argtypes = [ctypes.c_void_p]
        L.pq_close.argtypes = [ctypes.c_void_p]
        _lib = L
        return _lib


def available() -> bool:
    try:
        lib()
        return True
    except RuntimeError:
        return False


def infer_lib_path() -> str:
    """Build (if needed) and return the path of the native inference
    runner shared library — usable from ANY language via dlopen; no
    paddle_tpu import required at load/forward time (capi parity,
    reference capi/gradient_machine.h:36,73)."""
    global _infer_error
    with _lock:
        if _infer_error is not None:
            raise RuntimeError(
                "native inference build failed earlier: %s" % _infer_error
            )
        try:
            if not os.path.exists(_INFER_SO) or (
                os.path.getmtime(_INFER_SRC) > os.path.getmtime(_INFER_SO)
            ):
                _compile(_INFER_SRC, _INFER_SO)
        except Exception as e:
            _infer_error = e
            raise RuntimeError("cannot build native inference: %s" % e)
        return _INFER_SO


def infer_lib():
    """ctypes handle to the native inference runner with signatures set."""
    global _infer_lib
    path = infer_lib_path()
    with _lock:
        if _infer_lib is not None:
            return _infer_lib
        L = ctypes.CDLL(path)
        L.ptpu_infer_create.restype = ctypes.c_void_p
        L.ptpu_infer_create.argtypes = [ctypes.c_char_p]
        L.ptpu_infer_num_feeds.argtypes = [ctypes.c_void_p]
        L.ptpu_infer_feed_name.restype = ctypes.c_char_p
        L.ptpu_infer_feed_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
        L.ptpu_infer_num_fetch.argtypes = [ctypes.c_void_p]
        L.ptpu_infer_fetch_name.restype = ctypes.c_char_p
        L.ptpu_infer_fetch_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
        L.ptpu_infer_set_input.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ]
        L.ptpu_infer_forward.argtypes = [ctypes.c_void_p]
        L.ptpu_infer_error.restype = ctypes.c_char_p
        L.ptpu_infer_error.argtypes = [ctypes.c_void_p]
        L.ptpu_infer_out_rank.argtypes = [ctypes.c_void_p, ctypes.c_int]
        L.ptpu_infer_out_shape.restype = ctypes.POINTER(ctypes.c_int64)
        L.ptpu_infer_out_shape.argtypes = [ctypes.c_void_p, ctypes.c_int]
        L.ptpu_infer_out_data.restype = ctypes.POINTER(ctypes.c_float)
        L.ptpu_infer_out_data.argtypes = [ctypes.c_void_p, ctypes.c_int]
        L.ptpu_infer_out_lod_len.argtypes = [ctypes.c_void_p, ctypes.c_int]
        L.ptpu_infer_out_lod.restype = ctypes.POINTER(ctypes.c_int64)
        L.ptpu_infer_out_lod.argtypes = [ctypes.c_void_p, ctypes.c_int]
        L.ptpu_infer_set_input_lod.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ]
        L.ptpu_infer_destroy.argtypes = [ctypes.c_void_p]
        _infer_lib = L
        return _infer_lib


class InferenceRunner(object):
    """Convenience Python wrapper over the C ABI (the C ABI itself is the
    deliverable; this class just saves ctypes boilerplate in-process)."""

    def __init__(self, dirname: str):
        import numpy as np

        self._np = np
        self._L = infer_lib()
        self._h = self._L.ptpu_infer_create(dirname.encode())
        if not self._h:
            raise IOError("cannot load inference bundle at %s" % dirname)

    @property
    def feed_names(self):
        L, h = self._L, self._h
        return [
            L.ptpu_infer_feed_name(h, i).decode()
            for i in range(L.ptpu_infer_num_feeds(h))
        ]

    @property
    def fetch_names(self):
        L, h = self._L, self._h
        return [
            L.ptpu_infer_fetch_name(h, i).decode()
            for i in range(L.ptpu_infer_num_fetch(h))
        ]

    def run(self, feeds: dict, lods: dict = None, return_lod: bool = False):
        """feeds: name -> array. lods: name -> offsets (ragged inputs).
        With return_lod, returns (outs, lods_out) where lods_out[k] is
        the k-th fetch's sequence offsets ([] when dense)."""
        np = self._np
        L, h = self._L, self._h
        for name, arr in feeds.items():
            arr = np.asarray(arr)
            if arr.dtype.kind in "iu":
                arr = np.ascontiguousarray(arr, np.int64)
                code = 1
            else:
                arr = np.ascontiguousarray(arr, np.float32)
                code = 0
            shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
            L.ptpu_infer_set_input(
                h, name.encode(),
                arr.ctypes.data_as(ctypes.c_void_p), code, shape, arr.ndim,
            )
        for name, off in (lods or {}).items():
            off = np.ascontiguousarray(off, np.int64)
            rc = L.ptpu_infer_set_input_lod(
                h, name.encode(),
                off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                len(off),
            )
            if rc != 0:
                raise KeyError(
                    "lod for unknown input %r (set its tensor first)"
                    % name
                )
        if L.ptpu_infer_forward(h) != 0:
            raise RuntimeError(
                "native forward failed: %s"
                % L.ptpu_infer_error(h).decode()
            )
        outs = []
        lods_out = []
        for i in range(L.ptpu_infer_num_fetch(h)):
            rank = L.ptpu_infer_out_rank(h, i)
            shape = [L.ptpu_infer_out_shape(h, i)[k] for k in range(rank)]
            n = int(np.prod(shape)) if shape else 1
            data = np.ctypeslib.as_array(
                L.ptpu_infer_out_data(h, i), shape=(n,)
            ).copy()
            outs.append(data.reshape(shape))
            if return_lod:
                ll = L.ptpu_infer_out_lod_len(h, i)
                ptr = L.ptpu_infer_out_lod(h, i) if ll else None
                lods_out.append([ptr[k] for k in range(ll)] if ll else [])
        return (outs, lods_out) if return_lod else outs

    def close(self):
        if self._h:
            self._L.ptpu_infer_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------
# Python surface
# ---------------------------------------------------------------------


class RecordWriter(object):
    """Length-prefixed CRC-checked record file writer.

    NOTE: this is a bespoke on-disk format ([u32 len][u32 crc32][payload]
    per record, recordio.cc), NOT the reference RecordIO chunk layout
    (magic + compressed multi-record chunks, recordio library used by the
    Go master). Files are not interchangeable with reference-produced
    .recordio data; the capability being reproduced is the native
    record-stream + prefetch-queue data plane, not the wire format."""

    def __init__(self, path: str):
        self._h = lib().rio_writer_open(path.encode())
        if not self._h:
            raise IOError("cannot open %s for writing" % path)

    def write(self, payload: bytes):
        if lib().rio_write(self._h, payload, len(payload)) != 0:
            raise IOError("record write failed")

    def close(self):
        if self._h:
            lib().rio_writer_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


def read_records(path: str):
    """Synchronous record iterator."""
    L = lib()
    h = L.rio_open(path.encode())
    if not h:
        raise IOError("cannot open %s" % path)
    try:
        while True:
            n = L.rio_next(h)
            if n <= 0:
                return
            yield ctypes.string_at(L.rio_data(h), n)
    finally:
        L.rio_close(h)


class PrefetchReader(object):
    """Async prefetch over a list of record files: a native thread streams
    records into a bounded queue (PyDataProvider2 double-buffer parity);
    iteration pops from the queue."""

    def __init__(self, paths, capacity: int = 64):
        L = lib()
        arr = (ctypes.c_char_p * len(paths))(
            *[p.encode() for p in paths]
        )
        self._h = L.pq_open(arr, len(paths), capacity)
        self._L = L

    def __iter__(self):
        return self

    def __next__(self):
        n = self._L.pq_next(self._h)
        if n <= 0:
            self.close()
            raise StopIteration
        return ctypes.string_at(self._L.pq_data(self._h), n)

    def close(self):
        if self._h:
            self._L.pq_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
