"""Native (C++) runtime components, loaded via ctypes.

The reference keeps its data plane native (RecordIO chunks for the Go
master, PyDataProvider2's C++ prefetch queue); this package does the same
for the TPU framework: `recordio.cc` is compiled on first use with the
ambient g++ into a shared library (no pybind11 in this environment — the
C ABI + ctypes is the binding). Pure-Python fallbacks keep the API alive
on machines without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "_build", "librecordio.so")
_SRC = os.path.join(_HERE, "recordio.cc")
_lock = threading.Lock()
_lib = None
_build_error = None


def _build() -> str:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    tmp = _SO + ".tmp.so"
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
        _SRC, "-o", tmp,
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, _SO)
    return _SO


def lib():
    """The loaded shared library, building it on first use. Raises
    RuntimeError when no toolchain is available."""
    global _lib, _build_error
    with _lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            raise RuntimeError("native build failed earlier: %s" % _build_error)
        try:
            if not os.path.exists(_SO) or (
                os.path.getmtime(_SRC) > os.path.getmtime(_SO)
            ):
                _build()
            L = ctypes.CDLL(_SO)
        except Exception as e:  # keep the error for later callers
            _build_error = e
            raise RuntimeError("cannot build/load native recordio: %s" % e)
        L.rio_writer_open.restype = ctypes.c_void_p
        L.rio_writer_open.argtypes = [ctypes.c_char_p]
        L.rio_write.restype = ctypes.c_int
        L.rio_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        L.rio_writer_close.argtypes = [ctypes.c_void_p]
        L.rio_open.restype = ctypes.c_void_p
        L.rio_open.argtypes = [ctypes.c_char_p]
        L.rio_next.restype = ctypes.c_int64
        L.rio_next.argtypes = [ctypes.c_void_p]
        L.rio_data.restype = ctypes.POINTER(ctypes.c_uint8)
        L.rio_data.argtypes = [ctypes.c_void_p]
        L.rio_close.argtypes = [ctypes.c_void_p]
        L.pq_open.restype = ctypes.c_void_p
        L.pq_open.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
        ]
        L.pq_next.restype = ctypes.c_int64
        L.pq_next.argtypes = [ctypes.c_void_p]
        L.pq_data.restype = ctypes.POINTER(ctypes.c_uint8)
        L.pq_data.argtypes = [ctypes.c_void_p]
        L.pq_close.argtypes = [ctypes.c_void_p]
        _lib = L
        return _lib


def available() -> bool:
    try:
        lib()
        return True
    except RuntimeError:
        return False


# ---------------------------------------------------------------------
# Python surface
# ---------------------------------------------------------------------


class RecordWriter(object):
    """Length-prefixed CRC-checked record file writer."""

    def __init__(self, path: str):
        self._h = lib().rio_writer_open(path.encode())
        if not self._h:
            raise IOError("cannot open %s for writing" % path)

    def write(self, payload: bytes):
        if lib().rio_write(self._h, payload, len(payload)) != 0:
            raise IOError("record write failed")

    def close(self):
        if self._h:
            lib().rio_writer_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


def read_records(path: str):
    """Synchronous record iterator."""
    L = lib()
    h = L.rio_open(path.encode())
    if not h:
        raise IOError("cannot open %s" % path)
    try:
        while True:
            n = L.rio_next(h)
            if n <= 0:
                return
            yield ctypes.string_at(L.rio_data(h), n)
    finally:
        L.rio_close(h)


class PrefetchReader(object):
    """Async prefetch over a list of record files: a native thread streams
    records into a bounded queue (PyDataProvider2 double-buffer parity);
    iteration pops from the queue."""

    def __init__(self, paths, capacity: int = 64):
        L = lib()
        arr = (ctypes.c_char_p * len(paths))(
            *[p.encode() for p in paths]
        )
        self._h = L.pq_open(arr, len(paths), capacity)
        self._L = L

    def __iter__(self):
        return self

    def __next__(self):
        n = self._L.pq_next(self._h)
        if n <= 0:
            self.close()
            raise StopIteration
        return ctypes.string_at(self._L.pq_data(self._h), n)

    def close(self):
        if self._h:
            self._L.pq_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
