// Native C inference runner: load a saved inference bundle
// (__model__ JSON program + one .npy per persistable, written by
// fluid/io.py save_inference_model) and run forward — with NO Python.
//
// Capability parity with the reference pure-C serving surface:
//   paddle/capi/gradient_machine.h:36  paddle_gradient_machine_create_for_inference
//   paddle/capi/gradient_machine.h:73  paddle_gradient_machine_forward
//   paddle/fluid/inference/io.cc:108   inference::Load (ProgramDesc + persistables)
//
// TPU-first stance: training and batch serving run through XLA; this
// runner is the *edge/embedded* path the reference's capi serves —
// a dependency-free CPU interpreter over the same language-neutral
// bundle, exposed as a C ABI loaded via ctypes/dlopen from any host
// language. f32 compute; integer feeds (embedding ids) are carried as
// a separate int64 payload per tensor.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC inference.cc -o libptpu_infer.so

#include <algorithm>
#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <map>
#include <memory>
#include <set>
#include <limits>
#include <sstream>
#include <string>
#include <vector>

namespace {

// ---------------------------------------------------------------------
// Minimal JSON value + recursive-descent parser (schema is our own, so
// only the constructs serialization.py emits need to parse).
// ---------------------------------------------------------------------
struct JValue {
  enum Kind { NUL, BOOL, NUM, STR, ARR, OBJ } kind = NUL;
  bool b = false;
  double num = 0;
  std::string str;
  std::vector<JValue> arr;
  std::vector<std::pair<std::string, JValue>> obj;  // ordered

  const JValue* get(const std::string& k) const {
    for (auto& kv : obj)
      if (kv.first == k) return &kv.second;
    return nullptr;
  }
  double as_num(double dflt = 0) const { return kind == NUM ? num : dflt; }
  bool as_bool(bool dflt = false) const { return kind == BOOL ? b : dflt; }
};

struct JParser {
  const char* p;
  const char* end;
  bool ok = true;

  explicit JParser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  }
  bool eat(char c) {
    ws();
    if (p < end && *p == c) {
      ++p;
      return true;
    }
    return false;
  }
  JValue parse() {
    JValue v;
    ws();
    if (p >= end) {
      ok = false;
      return v;
    }
    char c = *p;
    if (c == '{') {
      ++p;
      v.kind = JValue::OBJ;
      ws();
      if (eat('}')) return v;
      do {
        ws();
        JValue key = parse_string();
        if (!ok || !eat(':')) {
          ok = false;
          return v;
        }
        v.obj.emplace_back(key.str, parse());
      } while (eat(','));
      if (!eat('}')) ok = false;
    } else if (c == '[') {
      ++p;
      v.kind = JValue::ARR;
      ws();
      if (eat(']')) return v;
      do {
        v.arr.push_back(parse());
      } while (eat(','));
      if (!eat(']')) ok = false;
    } else if (c == '"') {
      v = parse_string();
    } else if (c == 't') {
      v.kind = JValue::BOOL;
      v.b = true;
      p += 4;
    } else if (c == 'f') {
      v.kind = JValue::BOOL;
      v.b = false;
      p += 5;
    } else if (c == 'n') {
      v.kind = JValue::NUL;
      p += 4;
    } else {
      v.kind = JValue::NUM;
      char* q = nullptr;
      v.num = strtod(p, &q);
      if (q == p) ok = false;
      p = q;
    }
    return v;
  }
  JValue parse_string() {
    JValue v;
    v.kind = JValue::STR;
    ws();
    if (p >= end || *p != '"') {
      ok = false;
      return v;
    }
    ++p;
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) {
        ++p;
        switch (*p) {
          case 'n': v.str += '\n'; break;
          case 't': v.str += '\t'; break;
          case 'r': v.str += '\r'; break;
          case 'u': {  // \uXXXX — bundle names are ASCII; keep low byte
            unsigned code = 0;
            sscanf(p + 1, "%4x", &code);
            p += 4;
            v.str += static_cast<char>(code & 0xff);
            break;
          }
          default: v.str += *p;
        }
      } else {
        v.str += *p;
      }
      ++p;
    }
    ++p;  // closing quote
    return v;
  }
};

// ---------------------------------------------------------------------
// Tensor: f32 buffer + optional i64 view (for embedding ids / labels)
// ---------------------------------------------------------------------
struct Tensor {
  std::vector<int64_t> shape;
  std::vector<float> f;
  std::vector<int64_t> i;  // non-empty when the tensor is integral
  // ragged metadata: sequence start offsets over rows (reference
  // LoDTensor level 0; the Python side's "<name>@LOD0" side-band)
  std::vector<int64_t> lod;
  bool is_int = false;

  int64_t numel() const {
    int64_t n = 1;
    for (auto d : shape) n *= d;
    return n;
  }
  void resize_like_shape() {
    if (is_int)
      i.assign(numel(), 0);
    else
      f.assign(numel(), 0.f);
  }
  float at(int64_t k) const { return is_int ? static_cast<float>(i[k]) : f[k]; }
};

// flatten [d0..dk-1, dk..dn] -> [prod(前), prod(后)]
static void flatten2(const Tensor& t, int num_col_dims, int64_t* rows,
                     int64_t* cols) {
  int64_t r = 1, c = 1;
  for (size_t k = 0; k < t.shape.size(); ++k) {
    if ((int)k < num_col_dims)
      r *= t.shape[k];
    else
      c *= t.shape[k];
  }
  *rows = r;
  *cols = c;
}

// ---------------------------------------------------------------------
// .npy reader (format spec 1.0): magic, header dict, raw little-endian
// ---------------------------------------------------------------------
static bool load_npy(const std::string& path, Tensor* out) {
  std::ifstream fs(path, std::ios::binary);
  if (!fs) return false;
  char magic[6];
  fs.read(magic, 6);
  if (memcmp(magic, "\x93NUMPY", 6) != 0) return false;
  unsigned char ver[2];
  fs.read(reinterpret_cast<char*>(ver), 2);
  uint32_t hlen = 0;
  if (ver[0] == 1) {
    uint16_t h16 = 0;
    fs.read(reinterpret_cast<char*>(&h16), 2);
    hlen = h16;
  } else {
    fs.read(reinterpret_cast<char*>(&hlen), 4);
  }
  std::string header(hlen, '\0');
  fs.read(&header[0], hlen);

  auto find_val = [&](const char* key) -> std::string {
    auto pos = header.find(key);
    if (pos == std::string::npos) return "";
    pos = header.find(':', pos);
    auto endp = header.find(',', pos);
    // shape tuple contains commas: go to matching ')'
    auto paren = header.find('(', pos);
    if (paren != std::string::npos && paren < endp) {
      endp = header.find(')', paren);
      if (endp != std::string::npos) ++endp;
    }
    return header.substr(pos + 1, endp - pos - 1);
  };
  std::string descr = find_val("'descr'");
  std::string shape_s = find_val("'shape'");
  bool fortran = find_val("'fortran_order'").find("True") != std::string::npos;
  if (fortran) return false;  // numpy default is C order; we only emit that

  out->shape.clear();
  for (size_t k = 0; k < shape_s.size();) {
    if (isdigit(shape_s[k])) {
      char* q = nullptr;
      out->shape.push_back(strtoll(&shape_s[k], &q, 10));
      k = q - shape_s.data();
    } else {
      ++k;
    }
  }
  int64_t n = 1;
  for (auto d : out->shape) n *= d;

  auto read_all = [&](void* dst, size_t bytes) {
    fs.read(reinterpret_cast<char*>(dst), bytes);
    return fs.good() || fs.eof();
  };
  if (descr.find("f4") != std::string::npos) {
    out->is_int = false;
    out->f.resize(n);
    return read_all(out->f.data(), n * 4);
  }
  if (descr.find("f8") != std::string::npos) {
    std::vector<double> tmp(n);
    if (!read_all(tmp.data(), n * 8)) return false;
    out->is_int = false;
    out->f.assign(tmp.begin(), tmp.end());
    return true;
  }
  if (descr.find("i8") != std::string::npos) {
    out->is_int = true;
    out->i.resize(n);
    return read_all(out->i.data(), n * 8);
  }
  if (descr.find("i4") != std::string::npos) {
    std::vector<int32_t> tmp(n);
    if (!read_all(tmp.data(), n * 4)) return false;
    out->is_int = true;
    out->i.assign(tmp.begin(), tmp.end());
    return true;
  }
  return false;
}

// io.py _escape: '/' -> "%2F"
static std::string escape_name(const std::string& name) {
  std::string out;
  for (char c : name)
    if (c == '/')
      out += "%2F";
    else
      out += c;
  return out;
}

// ---------------------------------------------------------------------
// Op descriptors + interpreter
// ---------------------------------------------------------------------
struct OpDesc {
  std::string type;
  std::map<std::string, std::vector<std::string>> inputs, outputs;
  JValue attrs;

  const std::string& in(const char* slot, int k = 0) const {
    static const std::string empty;
    auto it = inputs.find(slot);
    return (it != inputs.end() && (int)it->second.size() > k) ? it->second[k]
                                                              : empty;
  }
  const std::string& out(const char* slot, int k = 0) const {
    static const std::string empty;
    auto it = outputs.find(slot);
    return (it != outputs.end() && (int)it->second.size() > k) ? it->second[k]
                                                               : empty;
  }
  double attr_num(const char* k, double dflt = 0) const {
    const JValue* v = attrs.get(k);
    return v ? v->as_num(dflt) : dflt;
  }
  bool attr_bool(const char* k, bool dflt = false) const {
    const JValue* v = attrs.get(k);
    return v ? v->as_bool(dflt) : dflt;
  }
  std::vector<int64_t> attr_ints(const char* k) const {
    std::vector<int64_t> out;
    const JValue* v = attrs.get(k);
    if (v && v->kind == JValue::ARR)
      for (auto& e : v->arr) out.push_back((int64_t)e.as_num());
    return out;
  }
  std::string attr_str(const char* k) const {
    const JValue* v = attrs.get(k);
    return (v && v->kind == JValue::STR) ? v->str : "";
  }
};

struct Model {
  std::vector<OpDesc> ops;  // block 0 only: inference programs are flat
  std::map<std::string, Tensor> vars;  // persistables + runtime values
  std::vector<std::string> feed_names, fetch_names;
  std::map<std::string, bool> var_is_int;
  // names whose lod was set by the caller (ptpu_infer_set_input_lod):
  // every OTHER var's lod is derived and cleared at each forward so a
  // second run with different offsets cannot read run-1's stale LoD
  std::map<std::string, bool> fed_lod;
  std::string error;
};

static Tensor* named(Model& m, const std::string& name) {
  return name.empty() ? nullptr : &m.vars[name];
}

// Integral tensors carry values in .i with .f empty; kernels whose inner
// loops index x.f directly (layer_norm, lrn, gru, lstm) must reject them
// up front instead of reading out of bounds.
static bool require_float(Model& m, const Tensor& t, const char* op_type,
                          const char* slot) {
  if (t.is_int) {
    m.error = std::string(op_type) + ": integral tensor fed to float slot " +
              slot + " (cast it first)";
    return false;
  }
  return true;
}

static void softmax_lastdim(const Tensor& x, Tensor* y) {
  y->shape = x.shape;
  y->is_int = false;
  int64_t C = x.shape.empty() ? 1 : x.shape.back();
  int64_t R = x.numel() / std::max<int64_t>(C, 1);
  y->f.resize(x.numel());
  for (int64_t r = 0; r < R; ++r) {
    const float* px = &x.f[r * C];
    float* py = &y->f[r * C];
    float mx = px[0];
    for (int64_t c = 1; c < C; ++c) mx = std::max(mx, px[c]);
    float s = 0;
    for (int64_t c = 0; c < C; ++c) {
      py[c] = std::exp(px[c] - mx);
      s += py[c];
    }
    for (int64_t c = 0; c < C; ++c) py[c] /= s;
  }
}

static bool eltwise(Model& m, const OpDesc& op, char kind) {
  Tensor& x = m.vars[op.in("X")];
  Tensor& y = m.vars[op.in("Y")];
  Tensor* o = named(m, op.out("Out"));
  o->shape = x.shape;
  o->is_int = false;
  o->f.resize(x.numel());
  int axis = (int)op.attr_num("axis", -1);
  // broadcast y over x starting at `axis` (reference elementwise broadcast)
  int64_t ny = y.numel(), nx = x.numel();
  if (axis < 0) axis = (int)x.shape.size() - (int)y.shape.size();
  int64_t pre = 1, mid = 1, post = 1;
  for (int k = 0; k < (int)x.shape.size(); ++k) {
    if (k < axis)
      pre *= x.shape[k];
    else if (k < axis + (int)y.shape.size())
      mid *= x.shape[k];
    else
      post *= x.shape[k];
  }
  if (mid != ny) {  // same-shape fast path (or scalar)
    pre = 1;
    mid = ny;
    post = nx / std::max<int64_t>(ny, 1);
    if (mid * post != nx) {
      m.error = "elementwise broadcast mismatch on " + op.in("X");
      return false;
    }
  }
  for (int64_t a = 0; a < pre; ++a)
    for (int64_t b = 0; b < mid; ++b) {
      float yv = y.at(b);
      for (int64_t c = 0; c < post; ++c) {
        int64_t k = (a * mid + b) * post + c;
        float xv = x.at(k);
        switch (kind) {
          case '+': o->f[k] = xv + yv; break;
          case '-': o->f[k] = xv - yv; break;
          case '*': o->f[k] = xv * yv; break;
          case '/': o->f[k] = xv / yv; break;
        }
      }
    }
  return true;
}

static bool conv2d(Model& m, const OpDesc& op) {
  Tensor& x = m.vars[op.in("Input")];
  if (!require_float(m, x, "conv2d", "Input")) return false;
  Tensor& w = m.vars[op.in("Filter")];
  Tensor* o = named(m, op.out("Output"));
  auto strides = op.attr_ints("strides");
  auto pads = op.attr_ints("paddings");
  int64_t g = (int64_t)op.attr_num("groups", 1);
  if (g < 1) g = 1;
  int64_t sh = strides.empty() ? 1 : strides[0];
  int64_t sw = strides.size() > 1 ? strides[1] : sh;
  int64_t ph = pads.empty() ? 0 : pads[0];
  int64_t pw = pads.size() > 1 ? pads[1] : ph;
  int64_t N = x.shape[0], C = x.shape[1], H = x.shape[2], W = x.shape[3];
  int64_t M = w.shape[0], Cg = w.shape[1], KH = w.shape[2], KW = w.shape[3];
  int64_t OH = (H + 2 * ph - KH) / sh + 1, OW = (W + 2 * pw - KW) / sw + 1;
  o->shape = {N, M, OH, OW};
  o->is_int = false;
  o->f.assign(N * M * OH * OW, 0.f);
  int64_t Mg = M / g;
  for (int64_t n = 0; n < N; ++n)
    for (int64_t mo = 0; mo < M; ++mo) {
      int64_t grp = mo / Mg;
      for (int64_t oh = 0; oh < OH; ++oh)
        for (int64_t ow = 0; ow < OW; ++ow) {
          float acc = 0;
          for (int64_t ci = 0; ci < Cg; ++ci) {
            int64_t c = grp * Cg + ci;
            for (int64_t kh = 0; kh < KH; ++kh) {
              int64_t ih = oh * sh - ph + kh;
              if (ih < 0 || ih >= H) continue;
              for (int64_t kw = 0; kw < KW; ++kw) {
                int64_t iw = ow * sw - pw + kw;
                if (iw < 0 || iw >= W) continue;
                acc += x.f[((n * C + c) * H + ih) * W + iw] *
                       w.f[((mo * Cg + ci) * KH + kh) * KW + kw];
              }
            }
          }
          o->f[((n * M + mo) * OH + oh) * OW + ow] = acc;
        }
    }
  return true;
}

static bool pool2d(Model& m, const OpDesc& op) {
  Tensor& x = m.vars[op.in("X")];
  if (!require_float(m, x, "pool2d", "X")) return false;
  Tensor* o = named(m, op.out("Out"));
  auto ksize = op.attr_ints("ksize");
  auto strides = op.attr_ints("strides");
  auto pads = op.attr_ints("paddings");
  bool global = op.attr_bool("global_pooling", false);
  bool is_max = op.attr_str("pooling_type") != "avg";
  int64_t N = x.shape[0], C = x.shape[1], H = x.shape[2], W = x.shape[3];
  int64_t kh = global ? H : (ksize.empty() ? 2 : ksize[0]);
  int64_t kw = global ? W : (ksize.size() > 1 ? ksize[1] : kh);
  int64_t sh = strides.empty() ? kh : strides[0];
  int64_t sw = strides.size() > 1 ? strides[1] : sh;
  int64_t ph = (global || pads.empty()) ? 0 : pads[0];
  int64_t pw = (global || pads.size() < 2) ? ph : pads[1];
  int64_t OH = (H + 2 * ph - kh) / sh + 1, OW = (W + 2 * pw - kw) / sw + 1;
  o->shape = {N, C, OH, OW};
  o->is_int = false;
  o->f.assign(N * C * OH * OW, 0.f);
  for (int64_t n = 0; n < N; ++n)
    for (int64_t c = 0; c < C; ++c)
      for (int64_t oh = 0; oh < OH; ++oh)
        for (int64_t ow = 0; ow < OW; ++ow) {
          float best = is_max ? -3.4e38f : 0.f;
          int64_t cnt = 0;
          for (int64_t i = 0; i < kh; ++i) {
            int64_t ih = oh * sh - ph + i;
            if (ih < 0 || ih >= H) continue;
            for (int64_t j = 0; j < kw; ++j) {
              int64_t iw = ow * sw - pw + j;
              if (iw < 0 || iw >= W) continue;
              float v = x.f[((n * C + c) * H + ih) * W + iw];
              if (is_max)
                best = std::max(best, v);
              else
                best += v;
              ++cnt;
            }
          }
          o->f[((n * C + c) * OH + oh) * OW + ow] =
              is_max ? best : best / std::max<int64_t>(cnt, 1);
        }
  return true;
}

static bool run_op(Model& m, const OpDesc& op) {
  const std::string& t = op.type;
  if (t == "feed" || t == "fetch") return true;
  if (t == "mul") {
    Tensor& x = m.vars[op.in("X")];
    Tensor& y = m.vars[op.in("Y")];
    if (!require_float(m, y, "mul", "Y")) return false;
    Tensor* o = named(m, op.out("Out"));
    int xnc = (int)op.attr_num("x_num_col_dims", 1);
    int ync = (int)op.attr_num("y_num_col_dims", 1);
    int64_t Rx, Cx, Ry, Cy;
    flatten2(x, xnc, &Rx, &Cx);
    flatten2(y, ync, &Ry, &Cy);
    if (Cx != Ry) {
      m.error = "mul shape mismatch";
      return false;
    }
    o->shape.clear();
    for (int k = 0; k < xnc; ++k) o->shape.push_back(x.shape[k]);
    for (size_t k = ync; k < y.shape.size(); ++k) o->shape.push_back(y.shape[k]);
    o->is_int = false;
    o->f.assign(Rx * Cy, 0.f);
    for (int64_t r = 0; r < Rx; ++r)
      for (int64_t k = 0; k < Cx; ++k) {
        float xv = x.at(r * Cx + k);
        if (xv == 0.f) continue;
        const float* py = &y.f[k * Cy];
        float* po = &o->f[r * Cy];
        for (int64_t c = 0; c < Cy; ++c) po[c] += xv * py[c];
      }
    return true;
  }
  if (t == "elementwise_add") return eltwise(m, op, '+');
  if (t == "elementwise_sub") return eltwise(m, op, '-');
  if (t == "elementwise_mul") return eltwise(m, op, '*');
  if (t == "elementwise_div") return eltwise(m, op, '/');
  if (t == "relu" || t == "sigmoid" || t == "tanh" || t == "exp" ||
      t == "sqrt" || t == "abs" || t == "square") {
    Tensor& x = m.vars[op.in("X")];
    Tensor* o = named(m, op.out("Out"));
    o->shape = x.shape;
    o->is_int = false;
    o->f.resize(x.numel());
    for (int64_t k = 0; k < x.numel(); ++k) {
      float v = x.at(k);
      if (t == "relu")
        v = v > 0 ? v : 0;
      else if (t == "sigmoid")
        v = 1.f / (1.f + std::exp(-v));
      else if (t == "tanh")
        v = std::tanh(v);
      else if (t == "exp")
        v = std::exp(v);
      else if (t == "sqrt")
        v = std::sqrt(v);
      else if (t == "abs")
        v = std::fabs(v);
      else
        v = v * v;
      o->f[k] = v;
    }
    return true;
  }
  if (t == "softmax") {
    if (!require_float(m, m.vars[op.in("X")], "softmax", "X")) return false;
    softmax_lastdim(m.vars[op.in("X")], named(m, op.out("Out")));
    return true;
  }
  if (t == "scale") {
    Tensor& x = m.vars[op.in("X")];
    Tensor* o = named(m, op.out("Out"));
    float s = (float)op.attr_num("scale", 1.0);
    float bias = (float)op.attr_num("bias", 0.0);
    o->shape = x.shape;
    o->is_int = false;
    o->f.resize(x.numel());
    for (int64_t k = 0; k < x.numel(); ++k) o->f[k] = x.at(k) * s + bias;
    return true;
  }
  if (t == "mean") {
    Tensor& x = m.vars[op.in("X")];
    Tensor* o = named(m, op.out("Out"));
    double s = 0;
    for (int64_t k = 0; k < x.numel(); ++k) s += x.at(k);
    o->shape = {1};
    o->is_int = false;
    o->f = {(float)(s / std::max<int64_t>(x.numel(), 1))};
    return true;
  }
  if (t == "sum") {
    auto it = op.inputs.find("X");
    Tensor* o = named(m, op.out("Out"));
    const Tensor& first = m.vars[it->second[0]];
    o->shape = first.shape;
    o->is_int = false;
    o->f.assign(first.numel(), 0.f);
    for (auto& nm : it->second) {
      Tensor& x = m.vars[nm];
      for (int64_t k = 0; k < x.numel(); ++k) o->f[k] += x.at(k);
    }
    return true;
  }
  if (t == "reshape" || t == "reshape2") {
    Tensor& x = m.vars[op.in("X")];
    Tensor* o = named(m, op.out("Out"));
    auto shape = op.attr_ints("shape");
    int64_t known = 1, infer = -1;
    for (size_t k = 0; k < shape.size(); ++k) {
      if (shape[k] == -1)
        infer = k;
      else
        known *= shape[k];
    }
    if (infer >= 0) shape[infer] = x.numel() / std::max<int64_t>(known, 1);
    *o = x;
    o->shape = shape;
    return true;
  }
  if (t == "dropout") {
    // inference semantics = downscale by keep probability (reference
    // dropout_op.cc default upscale_in_train=False; matches
    // kernels_nn.py _dropout's is_test branch)
    Tensor& x = m.vars[op.in("X")];
    Tensor* o = named(m, op.out("Out"));
    float keep = 1.f - (float)op.attr_num("dropout_prob", 0.5);
    o->shape = x.shape;
    o->is_int = false;
    o->f.resize(x.numel());
    for (int64_t kq = 0; kq < x.numel(); ++kq) o->f[kq] = x.at(kq) * keep;
    return true;
  }
  if (t == "batch_norm") {
    Tensor& x = m.vars[op.in("X")];
    Tensor& scale = m.vars[op.in("Scale")];
    Tensor& bias = m.vars[op.in("Bias")];
    Tensor& mean = m.vars[op.in("Mean")];
    Tensor& var = m.vars[op.in("Variance")];
    Tensor* o = named(m, op.out("Y"));
    float eps = (float)op.attr_num("epsilon", 1e-5);
    int64_t N = x.shape[0], C = x.shape.size() > 1 ? x.shape[1] : 1;
    int64_t inner = x.numel() / std::max<int64_t>(N * C, 1);
    o->shape = x.shape;
    o->is_int = false;
    o->f.resize(x.numel());
    for (int64_t n = 0; n < N; ++n)
      for (int64_t c = 0; c < C; ++c) {
        float inv = 1.f / std::sqrt(var.f[c] + eps);
        float a = scale.f[c] * inv;
        float b2 = bias.f[c] - mean.f[c] * a;
        for (int64_t k = 0; k < inner; ++k) {
          int64_t idx = (n * C + c) * inner + k;
          o->f[idx] = x.at(idx) * a + b2;
        }
      }
    return true;
  }
  if (t == "conv2d") return conv2d(m, op);
  if (t == "pool2d") return pool2d(m, op);
  if (t == "lrn") {
    // cross-channel local response normalisation (reference lrn_op.cc;
    // matches kernels_nn.py _lrn: window n centred with left pad n/2,
    // out = x * (k + alpha * sum(x^2 over window))^-beta)
    Tensor& x = m.vars[op.in("X")];
    if (!require_float(m, x, "lrn", "X")) return false;
    Tensor* o = named(m, op.out("Out"));
    int64_t n = (int64_t)op.attr_num("n", 5);
    float kk = (float)op.attr_num("k", 2.0);
    float alpha = (float)op.attr_num("alpha", 1e-4);
    float beta = (float)op.attr_num("beta", 0.75);
    int64_t N = x.shape[0], C = x.shape[1];
    int64_t inner = x.numel() / std::max<int64_t>(N * C, 1);
    o->shape = x.shape;
    o->is_int = false;
    o->f.resize(x.numel());
    int64_t half = n / 2;
    for (int64_t b = 0; b < N; ++b)
      for (int64_t c = 0; c < C; ++c) {
        int64_t c0 = std::max<int64_t>(c - half, 0);
        int64_t c1 = std::min<int64_t>(c - half + n, C);
        for (int64_t kx = 0; kx < inner; ++kx) {
          float acc = 0.f;
          for (int64_t cc = c0; cc < c1; ++cc) {
            float v = x.f[(b * C + cc) * inner + kx];
            acc += v * v;
          }
          int64_t idx = (b * C + c) * inner + kx;
          o->f[idx] = x.f[idx] * std::pow(kk + alpha * acc, -beta);
        }
      }
    return true;
  }
  if (t == "lookup_table") {
    Tensor& w = m.vars[op.in("W")];
    Tensor& ids = m.vars[op.in("Ids")];
    Tensor* o = named(m, op.out("Out"));
    int64_t V = w.shape[0], D = w.shape[1], n = ids.numel();
    // mirror the Python kernel's shape rule (kernels_tensor.py
    // _lookup_table): [N,1] ids -> [N,D]; otherwise ids.shape + [D]
    // (multi-field CTR ids [B,F] -> [B,F,D])
    o->shape = ids.shape;
    if (!o->shape.empty() && o->shape.back() == 1) o->shape.pop_back();
    o->shape.push_back(D);
    o->is_int = false;
    o->f.resize(n * D);
    int64_t padding_idx = (int64_t)op.attr_num("padding_idx", -1);
    for (int64_t k = 0; k < n; ++k) {
      int64_t id = ids.is_int ? ids.i[k] : (int64_t)ids.f[k];
      if (id < 0 || id >= V) {  // external feeds are untrusted
        m.error = "lookup_table id out of range: " + std::to_string(id) +
                  " (vocab " + std::to_string(V) + ")";
        return false;
      }
      if (id == padding_idx)  // kernels_tensor.py: padding rows read 0
        memset(&o->f[k * D], 0, D * sizeof(float));
      else
        memcpy(&o->f[k * D], &w.f[id * D], D * sizeof(float));
    }
    return true;
  }
  if (t == "reduce_sum" || t == "reduce_mean" || t == "reduce_max") {
    Tensor& x = m.vars[op.in("X")];
    Tensor* o = named(m, op.out("Out"));
    bool keep = op.attr_bool("keep_dim", false);
    int rank = (int)x.shape.size();
    std::vector<bool> red(rank, false);
    if (op.attr_bool("reduce_all", false)) {
      red.assign(rank, true);
    } else {
      std::vector<int64_t> dims = op.attr_ints("dim");
      if (dims.empty()) dims.push_back((int64_t)op.attr_num("dim", 0));
      for (int64_t d : dims) {
        if (d < 0) d += rank;
        if (d < 0 || d >= rank) {  // model files are untrusted input
          m.error = t + " dim out of range for rank " +
                    std::to_string(rank);
          return false;
        }
        red[d] = true;
      }
    }
    std::vector<int64_t> oshape;
    for (int k = 0; k < rank; ++k) {
      if (!red[k])
        oshape.push_back(x.shape[k]);
      else if (keep)
        oshape.push_back(1);
    }
    if (oshape.empty()) oshape.push_back(1);
    int64_t onum = 1;
    for (int64_t s : oshape) onum *= s;
    bool is_max = (t == "reduce_max");
    o->shape = oshape;
    o->is_int = false;
    o->f.assign(onum, is_max ? -std::numeric_limits<float>::infinity()
                             : 0.f);
    std::vector<int64_t> idx(rank, 0);
    for (int64_t k = 0; k < x.numel(); ++k) {
      int64_t oi = 0;
      for (int q = 0; q < rank; ++q)
        if (!red[q]) oi = oi * x.shape[q] + idx[q];
      if (is_max)
        o->f[oi] = std::max(o->f[oi], x.at(k));
      else
        o->f[oi] += x.at(k);
      for (int q = rank - 1; q >= 0; --q) {
        if (++idx[q] < x.shape[q]) break;
        idx[q] = 0;
      }
    }
    if (t == "reduce_mean") {
      // every output cell reduces the same number of input elements
      int64_t div = std::max<int64_t>(x.numel() / onum, 1);
      for (int64_t k = 0; k < onum; ++k) o->f[k] /= div;
    }
    return true;
  }
  if (t == "concat") {
    auto it = op.inputs.find("X");
    Tensor* o = named(m, op.out("Out"));
    int axis = (int)op.attr_num("axis", 0);
    const Tensor& first = m.vars[it->second[0]];
    if (axis < 0) axis += (int)first.shape.size();
    int64_t outer = 1, cat = 0, inner = 1;
    for (int k = 0; k < axis; ++k) outer *= first.shape[k];
    // explicit trailing product: numel()-based division breaks when the
    // first operand has 0 rows (an empty KV cache on decode step 0)
    for (size_t k = axis + 1; k < first.shape.size(); ++k)
      inner *= first.shape[k];
    for (auto& nm : it->second) cat += m.vars[nm].shape[axis];
    o->shape = first.shape;
    o->shape[axis] = cat;
    o->is_int = false;
    o->f.resize(outer * cat * inner);
    int64_t off = 0;
    for (auto& nm : it->second) {
      Tensor& x = m.vars[nm];
      int64_t xc = x.shape[axis];
      for (int64_t a = 0; a < outer; ++a)
        for (int64_t b = 0; b < xc; ++b)
          for (int64_t c = 0; c < inner; ++c)
            o->f[(a * cat + off + b) * inner + c] = x.at((a * xc + b) * inner + c);
      off += xc;
    }
    return true;
  }
  if (t == "im2sequence") {
    // reference operators/im2sequence_op.cc: sliding blocks -> rows in
    // (c, kh, kw) order, one sequence of oh*ow steps per image (matches
    // kernels_tensor.py _im2sequence / conv_general_dilated_patches)
    Tensor& x = m.vars[op.in("X")];
    if (!require_float(m, x, "im2sequence", "X")) return false;
    Tensor* o = named(m, op.out("Out"));
    auto ks = op.attr_ints("kernels");
    auto st = op.attr_ints("strides");
    auto pd = op.attr_ints("paddings");
    int64_t kh = ks.empty() ? 1 : ks[0], kw = ks.size() > 1 ? ks[1] : kh;
    int64_t sh = st.empty() ? 1 : st[0], sw = st.size() > 1 ? st[1] : sh;
    int64_t pu = pd.size() > 0 ? pd[0] : 0, pl = pd.size() > 1 ? pd[1] : 0;
    int64_t pb = pd.size() > 2 ? pd[2] : pu, pr = pd.size() > 3 ? pd[3] : pl;
    int64_t N = x.shape[0], C = x.shape[1], H = x.shape[2], W = x.shape[3];
    int64_t PH = H + pu + pb, PW = W + pl + pr;
    int64_t OH = (PH - kh) / sh + 1, OW = (PW - kw) / sw + 1;
    int64_t D = C * kh * kw;
    o->shape = {N * OH * OW, D};
    o->is_int = false;
    o->f.assign(N * OH * OW * D, 0.f);
    for (int64_t n = 0; n < N; ++n)
      for (int64_t oh = 0; oh < OH; ++oh)
        for (int64_t ow = 0; ow < OW; ++ow) {
          float* row = &o->f[((n * OH + oh) * OW + ow) * D];
          for (int64_t c = 0; c < C; ++c)
            for (int64_t a = 0; a < kh; ++a) {
              int64_t ih = oh * sh + a - pu;
              if (ih < 0 || ih >= H) continue;
              for (int64_t b2 = 0; b2 < kw; ++b2) {
                int64_t iw = ow * sw + b2 - pl;
                if (iw < 0 || iw >= W) continue;
                row[(c * kh + a) * kw + b2] =
                    x.f[((n * C + c) * H + ih) * W + iw];
              }
            }
        }
    o->lod.clear();
    for (int64_t n = 0; n <= N; ++n) o->lod.push_back(n * OH * OW);
    return true;
  }
  if (t == "gru") {
    // full-sequence GRU over a packed ragged batch (reference gru_op;
    // same math as kernels_rnn.py _gru: w[:, :H]=update, [H:2H]=reset,
    // [2H:]=candidate; x already holds the 3H input projection)
    Tensor& x = m.vars[op.in("Input")];
    if (!require_float(m, x, "gru", "Input")) return false;
    Tensor& w = m.vars[op.in("Weight")];
    Tensor* bias = op.in("Bias").empty() ? nullptr : &m.vars[op.in("Bias")];
    Tensor* h0 = op.in("H0").empty() ? nullptr : &m.vars[op.in("H0")];
    Tensor* o = named(m, op.out("Hidden"));
    if (x.lod.empty()) {
      m.error = "gru input has no sequence offsets (lod)";
      return false;
    }
    {
      // only the default activations are compiled in; a model asking
      // for others must fail loudly, not diverge silently
      std::string ga = op.attr_str("gate_activation");
      std::string ca = op.attr_str("activation");
      if ((!ga.empty() && ga != "sigmoid") || (!ca.empty() && ca != "tanh")) {
        m.error = "native gru supports gate_activation=sigmoid / "
                  "activation=tanh only (got " + ga + "/" + ca + ")";
        return false;
      }
    }
    bool reverse = op.attr_bool("is_reverse", false);
    int64_t Hd = w.shape[0];
    int64_t total = x.shape[0];
    o->shape = {total, Hd};
    o->is_int = false;
    o->f.assign(total * Hd, 0.f);
    o->lod = x.lod;
    std::vector<float> h(Hd), hn(Hd), g(3 * Hd);
    for (size_t s = 0; s + 1 < x.lod.size(); ++s) {
      int64_t b0 = x.lod[s], b1 = x.lod[s + 1];
      if (h0)
        memcpy(h.data(), &h0->f[s * Hd], Hd * sizeof(float));
      else
        std::fill(h.begin(), h.end(), 0.f);
      for (int64_t q = 0; q < b1 - b0; ++q) {
        int64_t row = reverse ? (b1 - 1 - q) : (b0 + q);
        const float* xr = &x.f[row * 3 * Hd];
        for (int64_t k = 0; k < 3 * Hd; ++k)
          g[k] = xr[k] + (bias ? bias->f[k] : 0.f);
        // g += h @ w for the update|reset halves
        for (int64_t r = 0; r < Hd; ++r) {
          float hv = h[r];
          if (hv == 0.f) continue;
          const float* wr = &w.f[r * 3 * Hd];
          for (int64_t c = 0; c < 2 * Hd; ++c) g[c] += hv * wr[c];
        }
        for (int64_t k = 0; k < 2 * Hd; ++k)
          g[k] = 1.f / (1.f + std::exp(-g[k]));
        // candidate: xc + (r*h) @ w_c
        for (int64_t r = 0; r < Hd; ++r) {
          float rh = g[Hd + r] * h[r];
          if (rh == 0.f) continue;
          const float* wr = &w.f[r * 3 * Hd];
          for (int64_t c = 0; c < Hd; ++c) g[2 * Hd + c] += rh * wr[2 * Hd + c];
        }
        for (int64_t k = 0; k < Hd; ++k) {
          float u = g[k], c = std::tanh(g[2 * Hd + k]);
          hn[k] = (1.f - u) * h[k] + u * c;
        }
        h = hn;
        memcpy(&o->f[row * Hd], h.data(), Hd * sizeof(float));
      }
    }
    return true;
  }
  if (t == "lstm") {
    // full-sequence LSTM over a packed ragged batch (reference lstm_op;
    // same math as kernels_rnn.py _lstm: gate order i,f,c,o in the 4H
    // axis; optional peephole weights ride in bias[4H:7H])
    Tensor& x = m.vars[op.in("Input")];
    if (!require_float(m, x, "lstm", "Input")) return false;
    Tensor& w = m.vars[op.in("Weight")];
    Tensor* bias = op.in("Bias").empty() ? nullptr : &m.vars[op.in("Bias")];
    Tensor* h0 = op.in("H0").empty() ? nullptr : &m.vars[op.in("H0")];
    Tensor* c0 = op.in("C0").empty() ? nullptr : &m.vars[op.in("C0")];
    Tensor* o = named(m, op.out("Hidden"));
    Tensor* oc = op.out("Cell").empty() ? nullptr : named(m, op.out("Cell"));
    if (x.lod.empty()) {
      m.error = "lstm input has no sequence offsets (lod)";
      return false;
    }
    {
      std::string ga = op.attr_str("gate_activation");
      std::string ca = op.attr_str("cell_activation");
      std::string da = op.attr_str("candidate_activation");
      if ((!ga.empty() && ga != "sigmoid") || (!ca.empty() && ca != "tanh") ||
          (!da.empty() && da != "tanh")) {
        m.error = "native lstm supports sigmoid/tanh activations only";
        return false;
      }
    }
    bool reverse = op.attr_bool("is_reverse", false);
    bool peephole = op.attr_bool("use_peepholes", true) && bias &&
                    bias->numel() >= 7 * w.shape[0];
    int64_t Hd = w.shape[0];
    int64_t total = x.shape[0];
    o->shape = {total, Hd};
    o->is_int = false;
    o->f.assign(total * Hd, 0.f);
    o->lod = x.lod;
    if (oc) {
      oc->shape = o->shape;
      oc->is_int = false;
      oc->f.assign(total * Hd, 0.f);
      oc->lod = x.lod;
    }
    std::vector<float> h(Hd), c(Hd), g(4 * Hd);
    auto sig = [](float v) { return 1.f / (1.f + std::exp(-v)); };
    for (size_t s = 0; s + 1 < x.lod.size(); ++s) {
      int64_t b0 = x.lod[s], b1 = x.lod[s + 1];
      if (h0)
        memcpy(h.data(), &h0->f[s * Hd], Hd * sizeof(float));
      else
        std::fill(h.begin(), h.end(), 0.f);
      if (c0)
        memcpy(c.data(), &c0->f[s * Hd], Hd * sizeof(float));
      else
        std::fill(c.begin(), c.end(), 0.f);
      for (int64_t q = 0; q < b1 - b0; ++q) {
        int64_t row = reverse ? (b1 - 1 - q) : (b0 + q);
        const float* xr = &x.f[row * 4 * Hd];
        for (int64_t k = 0; k < 4 * Hd; ++k)
          g[k] = xr[k] + (bias ? bias->f[k] : 0.f);
        for (int64_t r = 0; r < Hd; ++r) {
          float hv = h[r];
          if (hv == 0.f) continue;
          const float* wr = &w.f[r * 4 * Hd];
          for (int64_t k = 0; k < 4 * Hd; ++k) g[k] += hv * wr[k];
        }
        for (int64_t k = 0; k < Hd; ++k) {
          float gi = g[k], gf = g[Hd + k];
          if (peephole) {
            gi += c[k] * bias->f[4 * Hd + k];
            gf += c[k] * bias->f[5 * Hd + k];
          }
          float i = sig(gi), f2 = sig(gf);
          float cn = f2 * c[k] + i * std::tanh(g[2 * Hd + k]);
          float go = g[3 * Hd + k];
          if (peephole) go += cn * bias->f[6 * Hd + k];
          c[k] = cn;
          h[k] = sig(go) * std::tanh(cn);
        }
        memcpy(&o->f[row * Hd], h.data(), Hd * sizeof(float));
        if (oc) memcpy(&oc->f[row * Hd], c.data(), Hd * sizeof(float));
      }
    }
    return true;
  }
  if (t == "sequence_pool") {
    // per-sequence reduction (reference sequence_pool_op.cc); LAST and
    // FIRST are how sequence_last_step/sequence_first_step lower
    Tensor& x = m.vars[op.in("X")];
    if (!require_float(m, x, "sequence_pool", "X")) return false;
    Tensor* o = named(m, op.out("Out"));
    if (x.lod.empty()) {
      m.error = "sequence_pool input has no sequence offsets (lod)";
      return false;
    }
    std::string pt = op.attr_str("pooltype");
    if (pt.empty()) pt = op.attr_str("pool_type");
    if (pt.empty()) pt = "average";  // reference default
    for (auto& ch : pt) ch = std::tolower(ch);
    if (pt != "last" && pt != "first" && pt != "max" && pt != "sum" &&
        pt != "sqrt" && pt != "average" && pt != "avg" && pt != "mean") {
      m.error = "sequence_pool: unknown pooltype " + pt;
      return false;
    }
    int64_t n = (int64_t)x.lod.size() - 1;
    int64_t D = x.numel() / std::max<int64_t>(x.shape[0], 1);
    o->shape = {n, D};
    o->is_int = false;
    o->f.assign(n * D, 0.f);
    for (int64_t s = 0; s < n; ++s) {
      int64_t b0 = x.lod[s], b1 = x.lod[s + 1];
      if (b1 <= b0) continue;  // empty sequence pools to zeros
      if (pt == "last" || pt == "first") {
        int64_t row = (pt == "last") ? b1 - 1 : b0;
        memcpy(&o->f[s * D], &x.f[row * D], D * sizeof(float));
        continue;
      }
      for (int64_t d = 0; d < D; ++d) {
        float acc = (pt == "max") ? -3.4e38f : 0.f;
        for (int64_t r = b0; r < b1; ++r) {
          float v = x.f[r * D + d];
          if (pt == "max")
            acc = std::max(acc, v);
          else
            acc += v;
        }
        if (pt == "average" || pt == "avg" || pt == "mean")
          acc /= (float)(b1 - b0);
        else if (pt == "sqrt")
          acc /= std::sqrt((float)(b1 - b0));
        o->f[s * D + d] = acc;
      }
    }
    return true;
  }
  if (t == "ctc_align") {
    // CTC greedy decode (reference ctc_align_op.cc): per-step argmax,
    // collapse repeats, drop blanks. Output: packed kept tokens with
    // per-sequence lod (exact ragged — no padding needed host-side).
    Tensor& x = m.vars[op.in("Input")];
    Tensor* o = named(m, op.out("Output"));
    if (x.lod.empty()) {
      m.error = "ctc_align input has no sequence offsets (lod)";
      return false;
    }
    int64_t blank = (int64_t)op.attr_num("blank", 0);
    int64_t C = x.shape.size() > 1 ? x.shape.back() : 1;
    o->is_int = true;
    o->i.clear();
    o->lod.assign(1, 0);
    for (size_t s = 0; s + 1 < x.lod.size(); ++s) {
      int64_t prev = -1;
      for (int64_t r = x.lod[s]; r < x.lod[s + 1]; ++r) {
        int64_t tok = 0;
        if (C > 1) {
          // at() reads the int or float payload uniformly
          for (int64_t c = 1; c < C; ++c)
            if (x.at(r * C + c) > x.at(r * C + tok)) tok = c;
        } else {
          tok = x.is_int ? x.i[r] : (int64_t)x.f[r];
        }
        if (tok != blank && tok != prev) o->i.push_back(tok);
        prev = tok;
      }
      o->lod.push_back((int64_t)o->i.size());
    }
    o->shape = {(int64_t)o->i.size(), 1};
    return true;
  }
  if (t == "matmul") {
    // 2-D (optionally transposed) matmul — the attention building block
    // (reference matmul_op.cc; batched ranks collapse to 2-D here
    // because the serving decoder runs one sequence at a time)
    Tensor& x = m.vars[op.in("X")];
    Tensor& y = m.vars[op.in("Y")];
    Tensor* o = named(m, op.out("Out"));
    bool tx = op.attr_bool("transpose_X", false) ||
              op.attr_bool("transpose_x", false);
    bool ty = op.attr_bool("transpose_Y", false) ||
              op.attr_bool("transpose_y", false);
    if (x.shape.size() != 2 || y.shape.size() != 2) {
      m.error = "native matmul supports rank-2 operands";
      return false;
    }
    int64_t xr = x.shape[0], xc = x.shape[1];
    int64_t yr = y.shape[0], yc = y.shape[1];
    int64_t Mr = tx ? xc : xr, K = tx ? xr : xc;
    int64_t K2 = ty ? yc : yr, Nc = ty ? yr : yc;
    if (K != K2) {
      m.error = "matmul inner-dim mismatch";
      return false;
    }
    o->shape = {Mr, Nc};
    o->is_int = false;
    o->f.assign(Mr * Nc, 0.f);
    for (int64_t r = 0; r < Mr; ++r)
      for (int64_t k = 0; k < K; ++k) {
        float xv = tx ? x.at(k * xc + r) : x.at(r * xc + k);
        if (xv == 0.f) continue;
        for (int64_t c = 0; c < Nc; ++c) {
          float yv = ty ? y.at(c * yc + k) : y.at(k * yc + c);
          o->f[r * Nc + c] += xv * yv;
        }
      }
    return true;
  }
  if (t == "layer_norm") {
    // normalise over trailing dims from begin_norm_axis (reference
    // layer_norm_op.cc), with optional per-feature scale/bias
    Tensor& x = m.vars[op.in("X")];
    if (!require_float(m, x, "layer_norm", "X")) return false;
    Tensor* scale = op.in("Scale").empty() ? nullptr : &m.vars[op.in("Scale")];
    Tensor* bias = op.in("Bias").empty() ? nullptr : &m.vars[op.in("Bias")];
    Tensor* o = named(m, op.out("Y"));
    float eps = (float)op.attr_num("epsilon", 1e-5);
    int bna = (int)op.attr_num("begin_norm_axis", 1);
    int64_t R = 1, C = 1;
    for (size_t k = 0; k < x.shape.size(); ++k)
      ((int)k < bna ? R : C) *= x.shape[k];
    o->shape = x.shape;
    o->is_int = false;
    o->f.resize(x.numel());
    for (int64_t r = 0; r < R; ++r) {
      const float* px = &x.f[r * C];
      float* po = &o->f[r * C];
      double mu = 0;
      for (int64_t c = 0; c < C; ++c) mu += px[c];
      mu /= C;
      double var = 0;
      for (int64_t c = 0; c < C; ++c) var += (px[c] - mu) * (px[c] - mu);
      var /= C;
      float inv = 1.f / std::sqrt((float)var + eps);
      for (int64_t c = 0; c < C; ++c) {
        float v = (px[c] - (float)mu) * inv;
        if (scale) v *= scale->f[c];
        if (bias) v += bias->f[c];
        po[c] = v;
      }
    }
    return true;
  }
  if (t == "top_k") {
    Tensor& x = m.vars[op.in("X")];
    Tensor* vo = named(m, op.out("Out"));
    Tensor* io = named(m, op.out("Indices"));
    int64_t k = (int64_t)op.attr_num("k", 1);
    int64_t C = x.shape.back(), R = x.numel() / C;
    if (k > C) k = C;
    if (k < 1) k = 1;
    vo->shape = {R, k};
    vo->is_int = false;
    vo->f.resize(R * k);
    io->shape = {R, k};
    io->is_int = true;
    io->i.resize(R * k);
    std::vector<int64_t> idx(C);
    for (int64_t r = 0; r < R; ++r) {
      for (int64_t c = 0; c < C; ++c) idx[c] = c;
      std::partial_sort(idx.begin(), idx.begin() + k, idx.end(),
                        [&](int64_t a, int64_t b) {
                          return x.at(r * C + a) > x.at(r * C + b);
                        });
      for (int64_t j = 0; j < k; ++j) {
        vo->f[r * k + j] = x.at(r * C + idx[j]);
        io->i[r * k + j] = idx[j];
      }
    }
    return true;
  }
  if (t == "cast") {
    Tensor& x = m.vars[op.in("X")];
    Tensor* o = named(m, op.out("Out"));
    *o = x;  // numeric value carries; dtype tags only matter at fetch
    return true;
  }
  if (t == "fill_constant") {
    Tensor* o = named(m, op.out("Out"));
    o->shape = op.attr_ints("shape");
    o->is_int = false;
    o->f.assign(o->numel(), (float)op.attr_num("value", 0));
    return true;
  }
  m.error = "unsupported op in native inference: " + t;
  return false;
}

}  // namespace

// ---------------------------------------------------------------------
// C ABI (capi/gradient_machine.h parity)
// ---------------------------------------------------------------------
extern "C" {

void* ptpu_infer_create(const char* dirname) {
  auto m = std::make_unique<Model>();
  std::ifstream fs(std::string(dirname) + "/__model__");
  if (!fs) return nullptr;
  std::stringstream ss;
  ss << fs.rdbuf();
  const std::string text = ss.str();  // JParser keeps pointers into this
  JParser jp(text);
  JValue root = jp.parse();
  if (!jp.ok || root.kind != JValue::OBJ) return nullptr;

  const JValue* meta = root.get("meta");
  if (meta) {
    if (const JValue* f = meta->get("feed_names"))
      for (auto& e : f->arr) m->feed_names.push_back(e.str);
    if (const JValue* f = meta->get("fetch_names"))
      for (auto& e : f->arr) m->fetch_names.push_back(e.str);
  }
  const JValue* blocks = root.get("blocks");
  if (!blocks || blocks->arr.empty()) return nullptr;
  const JValue& b0 = blocks->arr[0];
  if (const JValue* vars = b0.get("vars")) {
    for (auto& v : vars->arr) {
      const JValue* nm = v.get("name");
      if (!nm) continue;
      if (const JValue* dt = v.get("dtype"))
        m->var_is_int[nm->str] = dt->str.find("int") != std::string::npos;
      if (v.get("persistable") && v.get("persistable")->as_bool()) {
        Tensor t;
        if (load_npy(std::string(dirname) + "/" + escape_name(nm->str) + ".npy",
                     &t))
          m->vars[nm->str] = std::move(t);
      }
    }
  }
  if (const JValue* ops = b0.get("ops")) {
    for (auto& o : ops->arr) {
      OpDesc od;
      od.type = o.get("type")->str;
      if (const JValue* ins = o.get("inputs"))
        for (auto& kv : ins->obj) {
          std::vector<std::string> names;
          for (auto& e : kv.second.arr) names.push_back(e.str);
          od.inputs[kv.first] = names;
        }
      if (const JValue* outs = o.get("outputs"))
        for (auto& kv : outs->obj) {
          std::vector<std::string> names;
          for (auto& e : kv.second.arr) names.push_back(e.str);
          od.outputs[kv.first] = names;
        }
      if (const JValue* at = o.get("attrs")) od.attrs = *at;
      m->ops.push_back(std::move(od));
    }
  }
  return m.release();
}

int ptpu_infer_num_feeds(void* h) {
  return (int)static_cast<Model*>(h)->feed_names.size();
}
const char* ptpu_infer_feed_name(void* h, int k) {
  return static_cast<Model*>(h)->feed_names[k].c_str();
}
int ptpu_infer_num_fetch(void* h) {
  return (int)static_cast<Model*>(h)->fetch_names.size();
}
const char* ptpu_infer_fetch_name(void* h, int k) {
  return static_cast<Model*>(h)->fetch_names[k].c_str();
}

// dtype codes: 0 = f32, 1 = i64
int ptpu_infer_set_input(void* h, const char* name, const void* data,
                         int dtype, const int64_t* shape, int ndim) {
  Model& m = *static_cast<Model*>(h);
  Tensor t;
  t.shape.assign(shape, shape + ndim);
  int64_t n = t.numel();
  if (dtype == 1) {
    t.is_int = true;
    t.i.assign(static_cast<const int64_t*>(data),
               static_cast<const int64_t*>(data) + n);
  } else {
    t.is_int = false;
    t.f.assign(static_cast<const float*>(data),
               static_cast<const float*>(data) + n);
  }
  m.vars[name] = std::move(t);
  m.fed_lod.erase(name);  // fresh tensor: any lod must be re-set
  return 0;
}

int ptpu_infer_forward(void* h) {
  Model& m = *static_cast<Model*>(h);
  m.error.clear();
  for (auto& kv : m.vars)
    if (!m.fed_lod.count(kv.first)) kv.second.lod.clear();
  // default LoD propagation (reference ShareLoD; Python _share_lod):
  // restricted to an allowlist of row-preserving op types, mirroring
  // the Python side's barrier logic — a shape-match heuristic alone
  // can hand a reshape/elementwise output a coincidental lod. Sequence
  // ops (im2sequence/gru/lstm/ctc_align/sequence_pool) set or clear
  // their own lod explicitly and are NOT listed.
  static const std::set<std::string> kLodPropagate = {
      "mul",         "matmul",        "elementwise_add", "elementwise_sub",
      "elementwise_mul", "elementwise_div", "relu",      "sigmoid",
      "tanh",        "exp",           "sqrt",            "abs",
      "square",      "softmax",       "scale",           "sum",
      "dropout",     "batch_norm",    "layer_norm",      "lookup_table",
      "cast",        "concat"};
  // reduces over FEATURE axes only stay row-wise (Python _share_lod:
  // dim excludes 0, no reduce_all, no negative dims)
  auto reduce_propagates = [](const OpDesc& op) {
    if (op.attr_bool("reduce_all", false)) return false;
    std::vector<int64_t> dims = op.attr_ints("dim");
    if (dims.empty()) dims.push_back((int64_t)op.attr_num("dim", 0));
    for (int64_t d : dims)
      if (d <= 0) return false;  // row axis (or negative: conservative)
    return true;
  };
  for (auto& op : m.ops) {
    if (!run_op(m, op)) return -1;
    bool is_reduce = op.type == "reduce_sum" || op.type == "reduce_mean" ||
                     op.type == "reduce_max";
    if (is_reduce ? !reduce_propagates(op) : !kLodPropagate.count(op.type))
      continue;
    // pick the ragged source positionally: prefer the canonical data
    // slot ("X" / "Input") over std::map iteration order so e.g.
    // elementwise(X=ragged, Y=broadcast) never inherits from Y.
    const Tensor* src = nullptr;
    for (const char* slot : {"X", "Input", "Ids"}) {
      auto sit = op.inputs.find(slot);
      if (sit == op.inputs.end() || sit->second.empty()) continue;
      auto it = m.vars.find(sit->second[0]);
      if (it != m.vars.end() && !it->second.lod.empty()) {
        src = &it->second;
        break;
      }
    }
    if (!src)
      for (auto& kv : op.inputs) {
        for (auto& nm : kv.second) {
          auto it = m.vars.find(nm);
          if (it != m.vars.end() && !it->second.lod.empty()) {
            src = &it->second;
            break;
          }
        }
        if (src) break;
      }
    if (src)
      for (auto& kv : op.outputs)
        for (auto& nm : kv.second) {
          auto it = m.vars.find(nm);
          if (it != m.vars.end() && it->second.lod.empty() &&
              !it->second.shape.empty() &&
              it->second.shape[0] == src->shape[0])
            it->second.lod = src->lod;
        }
  }
  return 0;
}

const char* ptpu_infer_error(void* h) {
  return static_cast<Model*>(h)->error.c_str();
}

int ptpu_infer_out_rank(void* h, int k) {
  Model& m = *static_cast<Model*>(h);
  return (int)m.vars[m.fetch_names[k]].shape.size();
}
const int64_t* ptpu_infer_out_shape(void* h, int k) {
  Model& m = *static_cast<Model*>(h);
  return m.vars[m.fetch_names[k]].shape.data();
}
// always materialised as f32 for the caller (indices cast)
const float* ptpu_infer_out_data(void* h, int k) {
  Model& m = *static_cast<Model*>(h);
  Tensor& t = m.vars[m.fetch_names[k]];
  if (t.is_int) {
    t.f.assign(t.i.begin(), t.i.end());
    t.is_int = false;
  }
  return t.f.data();
}

void ptpu_infer_destroy(void* h) { delete static_cast<Model*>(h); }

// ragged outputs (CTC decode, RNN sequences): per-sequence start
// offsets of fetch k — length 0 means the output is dense
int ptpu_infer_out_lod_len(void* h, int k) {
  Model& m = *static_cast<Model*>(h);
  return (int)m.vars[m.fetch_names[k]].lod.size();
}
const int64_t* ptpu_infer_out_lod(void* h, int k) {
  Model& m = *static_cast<Model*>(h);
  return m.vars[m.fetch_names[k]].lod.data();
}

// feed a ragged input: offsets for a previously-set input tensor
int ptpu_infer_set_input_lod(void* h, const char* name, const int64_t* lod,
                             int len) {
  Model& m = *static_cast<Model*>(h);
  auto it = m.vars.find(name);
  if (it == m.vars.end()) return -1;
  it->second.lod.assign(lod, lod + len);
  m.fed_lod[name] = true;
  return 0;
}

}  // extern "C"
