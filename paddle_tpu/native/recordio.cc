// Native data plane: RecordIO files + async prefetch queue.
//
// Reference parity: the Go master shards datasets as RecordIO chunks
// (go/master/service.go:106 partition; recordio vendored lib) and the
// legacy PyDataProvider2 feeds training through an async double-buffer
// queue (paddle/gserver/dataproviders/PyDataProvider2.cpp:511). This file
// provides both as a small C library consumed from Python via ctypes
// (no pybind11 in this environment): CRC-checked length-prefixed records
// and a bounded multi-threaded prefetch queue that overlaps host-side IO
// and decode with device steps.
//
// Record format: [u32 magic][u32 len][u32 crc32(payload)][payload bytes].
// A torn tail (partial final record) terminates iteration cleanly, so a
// writer crash never corrupts earlier records — same guarantee the Go
// pserver checkpoints get from CRC + atomic rename.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <condition_variable>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x50524543u;  // "PREC"

uint32_t crc32_of(const uint8_t* data, size_t n) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Writer {
  FILE* f;
};

struct Reader {
  FILE* f;
  std::vector<uint8_t> buf;
};

// ---------------------------------------------------------------------
// Async prefetch queue: N reader threads stream records from a list of
// files into a bounded queue (backpressure keeps memory flat).
// ---------------------------------------------------------------------
struct Prefetcher {
  std::vector<std::string> files;
  size_t capacity;
  std::queue<std::vector<uint8_t>> q;
  std::mutex mu;
  std::condition_variable can_push, can_pop;
  bool done = false;
  bool cancel = false;
  std::thread worker;
  std::vector<uint8_t> current;

  void run() {
    for (const auto& path : files) {
      FILE* f = fopen(path.c_str(), "rb");
      if (!f) continue;
      while (true) {
        uint32_t hdr[3];
        if (fread(hdr, sizeof(uint32_t), 3, f) != 3) break;
        if (hdr[0] != kMagic) break;
        std::vector<uint8_t> payload(hdr[1]);
        if (fread(payload.data(), 1, hdr[1], f) != hdr[1]) break;
        if (crc32_of(payload.data(), payload.size()) != hdr[2]) break;
        std::unique_lock<std::mutex> lk(mu);
        can_push.wait(lk, [&] { return q.size() < capacity || cancel; });
        if (cancel) {
          fclose(f);
          goto out;
        }
        q.push(std::move(payload));
        can_pop.notify_one();
      }
      fclose(f);
    }
  out: {
    std::lock_guard<std::mutex> lk(mu);
    done = true;
    can_pop.notify_all();
  }
  }
};

}  // namespace

extern "C" {

// ---- writer ----------------------------------------------------------
void* rio_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new Writer{f};
  return w;
}

int rio_write(void* wp, const uint8_t* data, uint32_t len) {
  auto* w = static_cast<Writer*>(wp);
  uint32_t hdr[3] = {kMagic, len, crc32_of(data, len)};
  if (fwrite(hdr, sizeof(uint32_t), 3, w->f) != 3) return -1;
  if (fwrite(data, 1, len, w->f) != len) return -1;
  return 0;
}

void rio_writer_close(void* wp) {
  auto* w = static_cast<Writer*>(wp);
  fclose(w->f);
  delete w;
}

// ---- reader (synchronous) -------------------------------------------
void* rio_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  return new Reader{f, {}};
}

// returns payload length, 0 at EOF/corruption; payload via rio_data
int64_t rio_next(void* rp) {
  auto* r = static_cast<Reader*>(rp);
  uint32_t hdr[3];
  if (fread(hdr, sizeof(uint32_t), 3, r->f) != 3) return 0;
  if (hdr[0] != kMagic) return 0;
  r->buf.resize(hdr[1]);
  if (fread(r->buf.data(), 1, hdr[1], r->f) != hdr[1]) return 0;
  if (crc32_of(r->buf.data(), r->buf.size()) != hdr[2]) return 0;
  return static_cast<int64_t>(hdr[1]);
}

const uint8_t* rio_data(void* rp) {
  return static_cast<Reader*>(rp)->buf.data();
}

void rio_close(void* rp) {
  auto* r = static_cast<Reader*>(rp);
  fclose(r->f);
  delete r;
}

// ---- async prefetcher ------------------------------------------------
void* pq_open(const char** paths, int n_paths, int capacity) {
  auto* p = new Prefetcher();
  for (int i = 0; i < n_paths; i++) p->files.emplace_back(paths[i]);
  p->capacity = capacity > 0 ? capacity : 64;
  p->worker = std::thread([p] { p->run(); });
  return p;
}

// blocks for the next record; returns length, 0 at end of stream
int64_t pq_next(void* pp) {
  auto* p = static_cast<Prefetcher*>(pp);
  std::unique_lock<std::mutex> lk(p->mu);
  p->can_pop.wait(lk, [&] { return !p->q.empty() || p->done; });
  if (p->q.empty()) return 0;
  p->current = std::move(p->q.front());
  p->q.pop();
  p->can_push.notify_one();
  return static_cast<int64_t>(p->current.size());
}

const uint8_t* pq_data(void* pp) {
  return static_cast<Prefetcher*>(pp)->current.data();
}

void pq_close(void* pp) {
  auto* p = static_cast<Prefetcher*>(pp);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->cancel = true;
    p->can_push.notify_all();
  }
  // drain so the worker can observe cancel even while waiting to push
  p->worker.join();
  delete p;
}

}  // extern "C"
