"""paddle_tpu: a TPU-native deep-learning framework with the capabilities
of 2018-era PaddlePaddle (reference at /root/reference, blueprint in
SURVEY.md).

Layout:
  fluid/     Fluid-compatible frontend: Program IR, layers, optimizers,
             executor that lowers whole blocks to fused XLA computations
  parallel/  device mesh, data/tensor parallel training over ICI (pjit)
  serving/   continuous-batching inference engine (slotted KV cache,
             bucketed prefill, one compiled decode step)
  data/      input pipeline: chunked CRC-checked shards, prefetching
             DataLoader with exact mid-epoch resume, coordinator-leased
             elastic sharding
  models/    reference model zoo (LeNet, ResNet, VGG, RNNs, ...)
  reader/    composable data readers (v2 reader decorator parity)
  ops/       pallas kernels for ops XLA cannot express well
  utils/     flags, logging, timers (N12 parity)
"""

__version__ = "0.1.0"

from . import fluid  # noqa: F401
from . import utils  # noqa: F401
from . import v2  # noqa: F401
